"""SLO verdicts: a declarative spec per query class, judged from the
existing obs planes.

The alert plane (obs/alerts) watches the process continuously; this
module answers a different question — **did one bounded run of
production-shaped traffic hold its SLOs?** A :class:`SloSpec` names
query classes (each a set of SQL shapes, joined to the PR-4 stats
table by fingerprint) with per-class targets (p50/p99 latency ceilings
estimated from the ``QueryStats`` histograms via
``obs.stats.estimate_quantile``, a minimum success rate) plus run-wide
policy (no alert left *firing*, error-budget burn within
``slo_max_burn`` of the ``alert_slo_error_rate`` budget). Nothing here
re-times queries: every signal is read from the stats/alerts planes
the serving path already feeds.

Evaluation is **windowed**: :meth:`SloEngine.begin` snapshots the
relevant fingerprints' histograms, :meth:`SloEngine.finish` differences
against them — so one run is judged on ITS traffic, not the process's
cumulative history. The result is one machine-readable report
(``verdict`` pass/fail with every failure naming its rule and key),
served by ``GET /slo``, console ``SLO``, and persisted by bench.py as
``BENCH_SLO_r{N}.json``.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Iterable, List, Optional

from orientdb_tpu.obs.stats import (
    QUANTILE_FIELDS,
    estimate_quantile,
    fingerprint_cached,
    stats,
)
from orientdb_tpu.obs.trace import span
from orientdb_tpu.utils.config import config
from orientdb_tpu.utils.logging import get_logger
from orientdb_tpu.utils.metrics import metrics

log = get_logger("slo")

#: verdict failure rules — the vocabulary every failure entry's
#: ``rule`` field draws from (the report's operator-facing index;
#: README "Traffic simulator & SLO verdicts" documents each)
FAILURE_RULES: Dict[str, str] = {
    "p50_latency": "a class's windowed p50 exceeds its p50_ms target",
    "p99_latency": "a class's windowed p99 exceeds its p99_ms target",
    "availability": "a class's windowed success rate is below its "
    "availability target",
    "no_traffic": "a class saw fewer calls than its min_calls floor — "
    "a silently dropped workload must not read as healthy",
    "alert_firing": "an alert was still FIRING at evaluation time "
    "(the run must end recovered, not mid-incident)",
    "error_budget_burn": "the run's overall error rate burned the "
    "alert_slo_error_rate budget beyond slo_max_burn",
}


class SloClass:
    """One query class: the SQL shapes that belong to it (parameter and
    literal spellings both — they fingerprint differently) plus its
    targets. ``None`` targets inherit the ``slo_*`` config defaults; an
    explicit 0/negative target disables that check."""

    __slots__ = ("name", "sqls", "p50_ms", "p99_ms", "availability",
                 "min_calls")

    def __init__(
        self,
        name: str,
        sqls: Iterable[str],
        p50_ms: Optional[float] = None,
        p99_ms: Optional[float] = None,
        availability: Optional[float] = None,
        min_calls: int = 1,
    ) -> None:
        self.name = name
        self.sqls = tuple(sqls)
        self.p50_ms = config.slo_p50_ms if p50_ms is None else p50_ms
        self.p99_ms = config.slo_p99_ms if p99_ms is None else p99_ms
        self.availability = (
            config.slo_availability if availability is None else availability
        )
        self.min_calls = min_calls

    def fids(self) -> List[str]:
        return sorted({fingerprint_cached(s).fid for s in self.sqls})

    def to_dict(self) -> Dict[str, object]:
        return {
            "name": self.name,
            "fingerprints": self.fids(),
            "p50_ms": self.p50_ms,
            "p99_ms": self.p99_ms,
            "availability": self.availability,
            "min_calls": self.min_calls,
        }


class SloSpec:
    """The declarative spec one run is judged against."""

    __slots__ = ("classes", "require_no_firing", "max_burn",
                 "error_budget")

    def __init__(
        self,
        classes: Iterable[SloClass],
        require_no_firing: bool = True,
        max_burn: Optional[float] = None,
        error_budget: Optional[float] = None,
    ) -> None:
        self.classes = list(classes)
        self.require_no_firing = require_no_firing
        self.max_burn = config.slo_max_burn if max_burn is None else max_burn
        self.error_budget = (
            config.alert_slo_error_rate
            if error_budget is None
            else error_budget
        )

    def to_dict(self) -> Dict[str, object]:
        return {
            "classes": [c.to_dict() for c in self.classes],
            "require_no_firing": self.require_no_firing,
            "max_burn": self.max_burn,
            "error_budget": self.error_budget,
        }


class SloRun:
    """One armed evaluation window: the spec plus the begin-time
    histogram snapshot :meth:`SloEngine.finish` differences against."""

    __slots__ = ("spec", "t0", "base")

    def __init__(self, spec: SloSpec, base: Dict[str, Dict]) -> None:
        self.spec = spec
        self.t0 = time.time()
        self.base = base


def _delta(cur: Dict, base: Optional[Dict]) -> Dict:
    """Windowed per-fingerprint stats: current minus the begin-time
    snapshot (a fingerprint absent at begin contributes whole)."""
    if base is None:
        return {
            "calls": cur["calls"],
            "errors": cur["errors"],
            "total_s": cur["total_s"],
            "max_s": cur["max_s"],
            "buckets": list(cur["buckets"]),
        }
    return {
        "calls": cur["calls"] - base["calls"],
        "errors": cur["errors"] - base["errors"],
        "total_s": cur["total_s"] - base["total_s"],
        # max_s is cumulative (no windowed max exists) — it only ever
        # OVER-bounds the overflow bucket's interpolation ceiling
        "max_s": cur["max_s"],
        "buckets": [
            c - b for c, b in zip(cur["buckets"], base["buckets"])
        ],
    }


class SloEngine:
    """Windowed SLO evaluation + the last report (the ``GET /slo``
    document). Process-wide singleton (:data:`engine`), mirroring the
    stats/alerts singletons."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._last: Optional[Dict] = None

    # -- run lifecycle -------------------------------------------------------

    def begin(self, spec: SloSpec) -> SloRun:
        """Arm one evaluation window: snapshot every spec fingerprint's
        histogram so :meth:`finish` scores only this run's traffic.
        Also installs the spec's class membership into the critical-path
        plane so its per-SloClass breakdowns roll up by the same
        names."""
        from orientdb_tpu.obs.critpath import register_slo_classes

        register_slo_classes(spec.classes)
        fids = [f for c in spec.classes for f in c.fids()]
        return SloRun(spec, stats.histogram_snapshot(fids))

    def finish(
        self, run: SloRun, extra: Optional[Dict] = None
    ) -> Dict[str, object]:
        """Judge the window: per-class quantiles/availability from the
        stats-table deltas, run-wide alert + burn policy from the alert
        engine. Returns (and stores) the machine-readable report;
        ``extra`` merges driver-side context (schedule digest, chaos
        summary) into it verbatim."""
        from orientdb_tpu.obs.alerts import engine as alert_engine

        with span("slo.evaluate", classes=len(run.spec.classes)):
            report = self._evaluate(run, alert_engine)
        if extra:
            report.update(extra)
        with self._mu:
            self._last = report
        metrics.gauge("slo.passed", 1 if report["verdict"] == "pass" else 0)
        metrics.gauge("slo.burn", report["burn"])
        metrics.gauge("slo.failures", len(report["failures"]))
        if report["verdict"] != "pass":
            log.warning(
                "SLO verdict FAIL: %s",
                "; ".join(
                    f"{f['rule']}({f['key']})" for f in report["failures"]
                ),
            )
        return report

    def _evaluate(self, run: SloRun, alert_engine) -> Dict[str, object]:
        spec = run.spec
        failures: List[Dict] = []

        def fail(rule: str, key: str, value, threshold, detail: str):
            failures.append(
                {
                    "rule": rule,
                    "key": key,
                    "value": round(float(value), 6),
                    "threshold": round(float(threshold), 6),
                    "detail": detail,
                }
            )

        classes: List[Dict] = []
        total_calls = total_errors = 0
        cur = stats.histogram_snapshot(
            [f for c in spec.classes for f in c.fids()]
        )
        for cls in spec.classes:
            agg = None
            for fid in cls.fids():
                if fid not in cur:
                    continue
                d = _delta(cur[fid], run.base.get(fid))
                if agg is None:
                    agg = d
                else:
                    agg["calls"] += d["calls"]
                    agg["errors"] += d["errors"]
                    agg["total_s"] += d["total_s"]
                    agg["max_s"] = max(agg["max_s"], d["max_s"])
                    agg["buckets"] = [
                        a + b for a, b in zip(agg["buckets"], d["buckets"])
                    ]
            calls = agg["calls"] if agg else 0
            errors = agg["errors"] if agg else 0
            row: Dict[str, object] = {
                "class": cls.name,
                "calls": calls,
                "errors": errors,
                "targets": {
                    "p50_ms": cls.p50_ms,
                    "p99_ms": cls.p99_ms,
                    "availability": cls.availability,
                },
            }
            if calls < cls.min_calls:
                fail(
                    "no_traffic", cls.name, calls, cls.min_calls,
                    f"class {cls.name} saw {calls} calls "
                    f"(< min_calls {cls.min_calls})",
                )
                classes.append(row)
                continue
            total_calls += calls
            total_errors += errors
            for field, q in QUANTILE_FIELDS:
                row[field] = round(
                    estimate_quantile(agg["buckets"], q, agg["max_s"])
                    * 1000.0,
                    3,
                )
            row["error_rate"] = round(errors / calls, 6)
            ok_rate = 1.0 - errors / calls
            if cls.availability > 0 and ok_rate < cls.availability:
                fail(
                    "availability", cls.name, ok_rate, cls.availability,
                    f"class {cls.name}: success rate {ok_rate:.4f} < "
                    f"target {cls.availability:.4f} "
                    f"({errors}/{calls} errors)",
                )
            for rule, field, target in (
                ("p50_latency", "p50_ms", cls.p50_ms),
                ("p99_latency", "p99_ms", cls.p99_ms),
            ):
                if target > 0 and row[field] > target:
                    fail(
                        rule, cls.name, row[field], target,
                        f"class {cls.name}: {field} {row[field]:.1f} ms "
                        f"> target {target:g} ms",
                    )
            classes.append(row)

        firing = [
            a for a in alert_engine.active() if a["state"] == "firing"
        ]
        if spec.require_no_firing:
            for a in firing:
                fail(
                    "alert_firing", a["rule"], a["value"], a["threshold"],
                    f"alert {a['rule']}({a['key']}) still firing: "
                    f"{a['detail']}",
                )
        burn = 0.0
        if total_calls > 0 and spec.error_budget > 0:
            burn = (total_errors / total_calls) / spec.error_budget
            if spec.max_burn > 0 and burn > spec.max_burn:
                fail(
                    "error_budget_burn", "run", burn, spec.max_burn,
                    f"run error rate {total_errors / total_calls:.4f} "
                    f"burns the {spec.error_budget:g} budget at "
                    f"{burn:.2f}x (> {spec.max_burn:g}x)",
                )
        return {
            "ts": round(time.time(), 3),
            "window_s": round(time.time() - run.t0, 3),
            "verdict": "fail" if failures else "pass",
            "failures": failures,
            "burn": round(burn, 4),
            "calls": total_calls,
            "errors": total_errors,
            "classes": classes,
            "alerts_firing": [a["rule"] for a in firing],
            "spec": spec.to_dict(),
        }

    # -- reading (scrape-time) ----------------------------------------------

    def report(self) -> Dict[str, object]:
        """The ``GET /slo`` document: the last run's report, or an
        explicit empty marker (never a fabricated pass)."""
        with self._mu:
            if self._last is not None:
                return dict(self._last)
        return {
            "ts": round(time.time(), 3),
            "verdict": "none",
            "detail": "no SLO run recorded in this process "
            "(workloads.driver.TrafficSim produces one)",
        }

    def reset(self) -> None:
        with self._mu:
            self._last = None


#: the process-wide engine (the stats/alerts singleton convention)
engine = SloEngine()
