"""Slow-query log: a bounded ring of queries over the threshold.

Analog of the reference's command profiling + the classic database
slow-query log ([E] OProfiler records per-command chronos; operators
watch the tail). Queries slower than ``config.slow_query_ms`` land in
a process-wide ring with their SQL, engine, duration, and trace id —
the console surfaces it (``SLOWLOG``), and every recorded entry bumps
the ``slowlog.recorded`` counter so /metrics shows the rate.

``slow_query_ms = 0`` disables recording entirely.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional

from orientdb_tpu.utils.config import config
from orientdb_tpu.utils.logging import get_logger

log = get_logger("slowlog")


class SlowQueryLog:
    def __init__(self, capacity: int) -> None:
        self._lock = threading.Lock()
        self._entries: deque = deque(maxlen=max(capacity, 8))

    def record(
        self,
        sql: str,
        duration_s: float,
        engine: str,
        trace_id: Optional[str] = None,
        fingerprint: Optional[str] = None,
        cache: Optional[str] = None,
    ) -> bool:
        """Record ``sql`` if it crossed the threshold; returns whether
        it did. Reads the threshold per call so tests (and a live
        console) can retune without restarting. ``fingerprint`` is the
        query-shape id (obs/stats) — the pivot from one slow query into
        its cumulative ``STATS QUERIES`` row and trace; ``cache``
        records how the plan was obtained (``hit``/``miss``/
        ``result-cache``/None)."""
        threshold_ms = config.slow_query_ms
        ms = duration_s * 1000.0
        if threshold_ms <= 0 or ms < threshold_ms:
            return False
        if fingerprint is None:
            # a caller outside the engine front door (or a sampled-out
            # query) still gets a joinable id — the fingerprint is pure
            # text normalization
            from orientdb_tpu.obs.stats import fingerprint_cached

            try:
                fingerprint = fingerprint_cached(sql).fid
            except Exception:
                fingerprint = None
        entry = {
            "ts": time.time(),
            "sql": sql,
            "ms": round(ms, 2),
            "engine": engine,
            "trace_id": trace_id,
            "fingerprint": fingerprint,
            "cache": cache,
        }
        with self._lock:
            self._entries.append(entry)
        from orientdb_tpu.utils.metrics import metrics

        metrics.incr("slowlog.recorded")
        log.info("slow query (%.1f ms, %s): %s", ms, engine, sql)
        return True

    def entries(self, limit: Optional[int] = None) -> List[Dict]:
        """Most recent first."""
        with self._lock:
            items = list(self._entries)
        items.reverse()
        return items if limit is None else items[:limit]

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()


#: the process-wide instance (sized by config.slowlog_capacity)
slowlog = SlowQueryLog(config.slowlog_capacity)
