"""Critical-path attribution: per-request latency decomposition.

The flight recorder (obs/timeline) can say *that* a dispatch was slow;
this plane says *which segment of the request's life* grew. Every
sampled request becomes a waterfall of named segments — admission →
parse → queue (lane window) → plan_resolve → param_upload|ring_hit →
device_compute|host_compute → result_transfer → marshal → flush — by
joining the existing per-query accumulator (obs/stats ``_Acc``: device,
transfer, queue, compile attribution) with stamps threaded through the
previously unstamped edges: admission entry (server/admission), request
parse and response marshal/flush (server/binary_server,
server/http_server), the oracle interpreter (exec/engine), retry sleep
in the device-fault ladder (exec/devicefault), and lane collection
(server/coalesce — per-item segments ride the items back to their
submitting sessions).

Aggregation (all at :func:`commit`, never mid-request):

- a bounded ring of recent decompositions (``critpath_capacity``);
- per-fingerprint cumulative segment columns riding the PR-4 stats
  table (:meth:`obs.stats.QueryStats.record_segments`);
- per-``SloClass`` cumulative breakdowns with a dominant-bottleneck
  rollup (class membership installed by :func:`register_slo_classes`
  from ``obs/slo``; unmapped fingerprints aggregate as
  ``unclassified``);
- a per-fingerprint sliding window feeding :meth:`CritPathPlane.blame`
  — the ``latency_regression`` alert's blame annotation: diff the
  recent window's mean breakdown against the older history and name
  the segment(s) that grew, with the worst recent request's trace id
  as exemplar.

Surfaces: ``GET /stats/critpath``, the debug bundle's ``critpath``
section, and the console's ``CRITPATH [k]``.

Accounting invariant: :func:`commit` folds any unattributed residual
(request wall minus the stamped segments) into ``host_compute``, so a
decomposition's segment sum always equals the measured wall latency —
nothing hides between segments. Segments stamped from worker threads
(lane device/transfer shares) are amortized sub-intervals of the
submitter's wait, so the residual stays non-negative in practice.

``critpathlint`` (orientdb_tpu/analysis) fails the build when a
``segment(...)``/``add_segment(...)`` stamp site names something not in
:data:`SEGMENT_CATALOG`, or a catalog entry has no stamp site left.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from typing import Dict, Iterable, List, Optional

from orientdb_tpu.utils.config import config

#: segment name -> what it measures. The decomposition vocabulary in
#: one place: ``critpathlint`` cross-checks every literal stamp site
#: against this dict, and the README's segment-catalog table renders
#: from the same entries — the two planes cannot drift.
SEGMENT_CATALOG: Dict[str, str] = {
    "admission": "admission-control pressure check and shed wait "
    "(server/admission.db_pressure)",
    "parse": "request envelope/frame parse on the wire listener "
    "(binary frame JSON decode, HTTP body decode)",
    "queue": "time parked before execution: coalesce lane queue + "
    "collection window, batch queue waits",
    "plan_resolve": "statement parse/plan/compile resolution before "
    "dispatch (recording executions ARE the compile cost)",
    "param_upload": "host->device parameter staging (jax.device_put "
    "of the dynamic args; a ParamRing miss)",
    "ring_hit": "device-resident ParamRing slot match — parameters "
    "reused in place, ~zero host bytes shipped",
    "device_compute": "on-device execution (the dispatch's device "
    "sync share from the profiled fetch waves)",
    "host_compute": "host-side execution: the oracle interpreter, "
    "plus any request wall time no other segment claimed",
    "result_transfer": "device->host result fetch (the profiled "
    "transfer share, bytes on the tunneled link)",
    "fault_retry": "device-fault ladder overhead: retry backoff sleep "
    "and failed attempts before the one that succeeded",
    "marshal": "result materialization/serialization (rows to dicts, "
    "response JSON encode)",
    "flush": "response frame/body write to the socket",
}

#: fingerprint windows kept for blame (LRU past this)
_FID_WINDOWS_MAX = 256

#: minimum per-fingerprint history before blame will diff windows
_BLAME_MIN_HISTORY = 8

#: absolute per-segment growth floor (seconds) below which a diff is
#: jitter, not blame — mirrors the alert plane's _MAD_FLOOR_S scale
_BLAME_FLOOR_S = 5e-4


class CritPath:
    """One sampled request's decomposition under construction."""

    __slots__ = ("kind", "sql", "trace_id", "t0", "ts", "wall_s",
                 "segs", "error", "stats_recorded")

    def __init__(self, kind: str, sql: Optional[str] = None) -> None:
        self.kind = kind
        self.sql = sql
        self.trace_id: Optional[str] = None
        self.t0 = time.monotonic()
        self.ts = 0.0  # stamped at commit (off the begin hot path)
        self.wall_s = 0.0
        self.segs: Dict[str, float] = {}
        self.error = False
        #: True when the execution path already wrote this request's
        #: (amortized) segment columns into the stats table — commit
        #: must not overwrite them with the full-batch split
        self.stats_recorded = False

    def add(self, name: str, seconds: float) -> None:
        if seconds > 0.0:
            self.segs[name] = self.segs.get(name, 0.0) + seconds

    #: held-record stamp: same contract as the module-level
    #: add_segment (critpathlint treats both spellings as stamp
    #: sites), minus the thread-local lookup a caller that already
    #: owns the record would pay for nothing
    add_segment = add

    def total(self) -> float:
        return sum(self.segs.values())

    def to_dict(self) -> Dict[str, object]:
        return {
            "kind": self.kind,
            "sql": self.sql,
            "trace_id": self.trace_id,
            "ts": round(self.ts, 3),
            "wall_ms": round(self.wall_s * 1000.0, 3),
            "segments_ms": {
                k: round(v * 1000.0, 3)
                for k, v in sorted(
                    self.segs.items(), key=lambda kv: -kv[1]
                )
            },
            "error": self.error,
        }


# -- thread-local record stack (mirrors timeline's active-record idiom) ------

_local = threading.local()


def _stack() -> list:
    st = getattr(_local, "stack", None)
    if st is None:
        st = _local.stack = []
    return st


def current() -> Optional[CritPath]:
    st = getattr(_local, "stack", None)
    return st[-1] if st else None


class active:
    """Make ``cp`` the thread's stamping target for a block. Pushing
    None is a no-op pair, so sampled-out paths stay branch-free."""

    __slots__ = ("cp",)

    def __init__(self, cp: Optional[CritPath]) -> None:
        self.cp = cp

    def __enter__(self) -> Optional[CritPath]:
        if self.cp is not None:
            _stack().append(self.cp)
        return self.cp

    def __exit__(self, *exc) -> None:
        if self.cp is not None:
            st = _stack()
            if st and st[-1] is self.cp:
                st.pop()
            else:  # unbalanced (should not happen): drop, don't corrupt
                try:
                    st.remove(self.cp)
                except ValueError:
                    pass


def begin_request(kind: str, sql: Optional[str] = None) -> Optional[CritPath]:
    """Open a decomposition for one request, or None when the plane is
    disabled or the request sampled out. Sampling rides the stats
    plane's rate (``stats_sample_rate``), so a committed decomposition
    joins the same query subset as stats/slowlog/traces."""
    from orientdb_tpu.obs.stats import sampled

    if not config.critpath_enabled or not sampled():
        return None
    cp = CritPath(kind, sql)
    from orientdb_tpu.obs.trace import current_trace_id

    cp.trace_id = current_trace_id()
    return cp


class segment:
    """Time a block into the thread's active record: ``with
    segment("parse"): ...``. No active record (sampled out, or a
    client-side caller of a shared helper) costs one thread-local
    read."""

    __slots__ = ("name", "_cp", "_t0")

    def __init__(self, name: str) -> None:
        self.name = name

    def __enter__(self) -> "segment":
        self._cp = current()
        if self._cp is not None:
            self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc) -> None:
        cp = self._cp
        if cp is not None:
            cp.add(self.name, time.monotonic() - self._t0)
            if cp.trace_id is None:
                from orientdb_tpu.obs.trace import current_trace_id

                cp.trace_id = current_trace_id()


def add_segment(name: str, seconds: float) -> None:
    """Fold measured seconds into the active record's segment — the
    non-context-manager stamp for sites that already hold a duration
    (the device-fault ladder's retry overhead, ring staging)."""
    cp = current()
    if cp is not None and seconds > 0.0:
        cp.add(name, seconds)
        if cp.trace_id is None:
            from orientdb_tpu.obs.trace import current_trace_id

            cp.trace_id = current_trace_id()


def merge(segs: Optional[Dict[str, float]]) -> None:
    """Fold a worker-thread-built segment dict into the active record —
    how a coalesce lane item's amortized decomposition (built on the
    lane worker) reaches its submitting session's request record."""
    cp = current()
    if cp is None or not segs:
        return
    for k, v in segs.items():
        cp.add(k, v)
    if cp.trace_id is None:
        from orientdb_tpu.obs.trace import current_trace_id

        cp.trace_id = current_trace_id()


def note_sql(sql: Optional[str]) -> None:
    """Attach the statement to a record opened before the SQL was known
    (the wire listeners open the record at frame arrival)."""
    cp = current()
    if cp is not None and sql and cp.sql is None:
        cp.sql = sql


class request:
    """Open-or-join front-door helper: when a record is already active
    on this thread (the wire listener opened it), yield that record and
    leave its lifecycle to the opener; otherwise begin + activate a new
    one and commit it on exit — embedded/bench callers of the engine
    front doors get attribution without a server in front."""

    __slots__ = ("kind", "sql", "_cp", "_owned")

    def __init__(self, kind: str, sql: Optional[str] = None) -> None:
        self.kind = kind
        self.sql = sql
        self._owned = False

    def __enter__(self) -> Optional[CritPath]:
        cp = current()
        if cp is not None:
            if self.sql and cp.sql is None:
                cp.sql = self.sql
            self._cp = cp
            return cp
        cp = begin_request(self.kind, self.sql)
        self._cp = cp
        if cp is not None:
            self._owned = True
            _stack().append(cp)
        return cp

    def __exit__(self, exc_type, *exc) -> None:
        if not self._owned:
            return
        cp = self._cp
        st = _stack()
        if st and st[-1] is cp:
            st.pop()
        else:
            try:
                st.remove(cp)
            except ValueError:
                pass
        if exc_type is not None:
            cp.error = True
        commit(cp)


def fold_query(
    cp: Optional[CritPath],
    duration_s: float,
    acc,
    stamped_before: float,
) -> None:
    """Map one finished engine execution onto catalog segments: the
    stats accumulator carries the profiled device/transfer/queue/
    compile attribution; whatever the engine window's wall clock holds
    beyond those AND beyond segments stamped during the window
    (``fault_retry``, the oracle's ``host_compute``) is host execution.
    ``stamped_before`` is ``cp.total()`` at engine entry, so nested
    front doors never double-claim each other's stamps."""
    if cp is None:
        return
    # stamp the held record directly — the caller owns cp, so the
    # thread-local current() lookup the module-level add_segment pays
    # is pure overhead here (commit's fallback covers the trace id)
    if acc is not None:
        cp.add_segment("queue", acc.queue_s)
        cp.add_segment("plan_resolve", acc.compile_s)
        cp.add_segment("device_compute", acc.device_s)
        cp.add_segment("result_transfer", acc.transfer_s)
    stamped_in_window = cp.total() - stamped_before
    cp.add_segment("host_compute", duration_s - stamped_in_window)


class _FidWindow:
    """One fingerprint's recent decompositions — the blame evidence."""

    __slots__ = ("text", "hist", "count", "wall_s", "segs")

    def __init__(self, text: str) -> None:
        self.text = text
        #: (wall_s, segs, trace_id), newest last
        self.hist: deque = deque(maxlen=128)
        self.count = 0
        self.wall_s = 0.0
        self.segs: Dict[str, float] = {}


class _ClassAgg:
    __slots__ = ("count", "wall_s", "segs")

    def __init__(self) -> None:
        self.count = 0
        self.wall_s = 0.0
        self.segs: Dict[str, float] = {}


def _dominant(segs: Dict[str, float]) -> Optional[str]:
    return max(segs, key=segs.get) if segs else None


class CritPathPlane:
    """Process-wide aggregation: ring + per-fid blame windows +
    per-SLO-class cumulative breakdowns. Written only at
    :meth:`commit` (one short lock per sampled request), read by the
    HTTP/console/bundle surfaces and the alert plane's blame hook."""

    def __init__(self, capacity: Optional[int] = None) -> None:
        self._lock = threading.Lock()
        self._ring: deque = deque()
        #: None = read config.critpath_capacity live per commit
        self._capacity = capacity
        self._by_fid: "OrderedDict[str, _FidWindow]" = OrderedDict()
        self._class_of: Dict[str, str] = {}
        self._by_class: Dict[str, _ClassAgg] = {}
        self._committed = 0
        self._totals: Dict[str, float] = {}

    def _cap(self) -> int:
        return (
            self._capacity
            if self._capacity is not None
            else int(config.critpath_capacity)
        )

    # -- write side ----------------------------------------------------------

    def commit(self, cp: Optional[CritPath]) -> None:
        """Seal one record: stamp wall, fold the unattributed residual
        into ``host_compute`` (the segment sum == wall invariant), and
        aggregate. A record never committed (an abandoned pipelined
        frame) simply never enters any surface."""
        if cp is None:
            return
        cp.wall_s = time.monotonic() - cp.t0
        cp.ts = time.time()  # deferred from begin: one clock read here
        residual = cp.wall_s - cp.total()
        if residual > 0.0:
            add = cp.segs.get("host_compute", 0.0) + residual
            cp.segs["host_compute"] = add
        if cp.trace_id is None:
            from orientdb_tpu.obs.trace import current_trace_id

            cp.trace_id = current_trace_id()
        fid = text = None
        if cp.sql:
            from orientdb_tpu.obs.stats import fingerprint_cached, stats

            fp = fingerprint_cached(cp.sql)
            fid, text = fp.fid, fp.text
            # per-fingerprint cumulative segment columns ride the PR-4
            # stats accumulator table (sampling already decided at
            # begin_request — record_segments must not thin it again)
            if not cp.stats_recorded:
                stats.record_segments(cp.sql, cp.segs)
        cap = self._cap()
        with self._lock:
            self._committed += 1
            for k, v in cp.segs.items():
                self._totals[k] = self._totals.get(k, 0.0) + v
            if cap > 0:
                # store the record itself; recent() renders at read
                # time so the hot path skips the dict build entirely
                self._ring.append(cp)
                while len(self._ring) > cap:
                    self._ring.popleft()
            cls = "unclassified"
            if fid is not None:
                w = self._by_fid.get(fid)
                if w is None:
                    while len(self._by_fid) >= _FID_WINDOWS_MAX:
                        self._by_fid.popitem(last=False)
                    w = self._by_fid[fid] = _FidWindow(text or "")
                else:
                    self._by_fid.move_to_end(fid)
                w.hist.append((cp.wall_s, dict(cp.segs), cp.trace_id))
                w.count += 1
                w.wall_s += cp.wall_s
                for k, v in cp.segs.items():
                    w.segs[k] = w.segs.get(k, 0.0) + v
                cls = self._class_of.get(fid, "unclassified")
            agg = self._by_class.get(cls)
            if agg is None:
                agg = self._by_class[cls] = _ClassAgg()
            agg.count += 1
            agg.wall_s += cp.wall_s
            for k, v in cp.segs.items():
                agg.segs[k] = agg.segs.get(k, 0.0) + v

    def register_classes(self, mapping: Dict[str, str]) -> None:
        """Install fingerprint -> SloClass-name membership (called by
        ``obs/slo`` when a spec begins; later registrations win)."""
        with self._lock:
            self._class_of.update(mapping)

    # -- blame (the latency_regression annotation) ---------------------------

    def blame(self, fid: str) -> Optional[Dict[str, object]]:
        """Diff the fingerprint's recent window against its older
        history: which segment(s) grew, and the worst recent request's
        trace id as exemplar. None when the history is too thin to
        split into baseline + current windows."""
        with self._lock:
            w = self._by_fid.get(fid)
            items = list(w.hist) if w is not None else []
        if len(items) < _BLAME_MIN_HISTORY:
            return None
        cut = max(4, len(items) // 4)
        recent, older = items[-cut:], items[:-cut]
        if not older:
            return None

        def _mean_segs(rows) -> Dict[str, float]:
            out: Dict[str, float] = {}
            for _wall, segs, _tid in rows:
                for k, v in segs.items():
                    out[k] = out.get(k, 0.0) + v
            return {k: v / len(rows) for k, v in out.items()}

        cur = _mean_segs(recent)
        base = _mean_segs(older)
        ratio = max(float(config.critpath_blame_ratio), 0.0)
        grown: List[Dict[str, float]] = []
        for seg in sorted(set(cur) | set(base)):
            c, b = cur.get(seg, 0.0), base.get(seg, 0.0)
            if c - b > max(b * ratio, _BLAME_FLOOR_S):
                grown.append(
                    {
                        "segment": seg,
                        "base_ms": round(b * 1000.0, 3),
                        "cur_ms": round(c * 1000.0, 3),
                        "delta_ms": round((c - b) * 1000.0, 3),
                    }
                )
        if not grown:
            return None
        grown.sort(key=lambda g: -g["delta_ms"])
        worst = max(
            recent, key=lambda row: row[0]
        )  # (wall, segs, trace) — worst wall carries the exemplar
        return {
            "segments": grown,
            "top": grown[0]["segment"],
            "trace_id": worst[2],
        }

    # -- read side -----------------------------------------------------------

    def totals(self) -> Dict[str, float]:
        """Cumulative seconds per segment across every committed
        record — the bench headline differences two of these around a
        timed block for its per-segment extras."""
        with self._lock:
            return dict(self._totals)

    def recent(self, k: int = 50) -> List[Dict]:
        with self._lock:
            items = list(self._ring)
        return [c.to_dict() for c in items[-max(k, 0):][::-1]]

    def report(self, k: int = 20) -> Dict[str, object]:
        """The ``GET /stats/critpath`` document: per-class rollups with
        dominant bottleneck, top fingerprints by cumulative wall, and
        the most recent decompositions."""
        with self._lock:
            classes = {
                name: {
                    "requests": agg.count,
                    "wall_ms_mean": round(
                        agg.wall_s * 1000.0 / agg.count, 3
                    ) if agg.count else 0.0,
                    "segments_ms_mean": {
                        s: round(v * 1000.0 / agg.count, 3)
                        for s, v in sorted(
                            agg.segs.items(), key=lambda kv: -kv[1]
                        )
                    } if agg.count else {},
                    "dominant": _dominant(agg.segs),
                }
                for name, agg in self._by_class.items()
            }
            fids = [
                {
                    "fingerprint": fid,
                    "query": w.text,
                    "requests": w.count,
                    "wall_ms_mean": round(
                        w.wall_s * 1000.0 / w.count, 3
                    ) if w.count else 0.0,
                    "segments_ms_mean": {
                        s: round(v * 1000.0 / w.count, 3)
                        for s, v in sorted(
                            w.segs.items(), key=lambda kv: -kv[1]
                        )
                    } if w.count else {},
                    "dominant": _dominant(w.segs),
                    "wall_s_total": w.wall_s,
                }
                for fid, w in self._by_fid.items()
            ]
            committed = self._committed
        fids.sort(key=lambda r: -r.pop("wall_s_total"))
        return {
            "ts": round(time.time(), 3),
            "enabled": bool(config.critpath_enabled),
            "requests": committed,
            "segment_catalog": dict(SEGMENT_CATALOG),
            "by_class": classes,
            "fingerprints": fids[: max(k, 0)],
            "recent": self.recent(min(max(k, 0), 20)),
        }

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()
            self._by_fid.clear()
            self._by_class.clear()
            self._class_of.clear()
            self._committed = 0
            self._totals.clear()


#: the process-wide plane (mirrors stats/tracer/recorder singletons)
plane = CritPathPlane()


def commit(cp: Optional[CritPath]) -> None:
    plane.commit(cp)


def register_slo_classes(classes: Iterable) -> None:
    """Map every SloClass's fingerprints to its name for the per-class
    rollup (``obs/slo`` calls this when a spec's run begins)."""
    mapping: Dict[str, str] = {}
    for cls in classes:
        try:
            for fid in cls.fids():
                mapping[fid] = cls.name
        except Exception:  # a malformed class must not kill the run
            continue
    if mapping:
        plane.register_classes(mapping)
