"""Fleet-level aggregation: ``/cluster/health`` and ``/cluster/metrics``.

The obs plane so far is per-process; a replicated/sharded deployment is
operated from the FLEET view ([E] the reference's distributed status
output — ``ODistributedServerManager.dump()`` / the ``HA STATUS``
command — and every serving stack's health+metrics aggregator):

- :func:`cluster_health` — one JSON document with per-member liveness
  (a real HTTP probe, not the coordinator's cached view), role,
  replication lag, in-doubt 2PC count, and slowlog depth;
- :func:`cluster_metrics_text` — fan-in: every member's registry
  snapshot (``GET /metrics?format=json``) merged into one Prometheus
  exposition, every series labeled ``member="<name>"``
  (``obs/registry.render_prometheus_multi``), plus a synthetic
  ``cluster.member_up`` gauge so an unreachable member is a visible
  0-series instead of a silent hole.

Both read ``server.cluster`` (set by ``parallel/cluster.Cluster`` when
the member registers). A server outside any cluster serves a
single-member degenerate view — the endpoints always answer, so
dashboards need no special-casing for standalone nodes.

Tests run all members in one process (the multi-server-in-one-JVM
strategy, SURVEY.md §4); the registries there are process-wide
singletons, so per-member numbers coincide — the fan-in transport and
labeling are what this module exercises.
"""

from __future__ import annotations

import base64
import json
import time
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, Optional

from orientdb_tpu.obs.registry import (
    render_prometheus_multi,
    snapshot_all,
)
from orientdb_tpu.utils.logging import get_logger

log = get_logger("cluster_view")

#: per-member probe/scrape timeout (seconds) — the health endpoint must
#: answer promptly even with a member down
PROBE_TIMEOUT = 1.5


def _get_json(url: str, user: str, password: str) -> Dict:
    from orientdb_tpu.chaos import fault

    cred = base64.b64encode(f"{user}:{password}".encode()).decode()
    req = urllib.request.Request(
        url, headers={"Authorization": f"Basic {cred}"}
    )
    with fault.point("cluster.probe"):
        with urllib.request.urlopen(req, timeout=PROBE_TIMEOUT) as r:
            return json.loads(r.read())


def _alerts_block() -> Dict:
    """The health document's alert view: active alerts (pending +
    firing, exemplars included) and the watchdog summary."""
    from orientdb_tpu.obs.alerts import engine

    return {"summary": engine.summary(), "active": engine.active()}


def _staged_2pc(db) -> int:
    """In-doubt (prepared, undecided) 2PC batches staged on a database."""
    reg = getattr(db, "_tx2pc_registry", None)
    return 0 if reg is None else len(reg.staged_report())


def _member_health(cluster, m) -> Dict:
    from orientdb_tpu.cdc.feed import feed_summary
    from orientdb_tpu.obs.slowlog import slowlog

    out: Dict[str, object] = {
        "role": m.role,
        "url": m.url,
        "in_doubt_2pc": _staged_2pc(m.db),
        "slowlog_depth": len(slowlog.entries()),
    }
    cdc = feed_summary(m.db)
    if cdc is not None:
        # changefeed pressure: consumer count, queue depth, worst lag
        out["cdc"] = cdc
    if m.puller is not None:
        out["replication"] = m.puller.lag()
    try:
        _get_json(
            f"{m.url}/listDatabases", cluster.user, cluster.password
        )
        out["alive"] = True
    except Exception as e:
        out["alive"] = False
        out["probe_error"] = f"{type(e).__name__}: {e}"
    return out


def cluster_health(server) -> Dict:
    """The fleet health document. ``server`` is the answering member's
    ``server.Server``; without an attached cluster the view degrades to
    this one node."""
    from orientdb_tpu.parallel.resilience import breaker_snapshot
    from orientdb_tpu.parallel.twophase import resolver

    cluster = getattr(server, "cluster", None)
    if cluster is None:
        from orientdb_tpu.cdc.feed import feed_summary
        from orientdb_tpu.obs.slowlog import slowlog

        member: Dict[str, object] = {
            "role": "STANDALONE",
            "alive": True,
            "in_doubt_2pc": sum(
                _staged_2pc(db) for db in server.databases.values()
            ),
            "slowlog_depth": len(slowlog.entries()),
        }
        cdc = {
            db.name: s
            for db in server.databases.values()
            for s in [feed_summary(db)]
            if s is not None
        }
        if cdc:
            member["cdc"] = cdc
        return {
            "ts": round(time.time(), 3),
            "cluster": None,
            "members": {server.name: member},
            "breakers": breaker_snapshot(),
            "indoubt_pending": resolver.pending(),
            "alerts": _alerts_block(),
            "device_faults": _device_faults_block(),
        }
    with cluster._lock:
        members = dict(cluster.members)
        primary = cluster.primary
        failovers = cluster.failovers
        dbname = cluster.dbname
    # probe members concurrently: one DOWN node must cost one timeout,
    # not one per caller-visible second of serial probing
    with ThreadPoolExecutor(max_workers=max(len(members), 1)) as pool:
        futs = {
            name: pool.submit(_member_health, cluster, m)
            for name, m in members.items()
        }
        out_members = {name: f.result() for name, f in futs.items()}
    return {
        "ts": round(time.time(), 3),
        "cluster": {
            "dbname": dbname,
            "primary": primary,
            "failovers": failovers,
        },
        "members": out_members,
        # per-channel circuit-breaker state (parallel/resilience) and
        # the coordinator-side in-doubt backlog the probe is resolving
        "breakers": breaker_snapshot(),
        "indoubt_pending": resolver.pending(),
        # the alert plane's view (obs/alerts): active alerts with
        # exemplar trace ids + the watchdog summary — the "is anything
        # wrong" answer next to the raw per-member signals above
        "alerts": _alerts_block(),
        # the device fault domain's local state (exec/devicefault):
        # quarantined plans, relief actuations, shed latch — the
        # operator's "is the device degrading" answer
        "device_faults": _device_faults_block(),
    }


def _device_faults_block() -> Dict:
    from orientdb_tpu.exec.devicefault import domain as _fault_domain

    return _fault_domain.snapshot()


def _member_snapshots(server) -> Dict[str, Optional[Dict]]:
    """Per-member registry snapshots: scraped over HTTP from each
    member (``None`` marks an unreachable one). A cluster-less server
    answers with its own in-process snapshot."""
    cluster = getattr(server, "cluster", None)
    if cluster is None:
        return {server.name: snapshot_all()}
    with cluster._lock:
        members = [(m.name, m.url) for m in cluster.members.values()]

    def scrape(url: str) -> Optional[Dict]:
        try:
            return _get_json(
                f"{url}/metrics?format=json",
                cluster.user,
                cluster.password,
            )
        except Exception:
            return None

    with ThreadPoolExecutor(max_workers=max(len(members), 1)) as pool:
        futs = {name: pool.submit(scrape, url) for name, url in members}
        return {name: f.result() for name, f in futs.items()}


def cluster_metrics_json(server) -> Dict:
    """The raw fan-in: ``{member: snapshot-or-null}``."""
    return {"members": _member_snapshots(server)}


def cluster_metrics_text(server) -> str:
    """The merged Prometheus exposition, labeled by member."""
    snaps = _member_snapshots(server)
    merged: Dict[str, Dict] = {}
    for name, snap in snaps.items():
        up = snap is not None
        snap = dict(snap) if up else {}
        # the synthetic liveness series: an unreachable member shows as
        # member_up 0 with no other series, never as a silent hole
        counters = dict(snap.get("counters", {}))
        gauges = dict(snap.get("gauges", {}))
        gauges["cluster.member_up"] = 1 if up else 0
        snap["counters"] = counters
        snap["gauges"] = gauges
        merged[name] = snap
    return render_prometheus_multi(merged)
