"""Flight-recorder debug bundle: one JSON artifact for incident triage.

When a distributed failure mode shows up (a quorum stall, an in-doubt
2PC, a replica diverging), the operator needs the process's recent
history as ONE artifact, not four endpoints scraped in a hurry:

- recent traces, ASSEMBLED by trace id (cross-node spans land in one
  group thanks to propagation — coordinator, participants, and
  replication applies of one write share a trace);
- the slow-query log (entries carry their query fingerprint);
- a full metrics snapshot (counters/gauges/durations/histograms);
- the query-statistics table (obs/stats: per-fingerprint cumulative
  cost) and the span-profile self-time tree (obs/profile) — the
  aggregate context a single slow trace is judged against;
- in-doubt 2PC state: staged-but-undecided batches per database, plus
  the coordinator-side in-doubt reports (``twophase.INDOUBT_LOG``);
- changefeed state per database (``orientdb_tpu/cdc``): head LSN,
  consumer lag/queue depth/shed counts, durable cursors — the first
  thing to read when a downstream pipeline reports missing events;
- the alert plane (``obs/alerts``): active and recently-resolved
  alerts (exemplar trace ids included) + the watchdog summary;
- the dispatch timeline (``obs/timeline``): the flight recorder's
  overlap report (device-idle / transfer-hidden fractions, ring
  savings) plus the most recent dispatch records — whether the perf
  plane's claimed overlap actually happened, in the same artifact as
  the traces that would explain why not;
- the bounded log ring (``utils/logging.log_ring``): recent structured
  log records carrying the trace/span ids of whatever emitted them —
  an alert, its exemplar trace, and its log lines join on one id;
- the device-memory ledger (``obs/memledger``): attributed HBM by
  owner kind, the watermark ring, a reconciliation pass against
  ``jax.live_arrays()``, and outstanding/stale epoch leases — what is
  in HBM, who owns it, and whether anything is leaking.

Served as ``GET /debug/bundle`` (admin-only) and from the console as
``DIAG [<path>]``. Everything here is JSON-friendly by construction.
"""

from __future__ import annotations

import time
from typing import Dict, Iterable, List, Optional

from orientdb_tpu.obs.memledger import memledger
from orientdb_tpu.obs.registry import snapshot_all
from orientdb_tpu.obs.slowlog import slowlog
from orientdb_tpu.obs.trace import tracer


def assemble_traces(max_traces: int = 50) -> List[Dict]:
    """The tracer ring grouped by trace id: newest ``max_traces``
    traces, each as ``{"trace_id", "spans": [...]}`` with spans in
    finish order. Cross-node spans that continued a propagated context
    group under the originating trace id."""
    groups: Dict[str, List[Dict]] = {}
    order: List[str] = []  # trace ids by FIRST finished span
    for sp in tracer.spans():
        tid = sp.trace_id
        if tid not in groups:
            groups[tid] = []
            order.append(tid)
        groups[tid].append(sp.to_dict())
    newest = order[-max_traces:] if max_traces else order
    return [
        {"trace_id": tid, "spans": groups[tid]} for tid in newest
    ]


def cdc_state(dbs: Iterable) -> Dict:
    """Per-database changefeed stats (databases without a feed are
    omitted — no feed means no subscribers and nothing to triage)."""
    out: Dict[str, Dict] = {}
    for db in dbs:
        feed = db.__dict__.get("_cdc_feed")
        if feed is not None:
            out[db.name] = feed.stats()
    return out


def _device_faults() -> Dict:
    """The device fault domain's state (lazy import: the bundle must
    stay loadable without pulling the exec stack at module import)."""
    from orientdb_tpu.exec.devicefault import domain as _fault_domain

    return _fault_domain.snapshot()


def in_doubt_state(dbs: Iterable) -> Dict:
    """Participant-side staged (prepared, undecided) 2PC batches per
    database plus the coordinator-side in-doubt reports."""
    from orientdb_tpu.parallel.twophase import INDOUBT_LOG

    staged: Dict[str, List[Dict]] = {}
    for db in dbs:
        reg = getattr(db, "_tx2pc_registry", None)
        items = reg.staged_report() if reg is not None else []
        if items:
            staged[db.name] = items
    return {
        "staged": staged,
        "coordinator_reports": list(INDOUBT_LOG),
    }


def debug_bundle(
    dbs: Iterable = (),
    member: Optional[str] = None,
    cluster=None,
    max_traces: int = 50,
) -> Dict:
    """The full bundle. ``dbs`` are this process's databases (for
    staged-2PC state); ``cluster`` (when attached) contributes the
    membership status block."""
    from orientdb_tpu.obs.alerts import engine
    from orientdb_tpu.obs.critpath import plane as critpath_plane
    from orientdb_tpu.obs.profile import profiler
    from orientdb_tpu.obs.stats import stats
    from orientdb_tpu.obs.timeline import recorder
    from orientdb_tpu.utils.config import config
    from orientdb_tpu.utils.logging import log_ring

    dbs = list(dbs)  # iterated twice: 2PC state and cdc state
    out: Dict[str, object] = {
        "ts": round(time.time(), 3),
        "member": member,
        "traces": assemble_traces(max_traces),
        "slowlog": slowlog.entries(),
        "metrics": snapshot_all(),
        "query_stats": stats.top(50),
        "profile": profiler.profile(),
        "in_doubt_2pc": in_doubt_state(dbs),
        "cdc": cdc_state(dbs),
        "alerts": {
            "summary": engine.summary(),
            "active": engine.active(),
            "history": engine.history(50),
        },
        # the dispatch flight recorder's recent window: the overlap
        # verdict plus a bounded slice of raw records (full Perfetto
        # export stays on GET /debug/timeline — a bundle is for triage,
        # not for a 2048-record trace dump)
        "timeline": {
            "overlap": recorder.overlap(
                window_s=config.timeline_window_s
            ),
            "records": recorder.records(
                window_s=config.timeline_window_s, limit=50
            ),
        },
        # per-request critical-path attribution (obs/critpath): which
        # segment of the request's life the latency lives in, per SLO
        # class and per fingerprint, with recent decompositions — the
        # blame evidence next to the alerts that cite it
        "critpath": critpath_plane.report(8),
        # the device-memory ledger (obs/memledger): per-owner HBM
        # rollup, watermark ring, reconciliation vs jax.live_arrays,
        # and lease/refusal state — what is in HBM and who owns it,
        # next to the traces that put it there
        "memory": memledger.report(),
        # the device fault domain (exec/devicefault): classified fault
        # counts, quarantined plans (with reasons + TTLs), relief
        # actuations, and the admission shed latch — the escalation
        # ladder's state next to the memory it was relieving
        "device_faults": _device_faults(),
        # recent structured log records, trace/span-correlated — the
        # ring is bounded (config.log_ring_capacity) and ships only
        # inside this admin-only bundle
        "logs": log_ring.entries(),
    }
    if cluster is not None:
        try:
            out["cluster"] = cluster.status()
        except Exception as e:  # never let status wedge the bundle
            out["cluster"] = {"error": f"{type(e).__name__}: {e}"}
    return out
