"""Histogram metrics + Prometheus-style text exposition.

Extends the process registry (``utils/metrics.py``: counters, gauges,
duration stats — the [E] OProfiler analog) with two things the serving
story needs:

- **histograms** — bucketed distributions (query latency, WAL fsync
  latency, frontier sizes) whose tails survive aggregation, unlike the
  count/total/max duration stats;
- **exposition** — :func:`render_prometheus` renders the ENTIRE
  registry (counters → ``_total`` counters, gauges → gauges, duration
  stats → summaries, histograms → classic cumulative-bucket
  histograms) in the Prometheus text format (version 0.0.4), served by
  the HTTP listener at ``GET /metrics``.

Metric names keep their internal dotted form in code
(``wal.append_s``) and sanitize to Prometheus identifiers on render
(``orienttpu_wal_append_s``).
"""

from __future__ import annotations

import bisect
import re
import threading
from typing import Dict, Iterable, List, Optional, Tuple

#: default latency buckets (seconds): 100 µs … 10 s, roughly 1-2.5-5
_LATENCY_BUCKETS = (
    0.0001,
    0.00025,
    0.0005,
    0.001,
    0.0025,
    0.005,
    0.01,
    0.025,
    0.05,
    0.1,
    0.25,
    0.5,
    1.0,
    2.5,
    5.0,
    10.0,
)

#: default size buckets (rows/bytes): pow4 ladder
_SIZE_BUCKETS = tuple(float(4**i) for i in range(1, 13))


class Histogram:
    """Cumulative-bucket histogram (thread-safe)."""

    __slots__ = ("name", "buckets", "_counts", "_sum", "_count", "_lock")

    def __init__(self, name: str, buckets: Iterable[float]) -> None:
        self.name = name
        self.buckets: Tuple[float, ...] = tuple(sorted(set(buckets)))
        self._counts = [0] * (len(self.buckets) + 1)  # last = +Inf
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        i = bisect.bisect_left(self.buckets, value)
        with self._lock:
            self._counts[i] += 1
            self._sum += value
            self._count += 1

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            counts = list(self._counts)
            total, n = self._sum, self._count
        cum, out = 0, {}
        for le, c in zip(self.buckets, counts):
            cum += c
            out[le] = cum
        return {"buckets": out, "sum": total, "count": n}


class ObsRegistry:
    """Process-wide histogram registry (counters/gauges/durations stay
    in ``utils.metrics.metrics``; this adds only what it lacks)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._hist: Dict[str, Histogram] = {}

    def histogram(
        self, name: str, buckets: Optional[Iterable[float]] = None
    ) -> Histogram:
        with self._lock:
            h = self._hist.get(name)
            if h is None:
                h = self._hist[name] = Histogram(
                    name, buckets or _LATENCY_BUCKETS
                )
            return h

    def observe(
        self,
        name: str,
        value: float,
        buckets: Optional[Iterable[float]] = None,
    ) -> None:
        self.histogram(name, buckets).observe(value)

    def observe_size(self, name: str, value: float) -> None:
        """Observe into a pow4 size ladder (rows, bytes)."""
        self.histogram(name, _SIZE_BUCKETS).observe(value)

    def snapshot(self) -> Dict[str, Dict]:
        with self._lock:
            hists = list(self._hist.values())
        return {h.name: h.snapshot() for h in hists}

    def reset(self) -> None:
        with self._lock:
            self._hist.clear()


#: the process-wide instance (mirrors utils.metrics.metrics)
obs = ObsRegistry()


_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    return "orienttpu_" + _NAME_RE.sub("_", name)


def _fmt(v: float) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def render_prometheus() -> str:
    """The whole process registry in Prometheus text format 0.0.4."""
    from orientdb_tpu.utils.metrics import metrics

    snap = metrics.snapshot()
    lines: List[str] = []
    for name, v in sorted(snap["counters"].items()):
        m = _prom_name(name) + "_total"
        lines.append(f"# TYPE {m} counter")
        lines.append(f"{m} {_fmt(v)}")
    for name, v in sorted(snap["gauges"].items()):
        m = _prom_name(name)
        lines.append(f"# TYPE {m} gauge")
        lines.append(f"{m} {_fmt(v)}")
    for name, d in sorted(snap["durations"].items()):
        # count/total/max duration stats render as a summary plus a
        # companion _max gauge (Prometheus summaries carry no max)
        m = _prom_name(name)
        lines.append(f"# TYPE {m} summary")
        lines.append(f"{m}_count {_fmt(d['count'])}")
        lines.append(f"{m}_sum {_fmt(d['total_s'])}")
        lines.append(f"# TYPE {m}_max gauge")
        lines.append(f"{m}_max {_fmt(d['max_s'])}")
    for name, h in sorted(obs.snapshot().items()):
        m = _prom_name(name)
        lines.append(f"# TYPE {m} histogram")
        for le, cum in h["buckets"].items():
            lines.append(f'{m}_bucket{{le="{_fmt(le)}"}} {cum}')
        lines.append(f'{m}_bucket{{le="+Inf"}} {h["count"]}')
        lines.append(f"{m}_sum {_fmt(h['sum'])}")
        lines.append(f"{m}_count {h['count']}")
    return "\n".join(lines) + "\n"
