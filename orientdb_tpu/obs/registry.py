"""Histogram metrics + Prometheus-style text exposition.

Extends the process registry (``utils/metrics.py``: counters, gauges,
duration stats — the [E] OProfiler analog) with two things the serving
story needs:

- **histograms** — bucketed distributions (query latency, WAL fsync
  latency, frontier sizes) whose tails survive aggregation, unlike the
  count/total/max duration stats;
- **exposition** — :func:`render_prometheus` renders the ENTIRE
  registry (counters → ``_total`` counters, gauges → gauges, duration
  stats → summaries, histograms → classic cumulative-bucket
  histograms) in the Prometheus text format (version 0.0.4), served by
  the HTTP listener at ``GET /metrics``.

Metric names keep their internal dotted form in code
(``wal.append_s``) and sanitize to Prometheus identifiers on render
(``orienttpu_wal_append_s``).
"""

from __future__ import annotations

import bisect
import re
import threading
from typing import Dict, Iterable, List, Optional, Tuple

#: default latency buckets (seconds): 100 µs … 10 s, roughly 1-2.5-5
_LATENCY_BUCKETS = (
    0.0001,
    0.00025,
    0.0005,
    0.001,
    0.0025,
    0.005,
    0.01,
    0.025,
    0.05,
    0.1,
    0.25,
    0.5,
    1.0,
    2.5,
    5.0,
    10.0,
)

#: default size buckets (rows/bytes): pow4 ladder
_SIZE_BUCKETS = tuple(float(4**i) for i in range(1, 13))


class Histogram:
    """Cumulative-bucket histogram (thread-safe)."""

    __slots__ = ("name", "buckets", "_counts", "_sum", "_count", "_lock")

    def __init__(self, name: str, buckets: Iterable[float]) -> None:
        self.name = name
        self.buckets: Tuple[float, ...] = tuple(sorted(set(buckets)))
        self._counts = [0] * (len(self.buckets) + 1)  # last = +Inf
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        i = bisect.bisect_left(self.buckets, value)
        with self._lock:
            self._counts[i] += 1
            self._sum += value
            self._count += 1

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            counts = list(self._counts)
            total, n = self._sum, self._count
        cum, out = 0, {}
        for le, c in zip(self.buckets, counts):
            cum += c
            out[le] = cum
        return {"buckets": out, "sum": total, "count": n}


class ObsRegistry:
    """Process-wide histogram registry (counters/gauges/durations stay
    in ``utils.metrics.metrics``; this adds only what it lacks)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._hist: Dict[str, Histogram] = {}

    def histogram(
        self, name: str, buckets: Optional[Iterable[float]] = None
    ) -> Histogram:
        with self._lock:
            h = self._hist.get(name)
            if h is None:
                h = self._hist[name] = Histogram(
                    name, buckets or _LATENCY_BUCKETS
                )
            return h

    def observe(
        self,
        name: str,
        value: float,
        buckets: Optional[Iterable[float]] = None,
    ) -> None:
        self.histogram(name, buckets).observe(value)

    def observe_size(self, name: str, value: float) -> None:
        """Observe into a pow4 size ladder (rows, bytes)."""
        self.histogram(name, _SIZE_BUCKETS).observe(value)

    def snapshot(self) -> Dict[str, Dict]:
        with self._lock:
            hists = list(self._hist.values())
        return {h.name: h.snapshot() for h in hists}

    def reset(self) -> None:
        with self._lock:
            self._hist.clear()


#: the process-wide instance (mirrors utils.metrics.metrics)
obs = ObsRegistry()


_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    return "orienttpu_" + _NAME_RE.sub("_", name)


def _fmt(v: float) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


#: cache families whose effectiveness renders as a derived hit-ratio
#: gauge (hits / (hits + misses)): the command (result) cache and the
#: TPU engine's compiled-plan cache (its hit/miss counters ARE the
#: compile-cache behavior — a miss records + compiles a new plan)
_CACHE_RATIO_FAMILIES = ("command_cache", "plan_cache")


def derived_gauges(counters: Dict[str, float]) -> Dict[str, float]:
    """Gauges computed FROM a counter snapshot at render time — cache
    hit ratios, so ``/metrics`` (and ``/cluster/metrics``, per member)
    shows cache effectiveness directly instead of leaving the division
    to every dashboard."""
    out: Dict[str, float] = {}
    for fam in _CACHE_RATIO_FAMILIES:
        hits = counters.get(f"{fam}.hit", 0)
        misses = counters.get(f"{fam}.miss", 0)
        if hits or misses:
            out[f"{fam}.hit_ratio"] = round(hits / (hits + misses), 6)
    return out


def snapshot_all() -> Dict[str, Dict]:
    """One combined snapshot of BOTH process registries (counters /
    gauges / durations from ``utils.metrics``, histograms from here)
    plus the per-fingerprint query-stats table — the unit
    ``/metrics?format=json`` serves and ``/cluster/metrics`` fans in
    per member. Memory/process telemetry gauges (obs/profile) refresh
    at scrape time, right before the snapshot is taken."""
    from orientdb_tpu.obs.alerts import engine
    from orientdb_tpu.obs.profile import run_gauge_providers
    from orientdb_tpu.obs.stats import stats
    from orientdb_tpu.utils.metrics import metrics

    run_gauge_providers()
    snap = metrics.snapshot()
    snap["histograms"] = obs.snapshot()
    snap["query_stats"] = stats.export()
    # per-rule alert state (obs/alerts): READ-only at scrape time —
    # rule evaluation happens at watchdog tick, never here
    snap["alerts"] = engine.export()
    return snap


def _render_into(lines: List[str], snap: Dict) -> None:
    """Render one process snapshot (the single-member exposition; the
    member-labeled fan-in lives in :func:`render_prometheus_multi`,
    which must iterate families OUTER and members inner and therefore
    cannot reuse this per-snapshot walk)."""

    def header(m: str, typ: str) -> None:
        lines.append(f"# HELP {m} orientdb-tpu metric {m}")
        lines.append(f"# TYPE {m} {typ}")

    def sample(m: str, v, extra: str = "") -> None:
        lines.append(f"{m}{{{extra}}} {v}" if extra else f"{m} {v}")

    counters = snap.get("counters", {})
    for name, v in sorted(counters.items()):
        m = _prom_name(name) + "_total"
        header(m, "counter")
        sample(m, _fmt(v))
    gauges = dict(snap.get("gauges", {}))
    gauges.update(derived_gauges(counters))
    for name, v in sorted(gauges.items()):
        m = _prom_name(name)
        header(m, "gauge")
        sample(m, _fmt(v))
    for name, d in sorted(snap.get("durations", {}).items()):
        # count/total/max duration stats render as a summary plus a
        # companion _max gauge (Prometheus summaries carry no max)
        m = _prom_name(name)
        header(m, "summary")
        sample(f"{m}_count", _fmt(d["count"]))
        sample(f"{m}_sum", _fmt(d["total_s"]))
        header(f"{m}_max", "gauge")
        sample(f"{m}_max", _fmt(d["max_s"]))
    for name, h in sorted(snap.get("histograms", {}).items()):
        m = _prom_name(name)
        header(m, "histogram")
        # bucket keys survive a JSON round trip as strings (the
        # /cluster/metrics fan-in path): normalize + sort numerically
        buckets = sorted(
            ((float(le), cum) for le, cum in h["buckets"].items()),
            key=lambda kv: kv[0],
        )
        for le, cum in buckets:
            sample(f"{m}_bucket", cum, extra=f'le="{_fmt(le)}"')
        sample(f"{m}_bucket", h["count"], extra='le="+Inf"')
        sample(f"{m}_sum", _fmt(h["sum"]))
        sample(f"{m}_count", h["count"])
    qs = snap.get("query_stats")
    if qs:
        from orientdb_tpu.obs.stats import render_stats_into

        render_stats_into(lines, {None: qs})
    al = snap.get("alerts")
    if al:
        from orientdb_tpu.obs.alerts import render_alerts_into

        render_alerts_into(lines, {None: al})


def render_prometheus() -> str:
    """The whole process registry in Prometheus text format 0.0.4."""
    lines: List[str] = []
    _render_into(lines, snapshot_all())
    return "\n".join(lines) + "\n"


def render_prometheus_multi(snapshots: Dict[str, Dict]) -> str:
    """Fan-in exposition: each member's registry snapshot (the
    ``snapshot_all`` shape, possibly JSON-round-tripped) merged into
    ONE text document, every sample labeled ``member="<name>"``.

    Families iterate OUTER and members inner: the exposition grammar
    requires all samples of one metric family to form a single group
    (HELP/TYPE first, then every series) — interleaving members by
    whole snapshots would scatter a family across the document. The
    member label keeps merged series unique."""
    lines: List[str] = []
    members = sorted(snapshots)

    def fam(kind: str) -> List[str]:
        names: set = set()
        for m in members:
            names.update(snapshots[m].get(kind, {}))
        return sorted(names)

    def header(m: str, typ: str) -> None:
        lines.append(f"# HELP {m} orientdb-tpu metric {m}")
        lines.append(f"# TYPE {m} {typ}")

    for name in fam("counters"):
        m = _prom_name(name) + "_total"
        header(m, "counter")
        for mem in members:
            v = snapshots[mem].get("counters", {}).get(name)
            if v is not None:
                lines.append(f'{m}{{member="{mem}"}} {_fmt(v)}')
    gauge_snaps = {
        mem: {
            **snapshots[mem].get("gauges", {}),
            **derived_gauges(snapshots[mem].get("counters", {})),
        }
        for mem in members
    }
    for name in sorted({n for g in gauge_snaps.values() for n in g}):
        m = _prom_name(name)
        header(m, "gauge")
        for mem in members:
            v = gauge_snaps[mem].get(name)
            if v is not None:
                lines.append(f'{m}{{member="{mem}"}} {_fmt(v)}')
    for name in fam("durations"):
        m = _prom_name(name)
        header(m, "summary")
        for mem in members:
            d = snapshots[mem].get("durations", {}).get(name)
            if d is not None:
                lines.append(
                    f'{m}_count{{member="{mem}"}} {_fmt(d["count"])}'
                )
                lines.append(
                    f'{m}_sum{{member="{mem}"}} {_fmt(d["total_s"])}'
                )
        header(f"{m}_max", "gauge")
        for mem in members:
            d = snapshots[mem].get("durations", {}).get(name)
            if d is not None:
                lines.append(
                    f'{m}_max{{member="{mem}"}} {_fmt(d["max_s"])}'
                )
    for name in fam("histograms"):
        m = _prom_name(name)
        header(m, "histogram")
        for mem in members:
            h = snapshots[mem].get("histograms", {}).get(name)
            if h is None:
                continue
            buckets = sorted(
                ((float(le), cum) for le, cum in h["buckets"].items()),
                key=lambda kv: kv[0],
            )
            for le, cum in buckets:
                lines.append(
                    f'{m}_bucket{{le="{_fmt(le)}",member="{mem}"}} {cum}'
                )
            lines.append(
                f'{m}_bucket{{le="+Inf",member="{mem}"}} {h["count"]}'
            )
            lines.append(f'{m}_sum{{member="{mem}"}} {_fmt(h["sum"])}')
            lines.append(f'{m}_count{{member="{mem}"}} {h["count"]}')
    # per-fingerprint query stats, fanned in with BOTH labels — the
    # same fingerprint id labels every member's series, so a shape's
    # fleet-wide cost reads off one family
    if any(snapshots[m].get("query_stats") for m in members):
        from orientdb_tpu.obs.stats import render_stats_into

        render_stats_into(
            lines,
            {m: snapshots[m].get("query_stats") or {} for m in members},
        )
    # per-rule alert state, fanned in with BOTH labels — one family
    # answers "which member is firing which rule" across the fleet
    if any(snapshots[m].get("alerts") for m in members):
        from orientdb_tpu.obs.alerts import render_alerts_into

        render_alerts_into(
            lines,
            {m: snapshots[m].get("alerts") or {} for m in members},
        )
    return "\n".join(lines) + "\n"
