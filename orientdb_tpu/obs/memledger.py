"""Process-wide device-memory ledger: attributed HBM accounting.

The delta plane (PR 15) and the tier plane (PR 16) both allocate, pin,
grow, and defer-free device buffers on the serving path — yet nothing
could answer "what is in HBM right now, who owns it, and is anything
leaking?". This module is that answer: every device allocation on the
serving path registers an attributed entry (owner kind + id, byte
size, creation trace id, pin state), and three consumers sit on top:

- **reconciliation** (:meth:`MemLedger.reconcile`, span
  ``memledger.reconcile``): diff ledger totals against
  ``jax.live_arrays()`` and classify the residue — live-but-untracked
  bytes are an instrumentation gap (reported, bounded by
  ``memledger_tolerance``); tracked-but-dead persistent entries are
  leak candidates; dead TRANSIENT entries (result pages, speculative
  prefetch pages) self-heal out of the ledger as reclaimed bytes.
- **epoch-leak detection**: every ``GraphSnapshot.retain`` records a
  lease (ts, trace id, epoch); a lease still held past
  ``memledger_leak_s`` is stale — the ``hbm_epoch_leak`` alert rule
  (obs/alerts) fires with the retaining lease's trace id as exemplar.
  ``hbm_headroom`` fires when attributed bytes approach
  ``tier_hbm_cap_bytes``.
- **surfaces**: scrape-time ``hbm.ledger_*`` / ``hbm.owner.*`` gauges
  ride ``snapshot_all()`` into ``/metrics`` and the member-labeled
  ``/cluster/metrics`` fan-in; ``GET /debug/memory`` (admin-only),
  the debug bundle's ``memory`` section, console
  ``MEMORY [OWNERS|WATERMARK]``, and a per-round ``memory``
  bench-evidence record whose peak-HBM leaf ``tools/perfdiff.py``
  gates round over round.

Owner taxonomy (fixed — the per-kind gauges and rollups key on it):

========== ==============================================================
kind       allocation site
========== ==============================================================
snapshot        base CSR / column arrays (``DeviceGraph._put``,
                ``apply_patches`` overlays re-register in place)
tier_pool       tiered hot-pool pages + block indexes (``t:*`` keys;
                storage/tiering grow/load/evict re-register)
delta_slab      overlay bucket-index tables (``bk:*`` keys,
                storage/deltas)
param_ring      device-resident parameter ring slots
                (``tpu_engine.ParamRing.stage``)
prefetched_page speculatively prefetched result pages (transient)
plan_const      per-class id sets baked into plan executables
                (``DeviceGraph.class_ids``)
result_page     elected result pages awaiting host copy (transient)
========== ==============================================================

Registration is an upsert keyed ``(kind, owner, key)`` — re-puts
(patches, pool growth) refresh bytes in place. Byte totals are always
exact; only the *trace-id capture* rides the sampled fast path
(``memledger_sample_rate``), which is what holds the hot-path overhead
under the established <1.35x guard. ``memledger_enabled=False``
no-ops every call.
"""

from __future__ import annotations

import threading
import time
import weakref
from collections import deque
from typing import Dict, List, Optional, Tuple

from orientdb_tpu.utils.config import config
from orientdb_tpu.utils.metrics import metrics

#: the fixed owner-kind taxonomy (see module docstring)
OWNER_KINDS: Tuple[str, ...] = (
    "snapshot",
    "tier_pool",
    "delta_slab",
    "param_ring",
    "prefetched_page",
    "plan_const",
    "result_page",
)

#: kinds whose entries die without an unregister hook — result and
#: prefetch pages between dispatches, ring slots when their lane
#: retires. A dead transient entry is RECLAIMED (pruned by reconcile),
#: never a leak candidate; the kinds with explicit drop hooks
#: (snapshot/tier_pool/delta_slab/plan_const via _free_device) are the
#: ones whose dead entries mean something went wrong.
TRANSIENT_KINDS = frozenset({"result_page", "prefetched_page", "param_ring"})


def _nbytes(arr) -> int:
    try:
        return int(getattr(arr, "nbytes", 0))
    except Exception:
        return 0


class _Entry:
    """One attributed device allocation."""

    __slots__ = (
        "kind",
        "owner",
        "key",
        "nbytes",
        "ts",
        "trace_id",
        "pinned",
        "transient",
        "ref",
        "arr_id",
    )

    def __init__(self, kind, owner, key, nbytes, ts, trace_id, pinned, transient, ref, arr_id):
        self.kind = kind
        self.owner = owner
        self.key = key
        self.nbytes = nbytes
        self.ts = ts
        self.trace_id = trace_id
        self.pinned = pinned
        self.transient = transient
        self.ref = ref  # weakref to the jax array when weakref-able
        self.arr_id = arr_id  # id() fallback identity

    def alive(self, live_ids: Dict[int, int]) -> bool:
        """Is the registered array still device-live? Weakref identity
        when available (immune to id() recycling); else id+size match
        against the live set."""
        if self.ref is not None:
            a = self.ref()
            if a is None:
                return False
            try:
                if a.is_deleted():
                    return False
            except Exception:
                pass
            return True
        return live_ids.get(self.arr_id) == self.nbytes


class MemLedger:
    """The process-wide ledger singleton (module-level ``memledger``)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._entries: Dict[Tuple[str, str, str], _Entry] = {}
        self._totals: Dict[str, int] = {k: 0 for k in OWNER_KINDS}
        self._pinned_total = 0  # maintained incrementally (tick-path O(1))
        self._peaks: Dict[str, int] = {k: 0 for k in OWNER_KINDS}
        self._peak_total = 0
        #: id(snapshot) -> deque of lease dicts (ts, trace_id, epoch)
        self._leases: Dict[int, deque] = {}
        self._lease_refs: Dict[int, object] = {}  # id -> weakref(snap)
        #: bounded (ts, total_bytes) ring, throttled ~4 Hz
        self._watermarks: deque = deque()
        self._wm_last = 0.0
        self._refusal_counts: Dict[str, int] = {}
        self._last_refusal: Optional[Dict] = None
        self._events: deque = deque(maxlen=32)
        self._last_reconcile: Optional[Dict] = None

    # -- registration (the hot path) ----------------------------------------

    def register(
        self,
        kind: str,
        owner: str,
        key: str,
        arr=None,
        nbytes: Optional[int] = None,
        pinned: bool = False,
    ) -> None:
        """Upsert one attributed allocation. Bytes are exact on every
        call; the trace-id capture samples (``memledger_sample_rate``)
        so full-rate registration stays off the dispatch critical
        path's profile."""
        if not config.memledger_enabled:
            return
        nb = _nbytes(arr) if nbytes is None else int(nbytes)
        tid = None
        rate = config.memledger_sample_rate
        if rate > 0:
            from orientdb_tpu.obs.stats import sampled
            from orientdb_tpu.obs.trace import current_trace_id

            if sampled(rate):
                tid = current_trace_id()
        ref = None
        arr_id = 0
        if arr is not None:
            arr_id = id(arr)
            try:
                ref = weakref.ref(arr)
            except TypeError:
                ref = None
        now = time.time()
        ident = (kind, owner, key)
        with self._lock:
            old = self._entries.get(ident)
            if old is not None:
                self._totals[kind] -= old.nbytes
                if old.pinned:
                    self._pinned_total -= old.nbytes
                if tid is None:
                    tid = old.trace_id
            self._entries[ident] = _Entry(
                kind, owner, key, nb, now, tid, pinned,
                kind in TRANSIENT_KINDS, ref, arr_id,
            )
            self._totals[kind] = self._totals.get(kind, 0) + nb
            if pinned:
                self._pinned_total += nb
            self._note_watermark_locked(now)

    def unregister(self, kind: str, owner: str, key: str) -> None:
        with self._lock:
            e = self._entries.pop((kind, owner, key), None)
            if e is not None:
                self._totals[e.kind] -= e.nbytes
                if e.pinned:
                    self._pinned_total -= e.nbytes
                self._note_watermark_locked(time.time())

    def drop_owner(self, kind: str, owner: str) -> int:
        """Drop every entry of one owner (a freed DeviceGraph, an
        evicted pool). Returns the bytes released."""
        freed = 0
        with self._lock:
            for ident in [
                i for i, e in self._entries.items()
                if e.kind == kind and e.owner == owner
            ]:
                e = self._entries.pop(ident)
                freed += e.nbytes
                if e.pinned:
                    self._pinned_total -= e.nbytes
            if freed:
                self._totals[kind] -= freed
                self._note_watermark_locked(time.time())
        return freed

    def drop_graph(self, dg) -> int:
        """Free-time hook (``GraphSnapshot._free_device``): every kind
        attributed through this DeviceGraph's owner id goes at once."""
        owner = getattr(dg, "_ledger_owner", None)
        if owner is None:
            return 0
        freed = 0
        for kind in ("snapshot", "tier_pool", "delta_slab", "plan_const"):
            freed += self.drop_owner(kind, owner)
        return freed

    def register_graph_array(self, dg, key: str, arr) -> None:
        """Classify + register one ``DeviceGraph`` array by its store
        key (the ``memory_report`` prefix taxonomy): ``t:*`` pages are
        the tier pool, ``bk:*`` tables are the delta overlay's bucket
        index, everything else is the snapshot itself."""
        if not config.memledger_enabled:
            return
        owner = getattr(dg, "_ledger_owner", None)
        if owner is None:
            owner = f"snap:{id(getattr(dg, 'snap', dg)):x}"
        if key.startswith("t:"):
            kind = "tier_pool"
        elif key.startswith("bk:"):
            kind = "delta_slab"
        else:
            kind = "snapshot"
        self.register(kind, owner, key, arr=arr)

    # -- epoch leases --------------------------------------------------------

    def lease_acquired(self, snap) -> None:
        """One ``retain()``/``try_retain()`` pin recorded with its
        trace id — the exemplar an ``hbm_epoch_leak`` alert joins."""
        if not config.memledger_enabled:
            return
        from orientdb_tpu.obs.trace import current_trace_id

        sid = id(snap)
        lease = {
            "ts": time.time(),
            "trace_id": current_trace_id(),
            "epoch": int(getattr(snap, "epoch", 0) or 0),
        }
        with self._lock:
            dq = self._leases.get(sid)
            if dq is None:
                dq = self._leases[sid] = deque()
                try:
                    self._lease_refs[sid] = weakref.ref(
                        snap, lambda _r, s=sid: self._forget_snap(s)
                    )
                except TypeError:
                    self._lease_refs[sid] = None
            dq.append(lease)

    def lease_released(self, snap) -> None:
        """Drop the OLDEST outstanding lease (FIFO — dispatches retire
        roughly in admission order; the exact pairing does not matter
        for leak detection, only the outstanding count and ages do)."""
        if not config.memledger_enabled:
            return
        sid = id(snap)
        with self._lock:
            dq = self._leases.get(sid)
            if dq:
                dq.popleft()
            if not dq:
                self._leases.pop(sid, None)
                self._lease_refs.pop(sid, None)

    def _forget_snap(self, sid: int) -> None:
        with self._lock:
            self._leases.pop(sid, None)
            self._lease_refs.pop(sid, None)

    def stale_leases(self) -> List[Dict]:
        """Leases outstanding longer than ``memledger_leak_s`` — a
        snapshot epoch whose refcount stays nonzero that long with no
        dispatch retiring it is the epoch-leak signature (a crashed
        dispatch path that skipped ``release()``, a lost lane)."""
        leak_s = config.memledger_leak_s
        if leak_s <= 0:
            return []
        now = time.time()
        out: List[Dict] = []
        with self._lock:
            for sid, dq in self._leases.items():
                # each deque is append-ordered by ts: the first lease
                # younger than the threshold ends the scan (keeps the
                # watchdog-tick cost O(stale), not O(outstanding))
                for lease in dq:
                    age = now - lease["ts"]
                    if age <= leak_s:
                        break
                    out.append(
                        {
                            "epoch": lease["epoch"],
                            "age_s": round(age, 3),
                            "trace_id": lease["trace_id"],
                            "outstanding": len(dq),
                        }
                    )
        return out

    def lease_count(self) -> int:
        with self._lock:
            return sum(len(dq) for dq in self._leases.values())

    # -- refusals (satellite: tiered+mesh / tiered+overlay telemetry) -------

    def note_refusal(self, reason: str, detail: str) -> None:
        """Count one tier-composition refusal (``tier.refusals`` total
        + per-reason ``tier.refusals.<reason>``) and remember the last
        one for ``/debug/memory`` — operators see WHY a snapshot did
        not tier, not just a raised ValueError in someone's log."""
        metrics.incr("tier.refusals")
        metrics.incr(f"tier.refusals.{reason}")
        with self._lock:
            self._refusal_counts[reason] = (
                self._refusal_counts.get(reason, 0) + 1
            )
            self._last_refusal = {
                "reason": reason,
                "detail": detail[:200],
                "ts": time.time(),
            }

    def note_event(self, kind: str, detail: str) -> None:
        """Breadcrumb ring for memory-plane lifecycle events (epoch
        compaction swaps, pool growth) shown in ``/debug/memory``."""
        with self._lock:
            self._events.append(
                {"kind": kind, "detail": detail[:200], "ts": time.time()}
            )

    # -- rollups / watermarks ------------------------------------------------

    def _note_watermark_locked(self, now: float) -> None:
        total = sum(self._totals.values())
        if total > self._peak_total:
            self._peak_total = total
        for k, v in self._totals.items():
            if v > self._peaks.get(k, 0):
                self._peaks[k] = v
        if now - self._wm_last >= 0.25:
            self._wm_last = now
            self._watermarks.append((round(now, 3), total))
            cap = max(int(config.memledger_watermark_capacity), 1)
            while len(self._watermarks) > cap:
                self._watermarks.popleft()

    def totals(self) -> Dict[str, int]:
        with self._lock:
            return {k: self._totals.get(k, 0) for k in OWNER_KINDS}

    def total_bytes(self) -> int:
        with self._lock:
            return sum(self._totals.values())

    def entry_count(self) -> int:
        with self._lock:
            return len(self._entries)

    def pinned_bytes(self) -> int:
        with self._lock:
            return self._pinned_total

    def telemetry(self) -> Dict:
        """Every scrape-time number in ONE lock acquisition — the
        watchdog ticks ``snapshot_all()`` at up to 50 Hz in tests, so
        the per-tick provider must not iterate entries or take the
        lock once per gauge."""
        with self._lock:
            return {
                "totals": {k: self._totals.get(k, 0) for k in OWNER_KINDS},
                "total": sum(self._totals.values()),
                "entries": len(self._entries),
                "pinned": self._pinned_total,
                "peak": self._peak_total,
            }

    def peaks(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._peaks)

    def peak_total(self) -> int:
        with self._lock:
            return self._peak_total

    def watermarks(self) -> List[Tuple[float, int]]:
        with self._lock:
            return list(self._watermarks)

    def owners(self) -> Dict[str, Dict]:
        """Per-kind rollup: bytes, entries, owners, oldest entry age —
        the ``/debug/memory`` OWNERS table."""
        now = time.time()
        with self._lock:
            out: Dict[str, Dict] = {
                k: {"bytes": 0, "entries": 0, "owners": set(), "oldest_s": 0.0}
                for k in OWNER_KINDS
            }
            for e in self._entries.values():
                row = out[e.kind]
                row["bytes"] += e.nbytes
                row["entries"] += 1
                row["owners"].add(e.owner)
                row["oldest_s"] = max(row["oldest_s"], now - e.ts)
        for row in out.values():
            row["owners"] = len(row["owners"])
            row["oldest_s"] = round(row["oldest_s"], 3)
        return out

    # -- reconciliation ------------------------------------------------------

    def reconcile(self) -> Dict:
        """Diff the ledger against ``jax.live_arrays()``:

        - ``untracked_bytes`` — live on device, not in the ledger: an
          instrumentation gap (reported; ``ok`` while it stays under
          ``memledger_tolerance`` × live bytes);
        - ``alias_bytes`` — live arrays that are the per-shard inner
          buffers (``Shard.data``) of a MATCHED entry's array:
          ``jax.live_arrays()`` enumerates both the outer ArrayImpl
          and its shard buffers, so without this credit every tracked
          byte would double-count as untracked;
        - ``tracked_dead`` — persistent entries whose array died
          without an unregister: leak candidates, one row each;
        - ``reclaimed_bytes`` — dead TRANSIENT entries (result /
          prefetch pages) pruned here, the ledger self-healing.
        """
        from orientdb_tpu.obs.trace import span

        with span("memledger.reconcile"):
            live_total = 0
            live_ids: Dict[int, int] = {}
            try:
                import jax

                for a in jax.live_arrays():
                    try:
                        if a.is_deleted():
                            continue
                    except Exception:
                        pass
                    nb = _nbytes(a)
                    live_ids[id(a)] = nb
                    live_total += nb
            except Exception:
                pass
            matched = 0
            reclaimed = 0
            alias_bytes = 0
            seen_alias: set = set()
            tracked_dead: List[Dict] = []
            with self._lock:
                for ident in list(self._entries):
                    e = self._entries[ident]
                    if e.alive(live_ids):
                        matched += e.nbytes
                        a = e.ref() if e.ref is not None else None
                        if a is not None:
                            try:
                                for sh in a.addressable_shards:
                                    d = sh.data
                                    did = id(d)
                                    if (
                                        d is not None
                                        and did != id(a)
                                        and did in live_ids
                                        and did not in seen_alias
                                    ):
                                        seen_alias.add(did)
                                        alias_bytes += live_ids[did]
                            except Exception:
                                pass
                    elif e.transient:
                        reclaimed += e.nbytes
                        del self._entries[ident]
                        self._totals[e.kind] -= e.nbytes
                        if e.pinned:
                            self._pinned_total -= e.nbytes
                    else:
                        tracked_dead.append(
                            {
                                "kind": e.kind,
                                "owner": e.owner,
                                "key": e.key,
                                "bytes": e.nbytes,
                                "age_s": round(time.time() - e.ts, 3),
                                "trace_id": e.trace_id,
                            }
                        )
            untracked = max(0, live_total - matched - alias_bytes)
            tol = config.memledger_tolerance
            ok = (
                untracked <= live_total * tol
                if live_total > 0
                else True
            )
            report = {
                "live_bytes": live_total,
                "ledger_bytes": self.total_bytes(),
                "matched_bytes": matched,
                "alias_bytes": alias_bytes,
                "untracked_bytes": untracked,
                "reclaimed_bytes": reclaimed,
                "tracked_dead_bytes": sum(
                    r["bytes"] for r in tracked_dead
                ),
                "tracked_dead": tracked_dead[:16],
                "tolerance": tol,
                "ok": ok,
                "ts": round(time.time(), 3),
            }
            with self._lock:
                self._last_reconcile = report
            return report

    # -- surfaces ------------------------------------------------------------

    def report(self, reconcile: bool = True) -> Dict:
        """The ``GET /debug/memory`` / debug-bundle document."""
        rec = self.reconcile() if reconcile else None
        with self._lock:
            last_rec = self._last_reconcile
            refusals = dict(self._refusal_counts)
            last_refusal = self._last_refusal
            events = list(self._events)
            leases = sum(len(dq) for dq in self._leases.values())
        return {
            "owners": self.owners(),
            "totals": self.totals(),
            "total_bytes": self.total_bytes(),
            "peak_bytes": self.peak_total(),
            "peak_by_owner": self.peaks(),
            "pinned_bytes": self.pinned_bytes(),
            "entries": self.entry_count(),
            "watermarks": [
                {"ts": ts, "bytes": b} for ts, b in self.watermarks()
            ],
            "reconcile": rec if rec is not None else last_rec,
            "leases": {
                "outstanding": leases,
                "stale": self.stale_leases(),
            },
            "refusals": {
                "counts": refusals,
                "last": last_refusal,
            },
            "events": events,
        }

    def reset(self) -> None:
        """Test hook: forget everything (entries, leases, peaks,
        refusals) — the singleton survives across tests."""
        with self._lock:
            self._entries.clear()
            self._totals = {k: 0 for k in OWNER_KINDS}
            self._pinned_total = 0
            self._peaks = {k: 0 for k in OWNER_KINDS}
            self._peak_total = 0
            self._leases.clear()
            self._lease_refs.clear()
            self._watermarks.clear()
            self._wm_last = 0.0
            self._refusal_counts.clear()
            self._last_refusal = None
            self._events.clear()
            self._last_reconcile = None


#: the process-wide ledger
memledger = MemLedger()


def ledger_telemetry() -> None:
    """Scrape-time gauge provider (rides ``registry.snapshot_all`` →
    ``/metrics`` as ``orienttpu_hbm_*`` and the member-labeled
    ``/cluster/metrics`` fan-in)."""
    if not config.memledger_enabled:
        return
    t = memledger.telemetry()
    metrics.gauge("hbm.ledger_bytes", float(t["total"]))
    metrics.gauge("hbm.ledger_entries", float(t["entries"]))
    metrics.gauge("hbm.ledger_pinned_bytes", float(t["pinned"]))
    metrics.gauge("hbm.ledger_peak_bytes", float(t["peak"]))
    metrics.gauge("hbm.leak_leases", float(len(memledger.stale_leases())))
    for kind in OWNER_KINDS:
        metrics.gauge(f"hbm.owner.{kind}_bytes", float(t["totals"][kind]))


def _install() -> None:
    from orientdb_tpu.obs.profile import register_gauge_provider

    register_gauge_provider(ledger_telemetry)


_install()


def bench_memory_summary() -> Dict:
    """One per-round ``memory`` evidence record (the watchdog block's
    twin): peak/steady bytes per owner, reconciliation residue, leak
    count. ``tools/perfdiff.py`` gates the peak-HBM leaves."""
    rec = memledger.reconcile()
    return {
        "peak_bytes": memledger.peak_total(),
        "peak_by_owner": memledger.peaks(),
        "steady_bytes": memledger.total_bytes(),
        "steady_by_owner": memledger.totals(),
        "pinned_bytes": memledger.pinned_bytes(),
        "entries": memledger.entry_count(),
        "reconcile_ok": rec["ok"],
        "untracked_bytes": rec["untracked_bytes"],
        "tracked_dead_bytes": rec["tracked_dead_bytes"],
        "reclaimed_bytes": rec["reclaimed_bytes"],
        "leak_count": len(memledger.stale_leases()),
        "lease_outstanding": memledger.lease_count(),
    }
