"""Observability subsystem: tracing, metric exposition, slow-query log,
and crash-safe evidence streaming.

The north star is a *measured* number (50× MATCH throughput at
result-set parity) served at production scale — proving and diagnosing
both claims needs more than `utils/metrics.py`'s counters:

- :mod:`orientdb_tpu.obs.trace` — lightweight structured spans with
  per-query trace IDs, threaded through the step executor, the compiled
  TPU engine's stage boundaries, tx commit, WAL append, and replication
  apply;
- :mod:`orientdb_tpu.obs.registry` — histogram metrics plus a
  Prometheus-style text exposition of the whole process registry
  (served at ``GET /metrics``);
- :mod:`orientdb_tpu.obs.slowlog` — bounded ring of queries slower than
  the configured threshold, surfaced in the console (``SLOWLOG``);
- :mod:`orientdb_tpu.obs.evidence` — append-only fsync'd JSONL sink so
  a timed-out bench/dryrun still leaves every completed block's numbers
  on disk (round 5 shipped rc:124 with NO perf evidence because the
  detail artifact wrote only at process exit);
- :mod:`orientdb_tpu.obs.propagation` — cross-node trace propagation:
  context injection/extraction for HTTP headers, binary-protocol
  frames, and WAL entries, so forwarded writes, 2PC rounds, and
  replication applies assemble into ONE trace;
- :mod:`orientdb_tpu.obs.cluster_view` — the fleet aggregation plane:
  ``GET /cluster/health`` and the member-labeled ``GET
  /cluster/metrics`` fan-in;
- :mod:`orientdb_tpu.obs.bundle` — the flight-recorder debug bundle
  (``GET /debug/bundle``, console ``DIAG``): traces assembled by
  trace id, slowlog, metrics snapshot, in-doubt 2PC state;
- :mod:`orientdb_tpu.obs.promlint` — Prometheus text-exposition
  grammar lint, run by tier-1 tests over the full ``/metrics`` and
  ``/cluster/metrics`` output;
- :mod:`orientdb_tpu.obs.stats` — the query-statistics plane
  (pg_stat_statements analog): normalized query fingerprints with
  cumulative per-shape cost (calls, latency, device-ms, compile vs
  cache-hit, bytes), served at ``GET /stats/queries`` and fanned into
  ``/cluster/metrics``;
- :mod:`orientdb_tpu.obs.profile` — continuous profiling: finished
  span trees fold into per-stage self-time profiles (``GET
  /stats/profile``), plus scrape-time memory/process telemetry gauges;
- :mod:`orientdb_tpu.obs.spanlint` — span-name catalog lint: every
  literal ``span(...)`` name must appear in ``SPAN_CATALOG``, so a
  typo cannot silently split profiles or break cross-node trace joins;
- :mod:`orientdb_tpu.obs.alerts` — the SLO alerting plane: a
  declarative rule catalog (replication lag, open breakers, in-doubt
  2PC age, CDC backlog, WAL/RSS/HBM watermarks, recompile storms,
  per-fingerprint latency regression vs an online EWMA+MAD baseline,
  two-window error-budget burn) driven pending → firing → resolved
  with exemplar trace ids, served at ``GET /alerts``;
- :mod:`orientdb_tpu.obs.watchdog` — the ``HealthWatchdog`` thread
  (started/stopped with ``Server``) that ticks the alert engine —
  evaluation never rides the query hot path;
- :mod:`orientdb_tpu.obs.timeline` — the dispatch flight recorder: a
  bounded ring of per-dispatch lifecycle timelines (every dispatch
  path: single, group, coalesce lane, sharded mesh, oracle) with an
  overlap-accounting pass (device-idle fraction, transfer-hidden
  bytes, lane window vs service, ring upload savings), Chrome-trace/
  Perfetto export at ``GET /debug/timeline``, and scrape-time
  ``orienttpu_overlap_*`` gauges.
"""

from orientdb_tpu.obs.alerts import RULE_CATALOG, render_alerts_prometheus
from orientdb_tpu.obs.alerts import engine as alert_engine
from orientdb_tpu.obs.bundle import assemble_traces, debug_bundle
from orientdb_tpu.obs.evidence import EvidenceSink, read_evidence
from orientdb_tpu.obs.profile import (
    profiler,
    register_gauge_provider,
    register_server_telemetry,
)
from orientdb_tpu.obs.promlint import lint_exposition
from orientdb_tpu.obs.spanlint import SPAN_CATALOG, lint_spans
from orientdb_tpu.obs.stats import (
    QueryStats,
    fingerprint,
    fingerprint_cached,
    render_stats_prometheus,
)
from orientdb_tpu.obs.stats import stats as query_stats
from orientdb_tpu.obs.propagation import (
    baggage,
    continue_trace,
    current_context,
    extract_headers,
    inject_frame,
    inject_headers,
)
from orientdb_tpu.obs.registry import (
    obs,
    render_prometheus,
    render_prometheus_multi,
    snapshot_all,
)
from orientdb_tpu.obs.slowlog import slowlog
from orientdb_tpu.obs.timeline import FlightRecorder
from orientdb_tpu.obs.timeline import recorder as flight_recorder
from orientdb_tpu.obs.trace import (
    current_span,
    current_trace_id,
    span,
    tracer,
)

__all__ = [
    "EvidenceSink",
    "FlightRecorder",
    "flight_recorder",
    "QueryStats",
    "RULE_CATALOG",
    "SPAN_CATALOG",
    "alert_engine",
    "render_alerts_prometheus",
    "fingerprint",
    "fingerprint_cached",
    "lint_spans",
    "profiler",
    "register_gauge_provider",
    "register_server_telemetry",
    "render_stats_prometheus",
    "query_stats",
    "read_evidence",
    "obs",
    "render_prometheus",
    "render_prometheus_multi",
    "snapshot_all",
    "slowlog",
    "span",
    "tracer",
    "current_trace_id",
    "current_span",
    "current_context",
    "continue_trace",
    "baggage",
    "inject_headers",
    "inject_frame",
    "extract_headers",
    "assemble_traces",
    "debug_bundle",
    "lint_exposition",
]
