"""Observability subsystem: tracing, metric exposition, slow-query log,
and crash-safe evidence streaming.

The north star is a *measured* number (50× MATCH throughput at
result-set parity) served at production scale — proving and diagnosing
both claims needs more than `utils/metrics.py`'s counters:

- :mod:`orientdb_tpu.obs.trace` — lightweight structured spans with
  per-query trace IDs, threaded through the step executor, the compiled
  TPU engine's stage boundaries, tx commit, WAL append, and replication
  apply;
- :mod:`orientdb_tpu.obs.registry` — histogram metrics plus a
  Prometheus-style text exposition of the whole process registry
  (served at ``GET /metrics``);
- :mod:`orientdb_tpu.obs.slowlog` — bounded ring of queries slower than
  the configured threshold, surfaced in the console (``SLOWLOG``);
- :mod:`orientdb_tpu.obs.evidence` — append-only fsync'd JSONL sink so
  a timed-out bench/dryrun still leaves every completed block's numbers
  on disk (round 5 shipped rc:124 with NO perf evidence because the
  detail artifact wrote only at process exit).
"""

from orientdb_tpu.obs.evidence import EvidenceSink, read_evidence
from orientdb_tpu.obs.registry import obs, render_prometheus
from orientdb_tpu.obs.slowlog import slowlog
from orientdb_tpu.obs.trace import current_trace_id, span, tracer

__all__ = [
    "EvidenceSink",
    "read_evidence",
    "obs",
    "render_prometheus",
    "slowlog",
    "span",
    "tracer",
    "current_trace_id",
]
