"""Structured tracing: per-query trace IDs and lightweight spans.

Analog of the reference's per-command profiling chain ([E]
OProfiler.startChrono/stopChrono around command execution; SURVEY.md
§5.1), redesigned as explicit spans: every query gets a trace id, and
the layers it crosses (engine dispatch, TPU-engine stages, tx commit,
WAL append, replication apply) each contribute a named span with wall
duration and free-form attributes.

Spans nest through a thread-local stack — a span opened while another
is active becomes its child and inherits the trace id — and finished
spans land in a process-wide bounded ring (:data:`tracer`), cheap
enough to leave on permanently. PROFILE and tests read the ring back
by trace id; nothing is ever written to disk here.

Usage::

    with span("tx.commit", creates=3) as sp:
        ...
        sp.set("rows", n)

    tracer.spans(trace_id=sp.trace_id)   # finished spans, oldest first
"""

from __future__ import annotations

import itertools
import threading
import time
import uuid
from collections import deque
from typing import Dict, List, Optional

from orientdb_tpu.utils.config import config

_ids = itertools.count(1)
#: process-unique id prefix: trace/span ids cross process boundaries
#: now (obs/propagation ships them to other nodes, and the debug
#: bundle groups by trace id), so two processes drawing from their own
#: counters must never mint the same id
_PROC = uuid.uuid4().hex[:8]
_local = threading.local()


def _stack() -> list:
    st = getattr(_local, "stack", None)
    if st is None:
        st = _local.stack = []
    return st


def current_trace_id() -> Optional[str]:
    """The active trace id on this thread, or None outside any span."""
    st = _stack()
    return st[-1].trace_id if st else None


def current_span() -> Optional["span"]:
    """The innermost active span on this thread, or None. Propagation
    (obs/propagation.py) reads it to build the outbound context."""
    st = _stack()
    return st[-1] if st else None


class span:
    """Context manager recording one span into the process tracer.

    A root span (no active parent on this thread) mints a fresh trace
    id; nested spans inherit it. Attributes passed as kwargs (or set
    later via :meth:`set`) must be JSON-friendly scalars — they travel
    into PROFILE output verbatim.
    """

    __slots__ = (
        "name",
        "attrs",
        "trace_id",
        "span_id",
        "parent_id",
        "start_ts",
        "duration_us",
        "error",
        "_t0",
    )

    def __init__(self, name: str, **attrs) -> None:
        self.name = name
        self.attrs: Dict[str, object] = dict(attrs)
        self.trace_id: Optional[str] = None
        self.span_id: Optional[str] = None
        self.parent_id: Optional[str] = None
        self.start_ts: Optional[float] = None
        self.duration_us: Optional[float] = None
        self.error: Optional[str] = None
        self._t0 = 0.0

    def set(self, key: str, value) -> None:
        self.attrs[key] = value

    def __enter__(self) -> "span":
        st = _stack()
        parent = st[-1] if st else None
        if parent is not None:
            self.trace_id = parent.trace_id
            self.parent_id = parent.span_id
        else:
            self.trace_id = f"t{_PROC}{next(_ids):08x}"
        self.span_id = f"s{_PROC}{next(_ids):08x}"
        self.start_ts = time.time()
        self._t0 = time.perf_counter()
        st.append(self)
        return self

    def __exit__(self, exc_type, exc, _tb):
        self.duration_us = round(
            (time.perf_counter() - self._t0) * 1e6, 1
        )
        if exc_type is not None:
            self.error = f"{exc_type.__name__}: {exc}"
        st = _stack()
        if st and st[-1] is self:
            st.pop()
        else:  # unbalanced exit (thread reuse): drop without corrupting
            try:
                st.remove(self)
            except ValueError:
                pass
        tracer.record(self)
        return False

    def to_dict(self) -> Dict[str, object]:
        out: Dict[str, object] = {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "name": self.name,
            "start_ts": self.start_ts,
            "duration_us": self.duration_us,
        }
        if self.parent_id is not None:
            out["parent_id"] = self.parent_id
        if self.attrs:
            out["attrs"] = dict(self.attrs)
        if self.error is not None:
            out["error"] = self.error
        return out


class Tracer:
    """Process-wide bounded ring of finished spans (thread-safe)."""

    def __init__(self, capacity: int) -> None:
        self._lock = threading.Lock()
        self._spans: deque = deque(maxlen=max(capacity, 16))
        #: finished-span listeners (obs/profile's aggregator); called
        #: OUTSIDE the ring lock, on the finishing span's own thread
        self._listeners: list = []

    def add_listener(self, fn) -> None:
        if fn not in self._listeners:
            self._listeners.append(fn)

    def remove_listener(self, fn) -> None:
        try:
            self._listeners.remove(fn)
        except ValueError:
            pass

    def record(self, sp: span) -> None:
        with self._lock:
            self._spans.append(sp)
        for fn in self._listeners:
            try:
                fn(sp)
            except Exception:  # a listener must never fail a span exit
                pass

    def spans(
        self,
        trace_id: Optional[str] = None,
        name: Optional[str] = None,
    ) -> List[span]:
        """Finished spans, oldest first, optionally filtered."""
        with self._lock:
            items = list(self._spans)
        if trace_id is not None:
            items = [s for s in items if s.trace_id == trace_id]
        if name is not None:
            items = [s for s in items if s.name == name]
        return items

    def reset(self) -> None:
        with self._lock:
            self._spans.clear()


#: the process-wide span ring (sized by config.trace_capacity)
tracer = Tracer(config.trace_capacity)
