"""Health watchdog: the thread that drives the alert lifecycle.

`obs/alerts.py` is a pure evaluator — something has to tick it. The
:class:`HealthWatchdog` runs with a :class:`~orientdb_tpu.server.server.Server`
(started in ``Server.startup``, stopped in ``shutdown``, mirroring
``Cluster``'s probe thread) and every ``config.watchdog_interval_s``
seconds evaluates the built-in rule catalog over this server's
databases and cluster. Evaluation happens ONLY here (and in explicit
:meth:`tick` calls from tests/bench) — the query hot path never pays
for it; the PR-4-style overhead guard in ``tests/test_alerts.py``
asserts that.

Each tick runs under a ``watchdog.tick`` span, so the watchdog's own
cost shows up in the profile plane like any other stage.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from orientdb_tpu.obs.alerts import engine
from orientdb_tpu.obs.trace import span
from orientdb_tpu.utils.config import config
from orientdb_tpu.utils.logging import get_logger

log = get_logger("watchdog")


class HealthWatchdog:
    """Periodic alert-rule evaluation over one server's state."""

    def __init__(self, server, interval: Optional[float] = None) -> None:
        self.server = server
        #: None = read config.watchdog_interval_s live per tick (the
        #: slowlog convention: retune without restarting)
        self.interval = interval
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle (Server.startup/shutdown) --------------------------------

    def start(self) -> "HealthWatchdog":
        with self._lock:
            # under the lock: two concurrent start() calls must not
            # each observe None and spawn duplicate tick loops
            if self._thread is None:
                self._stop.clear()
                self._thread = threading.Thread(
                    target=self._loop, name="health-watchdog", daemon=True
                )
                self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        with self._lock:
            t = self._thread
            self._thread = None
        if t is not None and t is not threading.current_thread():
            t.join(timeout=5)

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception:  # pragma: no cover - the loop must live
                log.exception("watchdog tick failed")
            self._stop.wait(
                self.interval
                if self.interval is not None
                else config.watchdog_interval_s
            )

    # -- one evaluation round -----------------------------------------------

    def tick(self) -> Dict[str, int]:
        """Evaluate every rule once over this server's state. Safe to
        call without the thread running (tests drive the lifecycle
        deterministically this way)."""
        srv = self.server
        dbs = list(getattr(srv, "databases", {}).values())
        cluster = getattr(srv, "cluster", None)
        if config.scrub_enabled and dbs:
            # one budgeted device-state scrub rotation per tick — the
            # continuous-correctness sweep rides the same cadence as
            # rule evaluation (storage/scrub; never raises into the
            # tick, repairs loudly via the scrub_corruption rule)
            from orientdb_tpu.storage.scrub import scrubber

            scrubber.sweep_all(dbs)
        with span("watchdog.tick") as sp:
            out = engine.evaluate(dbs=dbs, cluster=cluster)
            sp.set("fired", out["fired"])
            sp.set("resolved", out["resolved"])
        if out["fired"] or out["resolved"]:
            log.warning(
                "watchdog: %d alert(s) fired, %d resolved this tick",
                out["fired"],
                out["resolved"],
            )
        return out


def bench_watchdog_summary() -> Dict[str, object]:
    """One standalone evaluation over this process (no server needed)
    + the engine summary — the per-round health-evidence record
    ``bench.py`` writes next to ``static_analysis``."""
    engine.evaluate()
    return engine.summary()
