"""Query statistics: fingerprints + cumulative per-plan cost accounting.

The obs plane so far answers "what happened to THIS request" (traces,
slowlog, /metrics); this module answers "which query SHAPES dominate
the fleet, what do they cost on-device, and when did they regress" —
the pg_stat_statements / Dapper-aggregation analog ([E] OProfiler's
per-command chronos kept the per-statement totals; SURVEY.md §5.1):

- **fingerprint** — a normalized form of the SQL (literals → ``?``,
  literal IN-/list bodies collapsed to ``[?]``, case and whitespace
  folded) with a stable 64-bit id. The id is process-independent
  (BLAKE2b over the canonical token stream), so slowlog entries, stats
  rows, traces, and ``/cluster/metrics`` series from different members
  join on one value.
- **QueryStats** — a lock-cheap bounded table of per-fingerprint
  cumulative statistics: calls, errors, rows returned, a latency
  histogram, per-hop device/transfer time and bytes materialized
  (``exec/tpu_engine._fetch_profiled``), compile time vs plan-cache
  hits (recording executions ARE the compile path), recompiles due to
  parameter-driven shape overflow, and result-cache hits
  (``exec/command_cache``). Updated from hooks in ``exec/engine.py``;
  attribution of device/compile cost rides a **thread-local
  accumulator** (:meth:`QueryStats.begin` / :meth:`QueryStats.finish`)
  so the hot paths never search or lock per event.

``config.stats_sample_rate`` (default 1.0) samples whole queries out of
accounting; sampled-out queries skip every hook at ~one comparison.
"""

from __future__ import annotations

import hashlib
import random
import threading
import time
from collections import OrderedDict
from typing import Dict, List, NamedTuple, Optional

from orientdb_tpu.utils.config import config

# ---------------------------------------------------------------------------
# fingerprinting
# ---------------------------------------------------------------------------


class Fingerprint(NamedTuple):
    fid: str  #: stable 64-bit id, 16 hex chars
    text: str  #: normalized one-line SQL (display form)


def _normalize_tokens(sql: str):
    """(canonical token texts, display token texts).

    Canonical folds identifier case (class/field lookups are
    case-insensitive throughout the engine) and replaces literals with
    ``?``; display keeps the query's own identifier spelling so the
    stats table stays readable. A bracket group holding only literals
    and commas — an IN-list or literal list — collapses to ``[?]`` in
    both, so ``IN [1,2]`` and ``IN [1,2,3,4]`` share a fingerprint.
    """
    from orientdb_tpu.sql.lexer import tokenize

    toks = tokenize(sql)
    canon: List[str] = []
    disp: List[str] = []
    i, n = 0, len(toks)
    while i < n:
        t = toks[i]
        if t.kind == "EOF":
            break
        if t.kind == "OP" and t.text == "[":
            # literal-only bracket group → one collapsed placeholder
            # (commas and unary signs included, so [-1,-2] and
            # [-1,-2,-3] are one shape like their positive twins)
            j = i + 1
            only_literals = True
            has_literal = False
            while j < n and not (toks[j].kind == "OP" and toks[j].text == "]"):
                k = toks[j].kind
                if k in ("NUMBER", "STRING", "RID"):
                    has_literal = True
                elif not (k == "OP" and toks[j].text in (",", "-", "+")):
                    only_literals = False
                    break
                j += 1
            if only_literals and has_literal and j < n:
                canon.append("[?]")
                disp.append("[?]")
                i = j + 1
                continue
        if t.kind in ("NUMBER", "STRING", "RID"):
            canon.append("?")
            disp.append("?")
        elif t.kind == "IDENT":
            canon.append(t.text.casefold())
            disp.append(t.text)
        elif t.kind == "VAR":
            canon.append("$" + t.text.casefold())
            disp.append("$" + str(t.value))
        else:
            canon.append(t.text)
            disp.append(t.text)
        i += 1
    return canon, disp


def fingerprint(sql: str) -> Fingerprint:
    """Normalize ``sql`` and derive its stable 64-bit id. Unlexable
    input (a malformed statement that still reached the front door)
    falls back to the whitespace-collapsed raw text — it still gets a
    stable id, just without literal folding."""
    try:
        canon, disp = _normalize_tokens(sql)
        canon_s = " ".join(canon)
        text = " ".join(disp)
    except Exception:
        text = " ".join(sql.split())
        canon_s = text.casefold()
    fid = hashlib.blake2b(canon_s.encode(), digest_size=8).hexdigest()
    return Fingerprint(fid, text)


def sampled(rate: Optional[float] = None) -> bool:
    """ONE sampling decision for both planes (the stats table and the
    span-profile aggregator): record this query/trace?"""
    r = config.stats_sample_rate if rate is None else rate
    return r > 0 and (r >= 1.0 or random.random() < r)


_fp_cache: "OrderedDict[str, Fingerprint]" = OrderedDict()
_fp_lock = threading.Lock()


def fingerprint_cached(sql: str) -> Fingerprint:
    """LRU-cached :func:`fingerprint` (mirrors the statement cache —
    serving paths re-run the same SQL text constantly)."""
    with _fp_lock:
        fp = _fp_cache.get(sql)
        if fp is not None:
            _fp_cache.move_to_end(sql)
            return fp
    fp = fingerprint(sql)
    with _fp_lock:
        _fp_cache[sql] = fp
        while len(_fp_cache) > config.statement_cache_size:
            _fp_cache.popitem(last=False)
    return fp


# ---------------------------------------------------------------------------
# per-fingerprint statistics
# ---------------------------------------------------------------------------

#: latency histogram buckets (seconds) per fingerprint — coarser than
#: the global ladder; per-entry memory stays small
_LAT_BUCKETS = (0.001, 0.005, 0.025, 0.1, 0.5, 2.5, 10.0)

#: scalar fields exported to /stats/queries, the exposition fan-in, and
#: the debug bundle: (field, prometheus family suffix, prometheus type)
EXPORT_FIELDS = (
    ("calls", "query_calls_total", "counter"),
    ("errors", "query_errors_total", "counter"),
    ("rows_returned", "query_rows_returned_total", "counter"),
    ("total_s", "query_latency_seconds_total", "counter"),
    ("max_s", "query_latency_seconds_max", "gauge"),
    ("device_s", "query_device_seconds_total", "counter"),
    ("transfer_s", "query_transfer_seconds_total", "counter"),
    ("queue_s", "query_queue_seconds_total", "counter"),
    ("bytes_fetched", "query_bytes_fetched_total", "counter"),
    ("compile_s", "query_compile_seconds_total", "counter"),
    ("compiles", "query_compiles_total", "counter"),
    ("recompiles", "query_recompiles_total", "counter"),
    ("plan_cache_hits", "query_plan_cache_hits_total", "counter"),
    ("plan_cache_misses", "query_plan_cache_misses_total", "counter"),
    ("result_cache_hits", "query_result_cache_hits_total", "counter"),
)

#: latency quantiles derived from the per-entry histogram at read time
#: (field name, quantile): the SLO plane (obs/slo) and /stats/queries
#: read THESE instead of re-deriving their own estimates
QUANTILE_FIELDS = (("p50_ms", 0.50), ("p95_ms", 0.95), ("p99_ms", 0.99))

#: columns /stats/queries?by=… may sort on (every numeric export field
#: plus the derived mean and histogram quantiles)
SORT_COLUMNS = (
    tuple(f for f, _m, _t in EXPORT_FIELDS)
    + ("mean_ms",)
    + tuple(f for f, _q in QUANTILE_FIELDS)
)

#: short spellings accepted by ``?by=`` (``by=p99`` == ``by=p99_ms``)
SORT_ALIASES = {f.split("_")[0]: f for f, _q in QUANTILE_FIELDS}


def resolve_sort_column(by: str) -> str:
    """THE ``?by=`` resolution rule (alias expansion + unknown-column
    fallback), shared by :meth:`QueryStats.top` and the HTTP handler
    that echoes the resolved column — one copy, or the echo drifts
    from the actual sort order."""
    by = SORT_ALIASES.get(by, by)
    return by if by in SORT_COLUMNS else "total_s"


def estimate_quantile(
    buckets, q: float, max_s: float = 0.0
) -> float:
    """Estimate the ``q`` latency quantile (seconds) from one entry's
    histogram of PER-BUCKET counts (``_Entry.buckets``: one count per
    ``_LAT_BUCKETS`` boundary plus overflow — NOT the cumulative-`le`
    form a Prometheus exposition carries) — linear interpolation
    inside the bucket the rank lands in. The overflow (+Inf) bucket is
    bounded by the observed ``max_s`` instead of infinity, so a p99
    living there still reads as a finite, honest number."""
    total = sum(buckets)
    if total <= 0:
        return 0.0
    rank = q * total
    seen = 0
    lo = 0.0
    for le, count in zip(_LAT_BUCKETS, buckets):
        if seen + count >= rank and count > 0:
            return lo + (le - lo) * (rank - seen) / count
        seen += count
        lo = le
    # rank lands in the overflow bucket: interpolate toward max_s (or
    # pin to the last boundary when max_s never exceeded it)
    hi = max(max_s, lo)
    count = buckets[-1]
    if count <= 0:
        return lo
    return lo + (hi - lo) * (rank - seen) / count


class _Entry:
    __slots__ = (
        "fid",
        "text",
        "calls",
        "errors",
        "rows_returned",
        "total_s",
        "max_s",
        "device_s",
        "transfer_s",
        "queue_s",
        "bytes_fetched",
        "compile_s",
        "compiles",
        "recompiles",
        "plan_cache_hits",
        "plan_cache_misses",
        "result_cache_hits",
        "engines",
        "buckets",
        "first_ts",
        "last_ts",
        "plan",
        "segs",
    )

    def __init__(self, fid: str, text: str) -> None:
        self.fid = fid
        self.text = text
        self.calls = 0
        self.errors = 0
        self.rows_returned = 0
        self.total_s = 0.0
        self.max_s = 0.0
        self.device_s = 0.0
        self.transfer_s = 0.0
        self.queue_s = 0.0
        self.bytes_fetched = 0
        self.compile_s = 0.0
        self.compiles = 0
        self.recompiles = 0
        self.plan_cache_hits = 0
        self.plan_cache_misses = 0
        self.result_cache_hits = 0
        self.engines: Dict[str, int] = {}
        self.buckets = [0] * (len(_LAT_BUCKETS) + 1)
        self.first_ts = time.time()
        self.last_ts = self.first_ts
        self.plan: Optional[str] = None
        #: cumulative critical-path segment seconds (obs/critpath
        #: commit folds each sampled request's decomposition in here —
        #: the per-fingerprint segment columns riding this table)
        self.segs: Dict[str, float] = {}

    def to_dict(self) -> Dict[str, object]:
        out: Dict[str, object] = {
            "fingerprint": self.fid,
            "query": self.text,
        }
        for f, _m, _t in EXPORT_FIELDS:
            v = getattr(self, f)
            out[f] = round(v, 6) if isinstance(v, float) else v
        out["mean_ms"] = (
            round(self.total_s * 1000.0 / self.calls, 3) if self.calls else 0.0
        )
        for f, q in QUANTILE_FIELDS:
            out[f] = round(
                estimate_quantile(self.buckets, q, self.max_s) * 1000.0, 3
            )
        out["engines"] = dict(self.engines)
        out["latency_buckets"] = {
            ("+Inf" if le is None else repr(le)): c
            for le, c in zip(list(_LAT_BUCKETS) + [None], self.buckets)
        }
        out["first_ts"] = round(self.first_ts, 3)
        out["last_ts"] = round(self.last_ts, 3)
        if self.plan:
            out["plan"] = self.plan
        if self.segs:
            out["segments_s"] = {
                k: round(v, 6) for k, v in sorted(self.segs.items())
            }
        return out


class _Acc:
    """Per-query thread-local accumulator: the exec layers add device,
    compile, and cache events here without touching the shared table;
    :meth:`QueryStats.finish` folds it in under one short lock."""

    __slots__ = (
        "sql",
        "device_s",
        "transfer_s",
        "queue_s",
        "bytes_fetched",
        "compile_s",
        "compiles",
        "recompiles",
        "plan_cache_hits",
        "plan_cache_misses",
        "result_cache_hits",
        "plan",
        "_rows",
    )

    def __init__(self, sql: str) -> None:
        self.sql = sql
        self.device_s = 0.0
        self.transfer_s = 0.0
        self.queue_s = 0.0
        self.bytes_fetched = 0
        self.compile_s = 0.0
        self.compiles = 0
        self.recompiles = 0
        self.plan_cache_hits = 0
        self.plan_cache_misses = 0
        self.result_cache_hits = 0
        self.plan: Optional[str] = None
        self._rows: Optional[int] = None  # row count noted by the caller


_local = threading.local()


def _acc_stack() -> list:
    st = getattr(_local, "stack", None)
    if st is None:
        st = _local.stack = []
    return st


def current_acc() -> Optional[_Acc]:
    st = getattr(_local, "stack", None)
    return st[-1] if st else None


class capture:
    """Context manager capturing device/transfer attribution emitted on
    THIS thread (``add_device`` et al) without recording a query call —
    batch executors (the coalesce lane collect) run one fetch for N
    statements and split the captured cost across their members via
    :meth:`QueryStats.record_external`."""

    __slots__ = ("acc",)

    def __enter__(self) -> _Acc:
        self.acc = _Acc("")
        _acc_stack().append(self.acc)
        return self.acc

    def __exit__(self, *exc) -> None:
        st = _acc_stack()
        if st and st[-1] is self.acc:
            st.pop()
        else:  # unbalanced (should not happen): drop without corrupting
            try:
                st.remove(self.acc)
            except ValueError:
                pass


class QueryStats:
    """The process-wide per-fingerprint table (LRU-bounded)."""

    def __init__(self, capacity: Optional[int] = None) -> None:
        self._lock = threading.Lock()
        self._map: "OrderedDict[str, _Entry]" = OrderedDict()
        #: None = read config.query_stats_capacity live per insert (the
        #: slowlog convention: retune without restarting); an explicit
        #: capacity is fixed
        self._capacity = capacity

    # -- accumulator lifecycle (called by exec/engine) ----------------------

    def begin(self, sql: str) -> Optional[_Acc]:
        """Open accounting for one query on this thread; returns None
        when the query is sampled out (every later hook then no-ops at
        one thread-local read)."""
        if not sampled():
            return None
        acc = _Acc(sql)
        _acc_stack().append(acc)
        return acc

    def finish(
        self,
        acc: Optional[_Acc],
        duration_s: float,
        engine: str,
        rows: Optional[int] = None,
        error: Optional[BaseException] = None,
    ) -> Optional[str]:
        """Close the accumulator and fold it into the table; returns
        the fingerprint id (None when sampled out)."""
        if acc is None:
            return None
        st = _acc_stack()
        if st and st[-1] is acc:
            st.pop()
        else:  # unbalanced (should not happen): drop without corrupting
            try:
                st.remove(acc)
            except ValueError:
                pass
        fp = fingerprint_cached(acc.sql)
        self._record(fp, acc, duration_s, engine, rows, error)
        return fp.fid

    def calls_of(self, fid: str) -> int:
        """Recorded call count for a fingerprint (0 when untracked) —
        the materialized-view plane's hotness signal (exec/views)."""
        with self._lock:
            e = self._map.get(fid)
            return int(e.calls) if e is not None else 0

    def _entry_locked(self, fp: Fingerprint) -> Optional[_Entry]:
        """Get-or-create (and LRU-touch) the fingerprint's entry —
        caller holds ``_lock``. None when the table is disabled
        (capacity <= 0). THE insert/eviction block, shared by every
        writer so the policy cannot diverge between paths."""
        e = self._map.get(fp.fid)
        if e is not None:
            self._map.move_to_end(fp.fid)
            return e
        cap = (
            self._capacity
            if self._capacity is not None
            else config.query_stats_capacity
        )
        if cap <= 0:
            return None
        while len(self._map) >= cap:
            self._map.popitem(last=False)
        e = self._map[fp.fid] = _Entry(fp.fid, fp.text)
        return e

    def _record(
        self,
        fp: Fingerprint,
        acc: _Acc,
        duration_s: float,
        engine: str,
        rows: Optional[int],
        error: Optional[BaseException],
    ) -> None:
        import bisect

        bi = bisect.bisect_left(_LAT_BUCKETS, duration_s)
        with self._lock:
            e = self._entry_locked(fp)
            if e is None:
                return
            e.calls += 1
            e.last_ts = time.time()
            e.total_s += duration_s
            e.max_s = max(e.max_s, duration_s)
            e.buckets[bi] += 1
            if error is not None:
                e.errors += 1
            if rows is not None:
                e.rows_returned += rows
            e.engines[engine] = e.engines.get(engine, 0) + 1
            e.device_s += acc.device_s
            e.transfer_s += acc.transfer_s
            e.queue_s += acc.queue_s
            e.bytes_fetched += acc.bytes_fetched
            e.compile_s += acc.compile_s
            e.compiles += acc.compiles
            e.recompiles += acc.recompiles
            e.plan_cache_hits += acc.plan_cache_hits
            e.plan_cache_misses += acc.plan_cache_misses
            e.result_cache_hits += acc.result_cache_hits
            if acc.plan:
                e.plan = acc.plan

    def record_external(
        self,
        sql: str,
        duration_s: float,
        engine: str,
        rows: Optional[int] = None,
        error: Optional[BaseException] = None,
        queue_s: float = 0.0,
        device_s: float = 0.0,
        transfer_s: float = 0.0,
        bytes_fetched: int = 0,
    ) -> Optional[str]:
        """Record a query that ran without a thread-local accumulator —
        batch members (``query_batch`` amortizes one wall clock across
        its statements) and cached replays driven off-thread. Compile
        attribution is absent by construction; coalesce lanes pass the
        amortized device/transfer split they measured around the whole
        micro-batch (:func:`capture`) plus each item's queue wait, so
        the table splits "waiting for the lane" from "running"."""
        if not sampled():
            return None
        fp = fingerprint_cached(sql)
        acc = _Acc(sql)
        acc.queue_s = queue_s
        acc.device_s = device_s
        acc.transfer_s = transfer_s
        acc.bytes_fetched = bytes_fetched
        self._record(fp, acc, duration_s, engine, rows, error)
        return fp.fid

    def record_segments(self, sql: str, segs: Dict[str, float]) -> None:
        """Fold one committed critical-path decomposition
        (obs/critpath) into the fingerprint's cumulative segment
        columns WITHOUT counting a call — the execution path already
        recorded the call, and the critpath plane already made the
        sampling decision at begin_request (a second draw here would
        thin the segment columns against their own calls)."""
        if not segs:
            return
        fp = fingerprint_cached(sql)
        with self._lock:
            e = self._entry_locked(fp)
            if e is None:
                return
            d = e.segs
            for k, v in segs.items():
                if v > 0.0:
                    d[k] = d.get(k, 0.0) + v

    def segments_of(self, fid: str) -> Dict[str, float]:
        """One fingerprint's cumulative segment seconds ({} when
        untracked) — windowed readers difference two of these."""
        with self._lock:
            e = self._map.get(fid)
            return dict(e.segs) if e is not None else {}

    def record_queue(self, sql: str, queue_s: float) -> None:
        """Fold queue-wait seconds into a fingerprint's entry WITHOUT
        counting a call — the execution path already recorded the call;
        this adds the time the item spent parked in its coalesce lane
        before that execution started."""
        if queue_s <= 0.0 or not sampled():
            return
        fp = fingerprint_cached(sql)
        with self._lock:
            e = self._entry_locked(fp)
            if e is not None:
                e.queue_s += queue_s

    # -- reading ------------------------------------------------------------

    def top(self, k: int = 50, by: str = "total_s") -> List[Dict]:
        """The top-``k`` fingerprints ordered by any export column
        (``SORT_COLUMNS``; ``p99`` et al alias their ``_ms`` forms);
        unknown columns fall back to total_s."""
        by = resolve_sort_column(by)
        with self._lock:
            rows = [e.to_dict() for e in self._map.values()]
        rows.sort(key=lambda r: r.get(by, 0), reverse=True)
        return rows[: max(k, 0)]

    def export(self, limit: int = 128) -> Dict[str, Dict]:
        """Scalar-only snapshot for the exposition fan-in
        (``registry.snapshot_all``): ``{fid: {field: value}}`` for the
        ``limit`` costliest fingerprints by total latency."""
        with self._lock:
            entries = list(self._map.values())
        entries.sort(key=lambda e: e.total_s, reverse=True)
        out: Dict[str, Dict] = {}
        for e in entries[:limit]:
            out[e.fid] = {
                f: (round(getattr(e, f), 6) if isinstance(getattr(e, f), float)
                    else getattr(e, f))
                for f, _m, _t in EXPORT_FIELDS
            }
        return out

    def get(self, fid: str) -> Optional[Dict]:
        with self._lock:
            e = self._map.get(fid)
            return e.to_dict() if e is not None else None

    def histogram_snapshot(self, fids=None) -> Dict[str, Dict]:
        """Raw per-fingerprint histogram state for windowed readers
        (the SLO engine differences two of these to score ONE run
        instead of the process's whole cumulative history):
        ``{fid: {calls, errors, total_s, max_s, buckets}}``. ``fids``
        limits the snapshot; None snapshots the whole table."""
        with self._lock:
            entries = (
                list(self._map.values())
                if fids is None
                else [
                    self._map[f] for f in fids if f in self._map
                ]
            )
            return {
                e.fid: {
                    "calls": e.calls,
                    "errors": e.errors,
                    "total_s": e.total_s,
                    "max_s": e.max_s,
                    "buckets": list(e.buckets),
                }
                for e in entries
            }

    def reset(self) -> None:
        with self._lock:
            self._map.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._map)


#: the process-wide table (mirrors utils.metrics.metrics / obs registry)
stats = QueryStats()


# -- hot-path hooks (no-ops when no accumulator is active) -------------------


def add_device(device_s: float, transfer_s: float, nbytes: int) -> None:
    """Called by ``tpu_engine._fetch_profiled`` with each fetch wave's
    device-sync/transfer split and bytes moved."""
    acc = current_acc()
    if acc is not None:
        acc.device_s += device_s
        acc.transfer_s += transfer_s
        acc.bytes_fetched += nbytes


def add_compile(compile_s: float, rerecord: bool = False) -> None:
    """Called around ``tpu_engine._record`` — the eager recording
    execution IS the compile cost a caller absorbs on a plan-cache miss
    (``rerecord=True`` marks a shape-overflow re-record)."""
    acc = current_acc()
    if acc is not None:
        acc.compile_s += compile_s
        if rerecord:
            acc.recompiles += 1
        else:
            acc.compiles += 1


def note_plan_cache(hit: bool) -> None:
    acc = current_acc()
    if acc is not None:
        if hit:
            acc.plan_cache_hits += 1
        else:
            acc.plan_cache_misses += 1


def note_result_cache_hit() -> None:
    """Called by ``exec/command_cache`` — cached executions still count
    as calls; this marks how many were served without running."""
    acc = current_acc()
    if acc is not None:
        acc.result_cache_hits += 1


def note_plan(description: str) -> None:
    """Attach a plan description (compiled step chain / EXPLAIN head)
    to the active query's fingerprint entry."""
    acc = current_acc()
    if acc is not None:
        acc.plan = description[:400]


# ---------------------------------------------------------------------------
# Prometheus rendering (shared by /stats/queries and the registry fan-in)
# ---------------------------------------------------------------------------


def render_stats_into(
    lines: List[str],
    snapshots: Dict[Optional[str], Dict[str, Dict]],
) -> None:
    """Render per-fingerprint families into ``lines`` in exposition
    order (family outer, members+fingerprints inner — the grammar
    requires one contiguous group per family). ``snapshots`` maps a
    member name (or None for the single-process form) to that member's
    :meth:`QueryStats.export` dict."""
    members = sorted(snapshots, key=lambda m: m or "")
    for field, fam, typ in EXPORT_FIELDS:
        m = f"orienttpu_{fam}"
        header_done = False
        for mem in members:
            for fid in sorted(snapshots[mem] or {}):
                v = snapshots[mem][fid].get(field)
                if v is None:
                    continue
                if not header_done:
                    lines.append(f"# HELP {m} orientdb-tpu metric {m}")
                    lines.append(f"# TYPE {m} {typ}")
                    header_done = True
                labels = f'fingerprint="{fid}"'
                if mem is not None:
                    labels += f',member="{mem}"'
                lines.append(f"{m}{{{labels}}} {v}")


def render_stats_prometheus(limit: int = 128) -> str:
    """The process's own query-stats exposition (``GET
    /stats/queries?format=prometheus``)."""
    lines: List[str] = []
    render_stats_into(lines, {None: stats.export(limit)})
    return "\n".join(lines) + "\n"
