"""Prometheus text-exposition grammar lint.

A malformed metric — a bad name character, a TYPE after its samples, a
duplicate series — makes a real Prometheus server drop the WHOLE
scrape, silently blinding every dashboard. This linter checks the
text-format 0.0.4 grammar so a tier-1 test can fail the build instead
(`tests/test_cluster_obs.py` lints the full ``/metrics`` and
``/cluster/metrics`` output):

- metric names match ``[a-zA-Z_:][a-zA-Z0-9_:]*``, label names match
  ``[a-zA-Z_][a-zA-Z0-9_]*`` and never start ``__``;
- label values use only the legal escapes (``\\\\``, ``\\"``, ``\\n``);
- ``# TYPE`` at most once per family, BEFORE any of its samples, with
  a known type; ``# HELP`` at most once per family;
- all samples of a family form one contiguous group;
- histogram/summary child samples (``_bucket``/``_sum``/``_count``)
  attach to their declared family;
- no duplicate series (same name + label set);
- sample values parse as floats (``+Inf``/``-Inf``/``NaN`` included);
- the document ends with a newline.

Returns problems as strings; an empty list means the document is clean.

This is the RUNTIME half; the static half — literal metric names at
registration call sites must match the internal dotted grammar so
``_prom_name`` sanitizes them collision-free — runs as the
``promlint`` pass of ``orientdb_tpu/analysis`` on every tier-1 build.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

_METRIC_RE = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*\Z")
_LABEL_RE = re.compile(r"[a-zA-Z_][a-zA-Z0-9_]*\Z")
_TYPES = {"counter", "gauge", "histogram", "summary", "untyped"}

#: child-sample suffixes per complex type
_CHILD_SUFFIXES = {
    "histogram": ("_bucket", "_sum", "_count"),
    "summary": ("_sum", "_count"),
}


def _parse_labels(raw: str) -> Optional[List[Tuple[str, str]]]:
    """``a="b",c="d"`` → pairs, honoring escapes; None on bad syntax."""
    out: List[Tuple[str, str]] = []
    i, n = 0, len(raw)
    while i < n:
        j = raw.find("=", i)
        if j < 0:
            return None
        name = raw[i:j].strip()
        if j + 1 >= n or raw[j + 1] != '"':
            return None
        k = j + 2
        val = []
        while k < n:
            ch = raw[k]
            if ch == "\\":
                if k + 1 >= n or raw[k + 1] not in ('\\', '"', "n"):
                    return None
                val.append(raw[k : k + 2])
                k += 2
                continue
            if ch == '"':
                break
            if ch == "\n":
                return None
            val.append(ch)
            k += 1
        else:
            return None  # unterminated value
        out.append((name, "".join(val)))
        k += 1
        if k < n:
            if raw[k] != ",":
                return None
            k += 1
        i = k
    return out


def _value_ok(v: str) -> bool:
    if v in ("+Inf", "-Inf", "Inf", "NaN"):
        return True
    try:
        float(v)
        return True
    except ValueError:
        return False


def _family_of(name: str, types: Dict[str, str]) -> str:
    """The declared family a sample belongs to: exact, or the base of a
    histogram/summary child suffix."""
    if name in types:
        return name
    for typ, suffixes in _CHILD_SUFFIXES.items():
        for suf in suffixes:
            if name.endswith(suf):
                base = name[: -len(suf)]
                if types.get(base) == typ:
                    return base
    return name


def lint_exposition(text: str) -> List[str]:
    """Lint one exposition document; returns problems (empty = clean)."""
    problems: List[str] = []
    if not text.endswith("\n"):
        problems.append("document must end with a newline")
    types: Dict[str, str] = {}
    helps: set = set()
    sampled: set = set()  # families that already emitted samples
    closed: set = set()  # families whose group ended (another began)
    current: Optional[str] = None
    seen_series: set = set()
    for ln, line in enumerate(text.splitlines(), 1):
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) < 3 or parts[1] not in ("HELP", "TYPE"):
                continue  # plain comment: legal, ignored
            kind, name = parts[1], parts[2]
            if not _METRIC_RE.match(name):
                problems.append(f"line {ln}: bad metric name {name!r}")
                continue
            if kind == "TYPE":
                typ = parts[3].strip() if len(parts) > 3 else ""
                if typ not in _TYPES:
                    problems.append(
                        f"line {ln}: unknown TYPE {typ!r} for {name}"
                    )
                if name in types:
                    problems.append(
                        f"line {ln}: duplicate TYPE for {name}"
                    )
                if name in sampled:
                    problems.append(
                        f"line {ln}: TYPE for {name} after its samples"
                    )
                types[name] = typ
            else:
                if name in helps:
                    problems.append(
                        f"line {ln}: duplicate HELP for {name}"
                    )
                helps.add(name)
            continue
        # sample line: name[{labels}] value [timestamp]
        m = re.match(r"([a-zA-Z_:][a-zA-Z0-9_:]*)(\{(.*)\})?\s+(\S+)"
                     r"(\s+-?\d+)?\s*\Z", line)
        if m is None:
            problems.append(f"line {ln}: unparsable sample: {line!r}")
            continue
        name, _braced, rawlabels, value = (
            m.group(1), m.group(2), m.group(3), m.group(4),
        )
        labels: List[Tuple[str, str]] = []
        if rawlabels:
            parsed = _parse_labels(rawlabels)
            if parsed is None:
                problems.append(
                    f"line {ln}: bad label syntax: {rawlabels!r}"
                )
                continue
            labels = parsed
            for lname, _v in labels:
                if not _LABEL_RE.match(lname) or lname.startswith("__"):
                    problems.append(
                        f"line {ln}: bad label name {lname!r}"
                    )
        if not _value_ok(value):
            problems.append(f"line {ln}: bad sample value {value!r}")
        fam = _family_of(name, types)
        if fam in closed:
            problems.append(
                f"line {ln}: samples of {fam} are not contiguous"
            )
        if current is not None and fam != current:
            closed.add(current)
        current = fam
        sampled.add(fam)
        series = (name, tuple(sorted(labels)))
        if series in seen_series:
            problems.append(
                f"line {ln}: duplicate series {name}"
                f"{{{rawlabels or ''}}}"
            )
        seen_series.add(series)
    return problems
