"""Crash-safe evidence streaming: an append-only, fsync'd JSONL sink.

Round 5's bench ran to the driver's timeout and left NOTHING —
``bench.py`` wrote its detail artifact only at process exit, so
``BENCH_r05.json`` records ``rc:124`` and zero numbers. This module is
the fix: every completed block's result is appended as one JSON line
and flushed + fsync'd immediately, so a SIGKILL mid-run still leaves
every finished block on disk. ``bench.py`` emits after every block and
``tools/dryrun.py`` after every parity query.

The format is one JSON object per line::

    {"seq": 3, "ts": 1754…, "elapsed_s": 41.2, "block": "ldbc_is",
     "data": {…}}

:func:`read_evidence` tolerates a torn final line (the record being
written when the process died) by skipping anything that does not
parse — mirroring the WAL's torn-tail discipline.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional


class EvidenceSink:
    """Append-only JSONL writer; every record is durable before
    :meth:`emit` returns."""

    def __init__(self, path: str) -> None:
        self.path = path
        self._lock = threading.Lock()
        self._fh = None
        self._seq = 0
        self._t0 = time.perf_counter()

    def emit(self, block: str, data) -> Dict:
        """Append one evidence record for ``block``; returns it."""
        with self._lock:
            self._seq += 1
            rec = {
                "seq": self._seq,
                "ts": round(time.time(), 3),
                "elapsed_s": round(time.perf_counter() - self._t0, 3),
                "block": block,
                "data": data,
            }
            line = json.dumps(rec, sort_keys=True) + "\n"
            if self._fh is None:
                d = os.path.dirname(os.path.abspath(self.path))
                os.makedirs(d, exist_ok=True)
                self._fh = open(self.path, "a", encoding="utf-8")
            self._fh.write(line)
            self._fh.flush()
            os.fsync(self._fh.fileno())
        return rec

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None


def read_evidence(path: str) -> List[Dict]:
    """Parse an evidence stream; a torn/corrupt line is skipped (the
    record being written when the process died)."""
    if not os.path.exists(path):
        return []
    out: List[Dict] = []
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                continue
    return out


def evidence_sink(default_path: Optional[str]) -> Optional[EvidenceSink]:
    """Sink at ``$ORIENTTPU_EVIDENCE`` (overrides), else at
    ``default_path``; None when both are unset — callers no-op."""
    path = os.environ.get("ORIENTTPU_EVIDENCE") or default_path
    return EvidenceSink(path) if path else None
