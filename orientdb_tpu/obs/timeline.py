"""Dispatch flight recorder: per-dispatch lifecycle timelines with
overlap accounting and Chrome-trace/Perfetto export.

The perf arc (PRs 12–13) is now an *overlap* story — double-buffered
lane dispatch, device-resident parameter rings, speculative page
prefetch, collectives issued ahead of local expansion — but nothing
measured whether any of that overlap actually happens: the gauges count
events, not *concurrency*. This module records every dispatch's
lifecycle as timestamped events in a bounded ring and derives the
numbers the counters cannot express:

- **flight recorder** — each dispatch (compiled single, vmapped group,
  coalesce lane drain, sharded mesh, oracle) contributes ONE
  :class:`DispatchRecord`: monotonic-timestamped lifecycle events
  (``enqueue → lane_window → plan_resolve → param_upload｜ring_hit →
  device_dispatch → compute_done → transfer_start/done →
  result_delivered``), device-busy and transfer intervals (from
  ``exec/tpu_engine._fetch_profiled`` / ``_finish_pending`` /
  ``parallel/sharded.fetch_sharded``), and correlation ids (query
  fingerprint from the PR-4 stats plane, trace id from ``obs/trace``).
  Recording rides the ``config.stats_sample_rate`` sampling decision
  and thread-local hooks exactly like ``obs/stats`` — a sampled-out
  query costs one comparison per hook, and the tier-1 overhead guard
  pins the whole plane under 1.35x.
- **overlap accounting** (:meth:`FlightRecorder.overlap`) — the
  derived metrics: *device-idle fraction* (1 − merged device-busy time
  over the window span — how much of the wall the device sat idle
  between dispatches), *transfer-hidden fraction* (bytes whose copy
  interval overlapped device compute vs serialized after it — the
  number that proves or refutes the PR-13 prefetch and PR-12 double
  buffer), *lane queue/window vs service decomposition*, and *ring
  upload-avoidance savings*; globally, per dispatch path, and for the
  hottest fingerprints.
- **export** — :meth:`FlightRecorder.chrome_trace` renders the window
  as Chrome-trace JSON (the ``traceEvents`` array form Perfetto and
  ``chrome://tracing`` load directly), served admin-only at ``GET
  /debug/timeline``, bundled as the debug bundle's ``timeline``
  section, and printed by the console ``TIMELINE [n]`` verb. Scrape
  surfaces: ``orienttpu_overlap_*`` gauges in ``/metrics`` (and the
  member-labeled ``/cluster/metrics`` fan-in) refresh from a bounded
  recent window at scrape time, and the ``overlap_regression`` alert
  rule (obs/alerts) watches the device-idle fraction against its
  online EWMA baseline.

All timestamps are ``time.monotonic()`` seconds (the coalesce lanes'
enqueue clock), so intervals from different threads compare directly;
``chrome_trace`` rescales to microseconds.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from orientdb_tpu.utils.config import config

#: the lifecycle vocabulary (README "Dispatch timeline" documents each);
#: compute_done / transfer_start / transfer_done are stamped by
#: :func:`add_phase` alongside the intervals that carry their bytes
EVENTS = (
    "enqueue",          # item entered its coalesce lane (lane path)
    "lane_window",      # lane collection window closed, batch formed
    "plan_resolve",     # cached plan picked (variants.pick)
    "param_upload",     # dynamic args uploaded host→device
    "ring_hit",         # dynamic args served from the device ring
    "prefetch_start",   # speculative result-page copy started
    "kernel_build",     # mesh shard_map kernel built (sharded path)
    "device_dispatch",  # replay enqueued on device
    "compute_done",     # device sync returned
    "transfer_start",   # blocking device→host drain began
    "transfer_done",    # bytes on host
    "result_delivered", # record committed (rows marshalled)
    "device_fault",     # classified device fault crossed this dispatch
                        # (exec/devicefault; marks carry the kind count)
)

#: dispatch path labels (``note_path`` refines; "lane" is sticky — a
#: lane drain that group-dispatches is still the coalesce path)
PATHS = ("single", "batch", "group", "lane", "sharded", "oracle")


class DispatchRecord:
    """One dispatch's flight record. Owned by the dispatching thread
    until :meth:`FlightRecorder.commit` publishes it into the ring —
    no locking on the hot path."""

    __slots__ = (
        "seq",
        "path",
        "_fid",
        "sql",
        "trace_id",
        "n",
        "t0",
        "t_done",
        "events",
        "device",
        "transfers",
        "marks",
    )

    def __init__(
        self,
        seq: int,
        path: str,
        sql: Optional[str],
        trace_id: Optional[str],
        n: int,
    ) -> None:
        self.seq = seq
        self.path = path
        #: fingerprint resolution is DEFERRED to read time: begin()
        #: keeps only the SQL text so the hot path never pays the
        #: normalization LRU — readers are bounded by the ring
        self._fid: Optional[str] = None
        self.sql = sql
        self.trace_id = trace_id
        self.n = n
        self.t0 = time.monotonic()
        self.t_done: Optional[float] = None
        #: [(event name, monotonic ts)]
        self.events: List[Tuple[str, float]] = []
        #: device-busy intervals [(t_start, t_end)]
        self.device: List[Tuple[float, float]] = []
        #: transfer intervals [(t_start, t_end, nbytes, kind)] — kind
        #: "fetch" (blocking drain) or "prefetch" (copy started at
        #: dispatch time, i.e. hidden behind compute by construction)
        self.transfers: List[Tuple[float, float, int, str]] = []
        #: free-form counters/annotations (ring hits, window_s, ...)
        self.marks: Dict[str, object] = {}

    def add_event(self, name: str, ts: Optional[float] = None) -> None:
        self.events.append((name, time.monotonic() if ts is None else ts))

    def bump(self, key: str, by: int = 1) -> None:
        self.marks[key] = int(self.marks.get(key, 0)) + by

    @property
    def fid(self) -> Optional[str]:
        """The stats-plane fingerprint id (resolved lazily from the
        SQL captured at begin; cached on the record)."""
        if self._fid is None and self.sql:
            from orientdb_tpu.obs.stats import fingerprint_cached

            self._fid = fingerprint_cached(self.sql).fid
        return self._fid

    def span(self) -> Tuple[float, float]:
        """(first, last) timestamp this record covers."""
        ts = [self.t0]
        ts.extend(t for _n, t in self.events)
        ts.extend(t for pair in self.device for t in pair)
        ts.extend(t for t, t1, _b, _k in self.transfers for t in (t, t1))
        if self.t_done is not None:
            ts.append(self.t_done)
        return min(ts), max(ts)

    def to_dict(self) -> Dict[str, object]:
        out: Dict[str, object] = {
            "seq": self.seq,
            "path": self.path,
            "fingerprint": self.fid,
            "trace_id": self.trace_id,
            "n": self.n,
            "t0": round(self.t0, 6),
            "events": [(n, round(t, 6)) for n, t in self.events],
            "device": [
                (round(a, 6), round(b, 6)) for a, b in self.device
            ],
            "transfers": [
                (round(a, 6), round(b, 6), nb, k)
                for a, b, nb, k in self.transfers
            ],
        }
        if self.t_done is not None:
            out["t_done"] = round(self.t_done, 6)
        if self.marks:
            out["marks"] = dict(self.marks)
        return out


# -- thread-local active record (the obs/stats accumulator pattern) ----------

_local = threading.local()


def _rec_stack() -> list:
    st = getattr(_local, "stack", None)
    if st is None:
        st = _local.stack = []
    return st


def current() -> Optional[DispatchRecord]:
    st = getattr(_local, "stack", None)
    return st[-1] if st else None


class active:
    """Make ``rec`` the thread's active record for the block — the
    hot-path hooks below write to whatever is active. ``active(None)``
    is a no-op, so call sites need no sampling branch."""

    __slots__ = ("rec",)

    def __init__(self, rec: Optional[DispatchRecord]) -> None:
        self.rec = rec

    def __enter__(self) -> Optional[DispatchRecord]:
        if self.rec is not None:
            _rec_stack().append(self.rec)
        return self.rec

    def __exit__(self, *exc) -> None:
        if self.rec is None:
            return
        st = _rec_stack()
        if st and st[-1] is self.rec:
            st.pop()
        else:  # unbalanced (should not happen): drop without corrupting
            try:
                st.remove(self.rec)
            except ValueError:
                pass


# -- the recorder ------------------------------------------------------------


def _merge_intervals(
    ivs: List[Tuple[float, float]]
) -> List[Tuple[float, float]]:
    """Union of possibly-overlapping intervals, sorted."""
    out: List[Tuple[float, float]] = []
    for a, b in sorted(iv for iv in ivs if iv[1] > iv[0]):
        if out and a <= out[-1][1]:
            if b > out[-1][1]:
                out[-1] = (out[-1][0], b)
        else:
            out.append((a, b))
    return out


def _overlap_s(
    a0: float, a1: float, merged: List[Tuple[float, float]]
) -> float:
    """Seconds of ``[a0, a1]`` covered by the merged interval union."""
    total = 0.0
    for b0, b1 in merged:
        if b0 >= a1:
            break
        lo, hi = max(a0, b0), min(a1, b1)
        if hi > lo:
            total += hi - lo
    return total


class FlightRecorder:
    """Process-wide bounded ring of committed dispatch records."""

    def __init__(self, capacity: Optional[int] = None) -> None:
        self._lock = threading.Lock()
        self._ring: deque = deque()
        #: lock-free sequence (itertools.count is atomic in CPython) —
        #: begin() is on the per-query hot path
        self._seq = itertools.count(1)
        #: None = read config.timeline_capacity live per commit (the
        #: slowlog convention: retune without restarting)
        self._capacity = capacity

    def _cap(self) -> int:
        return int(
            self._capacity
            if self._capacity is not None
            else config.timeline_capacity
        )

    # -- record lifecycle ---------------------------------------------------

    def begin(
        self,
        path: str,
        sql: Optional[str] = None,
        n: int = 1,
    ) -> Optional[DispatchRecord]:
        """Open a record for one dispatch, or None when the recorder is
        disabled (capacity <= 0) or the dispatch sampled out — every
        later hook then no-ops at one thread-local read.

        Sampling rides the stats plane's decision, not an independent
        draw: for per-query dispatches (no ``sql`` passed) an ACTIVE
        stats accumulator is the sampled-in marker, so under
        ``stats_sample_rate < 1`` the timeline covers exactly the same
        query subset as stats/slowlog/traces — a trace id found in the
        slowlog always joins a timeline record. Detached dispatches
        (lane drains, the in-frame batch front door — their worker
        threads carry no per-query accumulator) pass their ``sql`` and
        draw a decision at the same rate. The fingerprint derives
        lazily (at read time) from the SQL; the trace id is the
        thread's active span's."""
        if self._cap() <= 0:
            return None
        from orientdb_tpu.obs.stats import current_acc, sampled
        from orientdb_tpu.obs.trace import current_trace_id

        if sql is None:
            acc = current_acc()
            if acc is None:
                return None  # the stats plane sampled this query out
            sql = acc.sql or None
        elif not sampled():
            return None
        return DispatchRecord(
            next(self._seq), path, sql, current_trace_id(), n
        )

    def commit(self, rec: Optional[DispatchRecord]) -> None:
        """Stamp ``result_delivered`` and publish the record. A record
        that is never committed (an errored or ineligible dispatch)
        simply never enters the ring."""
        if rec is None:
            return
        rec.t_done = time.monotonic()
        rec.add_event("result_delivered", rec.t_done)
        cap = self._cap()
        if cap <= 0:
            return
        with self._lock:
            self._ring.append(rec)
            while len(self._ring) > cap:
                self._ring.popleft()

    # -- reading ------------------------------------------------------------

    def _window(
        self, window_s: Optional[float]
    ) -> List[DispatchRecord]:
        with self._lock:
            recs = list(self._ring)
        if window_s is None or window_s <= 0 or not recs:
            return recs
        floor = time.monotonic() - window_s
        return [r for r in recs if (r.t_done or r.t0) >= floor]

    def records(
        self,
        window_s: Optional[float] = None,
        limit: Optional[int] = None,
    ) -> List[Dict]:
        recs = self._window(window_s)
        if limit is not None:
            recs = recs[-limit:] if limit > 0 else []
        return [r.to_dict() for r in recs]

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()

    # -- overlap accounting -------------------------------------------------

    def overlap(
        self,
        window_s: Optional[float] = None,
        top_fingerprints: int = 8,
    ) -> Dict[str, object]:
        """The derived-metrics pass over the (bounded) recent window.

        Runs under a ``timeline.overlap`` span — the accounting itself
        is an observable stage (it ticks at every scrape via the gauge
        provider and at watchdog evaluation)."""
        from orientdb_tpu.obs.trace import span

        with span("timeline.overlap"):
            return self._overlap(self._window(window_s), top_fingerprints)

    @staticmethod
    def _overlap(
        recs: List[DispatchRecord], top_fingerprints: int
    ) -> Dict[str, object]:
        out: Dict[str, object] = {"records": len(recs)}
        if not recs:
            return out
        spans = [r.span() for r in recs]
        lo = min(s[0] for s in spans)
        hi = max(s[1] for s in spans)
        span_s = max(hi - lo, 1e-9)
        busy = _merge_intervals(
            [iv for r in recs for iv in r.device]
        )
        busy_s = sum(b - a for a, b in busy)
        out["span_s"] = round(span_s, 6)
        out["device_busy_s"] = round(busy_s, 6)
        # device-idle fraction BETWEEN dispatches: of the window span,
        # how much had no device work in flight at all
        out["device_idle_fraction"] = round(
            max(0.0, 1.0 - busy_s / span_s), 6
        )
        # transfer-hidden split: a transfer interval's bytes count as
        # hidden in proportion to its overlap with device-busy time;
        # a zero-length "prefetch" interval (copy landed before the
        # drain even looked) is hidden by construction
        t_bytes = h_bytes = 0
        pf_bytes = 0
        for r in recs:
            for a, b, nb, kind in r.transfers:
                t_bytes += nb
                if kind == "prefetch":
                    pf_bytes += nb
                if b > a:
                    h_bytes += int(nb * _overlap_s(a, b, busy) / (b - a))
                elif kind == "prefetch":
                    h_bytes += nb
        out["transfer"] = {
            "bytes": t_bytes,
            "hidden_bytes": h_bytes,
            "serialized_bytes": t_bytes - h_bytes,
            "prefetch_bytes": pf_bytes,
            "transfer_hidden_fraction": (
                round(h_bytes / t_bytes, 6) if t_bytes else 0.0
            ),
        }
        # ring upload-avoidance savings (PR-12 parameter rings)
        hits = sum(int(r.marks.get("ring_hits", 0)) for r in recs)
        ups = sum(int(r.marks.get("ring_uploads", 0)) for r in recs)
        out["ring"] = {
            "hits": hits,
            "uploads": ups,
            "bytes_uploaded": sum(
                int(r.marks.get("ring_bytes", 0)) for r in recs
            ),
            "hit_fraction": (
                round(hits / (hits + ups), 6) if (hits + ups) else 0.0
            ),
        }
        out["prefetch"] = {
            "starts": sum(
                int(r.marks.get("prefetch_starts", 0)) for r in recs
            ),
            "hits": sum(
                int(r.marks.get("prefetch_hits", 0)) for r in recs
            ),
            "misses": sum(
                int(r.marks.get("prefetch_misses", 0)) for r in recs
            ),
        }
        # lane decomposition: time queued in the lane (enqueue →
        # device_dispatch), the collection window in force, and the
        # service time (device_dispatch → result_delivered)
        lane_q: List[float] = []
        lane_w: List[float] = []
        lane_s: List[float] = []
        paths: Dict[str, int] = {}
        for r in recs:
            paths[r.path] = paths.get(r.path, 0) + 1
            if r.path != "lane":
                continue
            ev = dict(r.events)
            dd = ev.get("device_dispatch")
            enq = ev.get("enqueue")
            if enq is not None and dd is not None:
                lane_q.append(max(0.0, dd - enq))
            if dd is not None and r.t_done is not None:
                lane_s.append(max(0.0, r.t_done - dd))
            w = r.marks.get("window_s")
            if w is not None:
                lane_w.append(float(w))

        def _mean_ms(xs: List[float]) -> Optional[float]:
            return round(sum(xs) / len(xs) * 1000.0, 3) if xs else None

        out["paths"] = paths
        if paths.get("lane"):
            out["lane"] = {
                "dispatches": paths["lane"],
                "queue_ms_mean": _mean_ms(lane_q),
                "window_ms_mean": _mean_ms(lane_w),
                "service_ms_mean": _mean_ms(lane_s),
            }
        # per-fingerprint: dispatches, device/transfer cost, its own
        # hidden fraction, and idle time between its dispatches
        by_fid: Dict[str, List[DispatchRecord]] = {}
        for r in recs:
            if r.fid is not None:
                by_fid.setdefault(r.fid, []).append(r)
        tops = sorted(
            by_fid.items(), key=lambda kv: -len(kv[1])
        )[: max(top_fingerprints, 0)]
        fps: Dict[str, Dict] = {}
        for fid, rs in tops:
            fb = _merge_intervals([iv for r in rs for iv in r.device])
            fb_s = sum(b - a for a, b in fb)
            f_lo = min(r.span()[0] for r in rs)
            f_hi = max(r.span()[1] for r in rs)
            f_span = max(f_hi - f_lo, 1e-9)
            tb = hb = 0
            for r in rs:
                for a, b, nb, kind in r.transfers:
                    tb += nb
                    if b > a:
                        hb += int(nb * _overlap_s(a, b, busy) / (b - a))
                    elif kind == "prefetch":
                        hb += nb
            fps[fid] = {
                "dispatches": len(rs),
                "device_s": round(fb_s, 6),
                "idle_fraction": round(
                    max(0.0, 1.0 - fb_s / f_span), 6
                ),
                "transfer_bytes": tb,
                "transfer_hidden_fraction": (
                    round(hb / tb, 6) if tb else 0.0
                ),
            }
        if fps:
            out["fingerprints"] = fps
        return out

    # -- Chrome-trace / Perfetto export -------------------------------------

    def chrome_trace(
        self, window_s: Optional[float] = None
    ) -> Dict[str, object]:
        """The window as Chrome-trace JSON (``traceEvents`` array form)
        — loadable by Perfetto (ui.perfetto.dev) and chrome://tracing.
        One lane (tid) per dispatch path plus its device and transfer
        sub-lanes; lifecycle events render as instants, device/transfer
        intervals and whole dispatches as complete ("X") slices."""
        from orientdb_tpu.obs.trace import span

        with span("timeline.export") as sp:
            recs = self._window(window_s)
            sp.set("records", len(recs))
            events: List[Dict] = [
                {
                    "name": "process_name",
                    "ph": "M",
                    "pid": 1,
                    "tid": 0,
                    "args": {"name": "orienttpu dispatch"},
                }
            ]
            tids: Dict[str, int] = {}

            def tid(lane: str) -> int:
                t = tids.get(lane)
                if t is None:
                    t = tids[lane] = len(tids) + 1
                    events.append(
                        {
                            "name": "thread_name",
                            "ph": "M",
                            "pid": 1,
                            "tid": t,
                            "args": {"name": lane},
                        }
                    )
                return t

            def us(t: float) -> float:
                return round(t * 1e6, 1)

            for r in recs:
                lo, hi = r.span()
                args = {
                    "seq": r.seq,
                    "fingerprint": r.fid,
                    "trace_id": r.trace_id,
                    "n": r.n,
                }
                if r.marks:
                    args.update(r.marks)
                events.append(
                    {
                        "name": f"{r.path} dispatch",
                        "cat": r.path,
                        "ph": "X",
                        "ts": us(lo),
                        "dur": max(round((hi - lo) * 1e6, 1), 1.0),
                        "pid": 1,
                        "tid": tid(r.path),
                        "args": args,
                    }
                )
                for name, t in r.events:
                    events.append(
                        {
                            "name": name,
                            "cat": r.path,
                            "ph": "i",
                            "s": "t",
                            "ts": us(t),
                            "pid": 1,
                            "tid": tid(r.path),
                            "args": {"seq": r.seq},
                        }
                    )
                for a, b in r.device:
                    events.append(
                        {
                            "name": "device",
                            "cat": r.path,
                            "ph": "X",
                            "ts": us(a),
                            "dur": max(round((b - a) * 1e6, 1), 1.0),
                            "pid": 1,
                            "tid": tid(f"{r.path}:device"),
                            "args": {"seq": r.seq},
                        }
                    )
                for a, b, nb, kind in r.transfers:
                    events.append(
                        {
                            "name": kind,
                            "cat": r.path,
                            "ph": "X",
                            "ts": us(a),
                            "dur": max(round((b - a) * 1e6, 1), 1.0),
                            "pid": 1,
                            "tid": tid(f"{r.path}:transfer"),
                            "args": {"seq": r.seq, "bytes": nb},
                        }
                    )
            return {
                "traceEvents": events,
                "displayTimeUnit": "ms",
                "otherData": {
                    "generator": "orientdb-tpu dispatch flight recorder",
                    "overlap": self._overlap(recs, 8),
                },
            }


#: the process-wide recorder (mirrors stats/tracer/alert singletons)
recorder = FlightRecorder()


# -- hot-path hooks (no-ops when no record is active) ------------------------


def mark(name: str, ts: Optional[float] = None) -> None:
    rec = current()
    if rec is not None:
        rec.add_event(name, ts)


def note_path(path: str) -> None:
    """Refine the active record's dispatch path from a deeper layer
    (``dispatch_many`` → group, a mesh plan's dispatch → sharded).
    "lane" is sticky: a lane drain that group-dispatches is still the
    coalesce path — the lane IS the story."""
    rec = current()
    if rec is not None and rec.path != "lane":
        rec.path = path


def note(key: str, value) -> None:
    rec = current()
    if rec is not None:
        rec.marks[key] = value


def add_phase(device_s: float, transfer_s: float, nbytes: int) -> None:
    """Called next to ``obs.stats.add_device`` with a fetch wave's
    device-sync/transfer split: anchors the intervals at *now* (the
    hook runs right after the wave ends), stamping the
    compute_done/transfer_start/transfer_done lifecycle events."""
    rec = current()
    if rec is None:
        return
    now = time.monotonic()
    t_mid = now - max(transfer_s, 0.0)
    if device_s > 0.0:
        rec.device.append((t_mid - device_s, t_mid))
    rec.add_event("compute_done", t_mid)
    if transfer_s > 0.0 or nbytes:
        rec.transfers.append((t_mid, now, int(nbytes), "fetch"))
        rec.add_event("transfer_start", t_mid)
        rec.add_event("transfer_done", now)


def add_transfer(
    t_start: float, t_end: float, nbytes: int, kind: str = "fetch"
) -> None:
    rec = current()
    if rec is not None:
        rec.transfers.append((t_start, t_end, int(nbytes), kind))


def note_fault(kind: str) -> None:
    """A classified device fault (exec/devicefault) crossed the active
    dispatch: stamp the lifecycle event and bump the per-kind mark so
    the flight recorder shows WHERE the ladder engaged."""
    rec = current()
    if rec is not None:
        rec.add_event("device_fault")
        rec.bump(f"device_fault.{kind}")


def note_ring(hit: bool, nbytes: int = 0) -> None:
    """ParamRing.stage outcome: a staged-slot reuse (zero host bytes)
    or a fresh explicit upload."""
    rec = current()
    if rec is None:
        return
    if hit:
        rec.bump("ring_hits")
        rec.add_event("ring_hit")
    else:
        rec.bump("ring_uploads")
        rec.bump("ring_bytes", int(nbytes))
        rec.add_event("param_upload")


def note_prefetch_start() -> None:
    rec = current()
    if rec is None:
        return
    now = time.monotonic()
    rec.bump("prefetch_starts")
    rec.marks["prefetch_start_ts"] = now
    rec.add_event("prefetch_start", now)


def note_prefetch(hit: bool, nbytes: int = 0) -> None:
    """Page-election outcome. A HIT means the elected page's copy has
    been in flight since dispatch — record that transfer as spanning
    dispatch → election, i.e. overlapped with the device work in front
    of it (kind "prefetch"), which is exactly the hidden-bytes claim
    the accounting pass scores."""
    rec = current()
    if rec is None:
        return
    if hit:
        rec.bump("prefetch_hits")
        now = time.monotonic()
        start = float(
            rec.marks.get("prefetch_start_ts") or rec.t0
        )
        rec.transfers.append((start, now, int(nbytes), "prefetch"))
    else:
        rec.bump("prefetch_misses")


# -- scrape-time gauges ------------------------------------------------------


#: publish_overlap_gauges recompute floor: the overlap pass over a full
#: ring costs ~10ms of host time, and the provider runs inside EVERY
#: registry.snapshot_all() — a fast-ticking watchdog (tests tick at
#: 50Hz; production every few seconds) must not pay it per tick. 250ms
#: keeps /metrics effectively live while bounding the cost at any rate.
_PUBLISH_MIN_INTERVAL_S = 0.25
_publish_last_ts = 0.0


def publish_overlap_gauges() -> None:
    """Refresh the ``orienttpu_overlap_*`` gauges from a bounded recent
    window (``config.timeline_window_s``). Registered as a scrape-time
    gauge provider (obs/profile), so ``/metrics``, the member-labeled
    ``/cluster/metrics`` fan-in, and every alert-engine snapshot carry
    them without any hot-path cost. Recomputes at most once per
    ``_PUBLISH_MIN_INTERVAL_S`` (consumers in between read the prior
    gauge values — a racy double recompute is harmless)."""
    from orientdb_tpu.utils.metrics import metrics

    global _publish_last_ts
    now = time.monotonic()
    if now - _publish_last_ts < _PUBLISH_MIN_INTERVAL_S:
        return
    _publish_last_ts = now
    # span-FREE accounting: this provider runs inside EVERY
    # registry.snapshot_all() (scrapes, watchdog ticks, bundles) — a
    # span here would stamp the tracer ring on every scrape and poison
    # the alert plane's newest-span exemplar fallback. The explicit
    # surfaces (overlap()/chrome_trace()) keep their cataloged spans.
    rep = recorder._overlap(
        recorder._window(config.timeline_window_s), 8
    )
    metrics.gauge("overlap.window_records", float(rep.get("records", 0)))
    if not rep.get("records"):
        # window emptied (traffic stopped): DROP the fraction gauges
        # rather than freeze their last values — a scrape must never
        # read a stale idle fraction as live data (0.0 would fabricate
        # "fully busy"; absence is the honest shape)
        metrics.drop_gauge("overlap.device_idle_fraction")
        metrics.drop_gauge("overlap.transfer_hidden_fraction")
        metrics.drop_gauge("overlap.ring_hit_fraction")
        return
    metrics.gauge(
        "overlap.device_idle_fraction",
        float(rep.get("device_idle_fraction", 0.0)),
    )
    tr = rep.get("transfer") or {}
    metrics.gauge(
        "overlap.transfer_hidden_fraction",
        float(tr.get("transfer_hidden_fraction", 0.0)),
    )
    ring = rep.get("ring") or {}
    metrics.gauge(
        "overlap.ring_hit_fraction", float(ring.get("hit_fraction", 0.0))
    )


def _register_provider() -> None:
    from orientdb_tpu.obs.profile import register_gauge_provider

    register_gauge_provider(publish_overlap_gauges)


_register_provider()
