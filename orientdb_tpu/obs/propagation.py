"""Cross-node trace propagation: the Dapper-style context carrier.

`obs/trace.py` gives every query a trace id, but a span stack is
thread- (and therefore process-) local: a forwarded write, a 2PC
phase, or a replication apply lands on another node's server thread
and mints an unrelated trace. This module carries the context across
every inter-node channel so the remote side CONTINUES the trace
instead:

- **context** — ``{"trace_id": ..., "span_id": ..., "baggage": {...}}``.
  ``span_id`` is the caller's active span; the remote side's first span
  uses it as ``parent_id``. Baggage is a small key→scalar dict that
  propagates onward across further hops (2PC puts the ``txid`` there so
  every participant span is joinable by transaction).
- **HTTP** — :func:`inject_headers` / :func:`extract_headers` move the
  context through ``X-Orienttpu-Trace-Id`` / ``-Parent-Span`` /
  ``-Baggage`` request headers (forwarding, 2PC phases, quorum pushes).
- **binary protocol** — the frame envelope carries the same dict under
  a ``"trace"`` key (:func:`inject_frame`; `binary_server` extracts).
- **WAL entries** — the originating write's context is stamped onto the
  entry (``storage/durability.WriteAheadLog.append``), so an
  asynchronous replica apply — pulled seconds later by a thread that
  never saw the request — still links back to the write that produced
  it (:func:`continue_trace` with ``force=True``).

Nothing here talks to the network; callers inject/extract at their own
channel's framing layer.
"""

from __future__ import annotations

import json
import threading
from contextlib import contextmanager
from typing import Dict, Optional

from orientdb_tpu.obs.trace import current_span, span

#: HTTP header names (one per context field; baggage is a JSON object)
HDR_TRACE_ID = "X-Orienttpu-Trace-Id"
HDR_PARENT_SPAN = "X-Orienttpu-Parent-Span"
HDR_BAGGAGE = "X-Orienttpu-Baggage"

_local = threading.local()


def current_baggage() -> Dict[str, object]:
    """The merged baggage visible on this thread (innermost wins)."""
    stack = getattr(_local, "baggage", None)
    if not stack:
        return {}
    out: Dict[str, object] = {}
    for frame in stack:
        out.update(frame)
    return out


@contextmanager
def baggage(**items):
    """Attach key→scalar items to every context captured inside the
    block; they ride along on every outbound hop and re-propagate from
    the receiving side (``continue_trace`` re-opens them there)."""
    stack = getattr(_local, "baggage", None)
    if stack is None:
        stack = _local.baggage = []
    stack.append(dict(items))
    try:
        yield
    finally:
        if stack and stack[-1] is not None:
            stack.pop()


def current_context() -> Optional[Dict]:
    """The propagatable context of this thread's active span (plus
    baggage), or None outside any trace."""
    sp = current_span()
    if sp is None:
        return None
    ctx: Dict[str, object] = {
        "trace_id": sp.trace_id,
        "span_id": sp.span_id,
    }
    bag = current_baggage()
    if bag:
        ctx["baggage"] = bag
    return ctx


# -- channel framing ---------------------------------------------------------


def inject_headers(headers: Dict, ctx: Optional[Dict] = None) -> Dict:
    """Add the context (given, or this thread's current) to an HTTP
    header dict; returns the dict. No-op outside any trace."""
    ctx = ctx if ctx is not None else current_context()
    if not ctx or not ctx.get("trace_id"):
        return headers
    headers[HDR_TRACE_ID] = str(ctx["trace_id"])
    if ctx.get("span_id"):
        headers[HDR_PARENT_SPAN] = str(ctx["span_id"])
    bag = ctx.get("baggage")
    if bag:
        try:
            headers[HDR_BAGGAGE] = json.dumps(bag, sort_keys=True)
        except (TypeError, ValueError):
            pass  # non-JSON baggage never breaks the request itself
    return headers


def extract_headers(headers) -> Optional[Dict]:
    """Context from an HTTP request's headers (an ``email.Message`` or
    any mapping with ``.get``), or None when the request carries none."""
    tid = headers.get(HDR_TRACE_ID)
    if not tid:
        return None
    ctx: Dict[str, object] = {"trace_id": tid}
    parent = headers.get(HDR_PARENT_SPAN)
    if parent:
        ctx["span_id"] = parent
    raw = headers.get(HDR_BAGGAGE)
    if raw:
        try:
            bag = json.loads(raw)
            if isinstance(bag, dict):
                ctx["baggage"] = bag
        except ValueError:
            pass  # malformed baggage: keep the trace link anyway
    return ctx


def inject_frame(frame: Dict, ctx: Optional[Dict] = None) -> Dict:
    """Binary-protocol variant: the envelope dict carries the context
    under ``"trace"``. No-op outside any trace."""
    ctx = ctx if ctx is not None else current_context()
    if ctx and ctx.get("trace_id"):
        frame["trace"] = ctx
    return frame


# -- continuing a trace ------------------------------------------------------


@contextmanager
def continue_trace(
    name: str, ctx: Optional[Dict], force: bool = False, **attrs
):
    """Open a span that CONTINUES a remote context: it adopts the
    remote trace id and parents onto the remote span, so the two sides
    assemble into one cross-node trace.

    Without a usable ``ctx`` this is exactly ``span(name, **attrs)``.
    Adoption normally applies only when this thread has no active span
    (a server thread picking up a request); ``force=True`` adopts even
    under a local parent — the replication-apply case, where the
    per-entry span must join the ORIGINATING WRITE's trace, not the
    apply batch's. Remote baggage lands in the span's attrs and is
    re-opened as local baggage so it propagates across further hops.
    """
    remote = bool(ctx and ctx.get("trace_id"))
    with span(name, **attrs) as sp:
        if remote and (force or sp.parent_id is None):
            sp.trace_id = ctx["trace_id"]
            sp.parent_id = ctx.get("span_id")
        bag = (ctx or {}).get("baggage") if remote else None
        if bag:
            for k, v in bag.items():
                sp.attrs.setdefault(k, v)
            with baggage(**bag):
                yield sp
        else:
            yield sp
