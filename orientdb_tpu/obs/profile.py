"""Continuous profiling: span-tree self-time folding + process telemetry.

Two always-on planes that turn the raw obs primitives into aggregate
evidence:

- **SpanProfileAggregator** — a tracer listener that folds every
  finished LOCAL span tree into a cumulative per-stage *self-time*
  profile (flamegraph-style ``{name, self_ms, total_ms, count,
  children}``). Self time is a span's duration minus its children's —
  the number that says WHERE wall clock goes (e.g. ``query`` →
  ``tpu.step`` hops vs marshalling) without double counting. Governed
  by the same ``config.stats_sample_rate`` knob as the stats table;
  folding costs one dict merge per span, cheap enough to leave on.
- **gauge providers** — callables run at every registry scrape
  (``registry.snapshot_all``) that refresh memory/process gauges in the
  existing registry: RSS, thread count, uptime, live jax buffer bytes,
  snapshot column/adjacency bytes, and WAL segment bytes per attached
  database (``register_server_telemetry`` wires a server's databases
  in at startup).

Spans that continue a REMOTE trace (propagation) fold when their local
outermost span exits; a trace whose root lives on another node
contributes its local subtree only — per-stage profiles are about this
process's execution stages.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from orientdb_tpu.obs.stats import sampled
from orientdb_tpu.utils.config import config

_START_TS = time.time()


# ---------------------------------------------------------------------------
# span-profile aggregation
# ---------------------------------------------------------------------------


class _Node:
    __slots__ = ("name", "count", "self_us", "total_us", "children")

    def __init__(self, name: str) -> None:
        self.name = name
        self.count = 0
        self.self_us = 0.0
        self.total_us = 0.0
        self.children: Dict[str, "_Node"] = {}

    def to_dict(self) -> Dict[str, object]:
        return {
            "name": self.name,
            "count": self.count,
            "self_ms": round(self.self_us / 1000.0, 3),
            "total_ms": round(self.total_us / 1000.0, 3),
            "children": [
                c.to_dict()
                for c in sorted(
                    self.children.values(),
                    key=lambda n: n.total_us,
                    reverse=True,
                )
            ],
        }


class SpanProfileAggregator:
    """Accumulates finished span trees into one cumulative profile.

    Spans arrive in finish order (children before parents); they are
    parked per (trace id, THREAD) and folded when that thread's span
    stack empties — at that point every descendant recorded by the
    thread is present. Keying by thread matters: a force-joined trace
    (an in-process replica apply joining the write's trace) finishes
    spans of ONE trace on several threads, and a trace-only key would
    let the first idle thread consume another thread's still-open
    subtree — misattributing children as roots and double-counting the
    parent's self time. Unfinished traces age out of the bounded
    pending map.
    """

    _PENDING_MAX = 256
    _SAMPLED_OUT = ()  # sentinel: trace sampled out, drop its spans

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._pending: Dict[str, object] = {}
        self._pending_order: deque = deque()
        self._root = _Node("")
        self._traces = 0

    # -- ingestion (tracer listener) ----------------------------------------

    def on_span(self, sp) -> None:
        """Tracer listener: called once per finished span, on the span's
        own thread (so the thread-local span stack tells us whether this
        was the outermost)."""
        from orientdb_tpu.obs.trace import current_span

        if config.stats_sample_rate <= 0:  # plane disabled: no lock,
            return  # no pending bookkeeping
        key = (sp.trace_id, threading.get_ident())
        with self._lock:
            rec = self._pending.get(key)
            if rec is None:
                rec = [] if sampled() else self._SAMPLED_OUT
                self._pending[key] = rec
                self._pending_order.append(key)
                while len(self._pending_order) > self._PENDING_MAX:
                    old = self._pending_order.popleft()
                    self._pending.pop(old, None)
            if rec is not self._SAMPLED_OUT and isinstance(rec, list):
                rec.append(
                    (sp.span_id, sp.parent_id, sp.name, sp.duration_us or 0.0)
                )
        # outermost on this thread: every descendant THIS thread
        # recorded for the trace has finished
        if current_span() is None:
            self._fold(key)

    def _fold(self, key) -> None:
        with self._lock:
            rec = self._pending.pop(key, None)
            if rec is None:
                return
            # drop the order entry for sampled-out traces too, or stale
            # ids eat the eviction window and evict LIVE traces
            try:
                self._pending_order.remove(key)
            except ValueError:
                pass
            if not rec or rec is self._SAMPLED_OUT:
                return
            by_id = {sid: (sid, pid, name, dur) for sid, pid, name, dur in rec}
            kids: Dict[Optional[str], List] = {}
            for sid, pid, name, dur in rec:
                parent = pid if pid in by_id else None
                kids.setdefault(parent, []).append((sid, name, dur))

            def merge(node: _Node, sid: str, name: str, dur: float) -> None:
                child = node.children.get(name)
                if child is None:
                    child = node.children[name] = _Node(name)
                child.count += 1
                child.total_us += dur
                child_dur = 0.0
                for csid, cname, cdur in kids.get(sid, ()):
                    child_dur += cdur
                    merge(child, csid, cname, cdur)
                child.self_us += max(dur - child_dur, 0.0)

            for sid, name, dur in kids.get(None, ()):
                merge(self._root, sid, name, dur)
            self._traces += 1

    # -- reading ------------------------------------------------------------

    def profile(self) -> Dict[str, object]:
        """The cumulative flamegraph-style profile."""
        with self._lock:
            return {
                "traces": self._traces,
                "pending": len(self._pending),
                "stages": self._root.to_dict()["children"],
            }

    def flat(self, k: int = 20) -> List[Dict[str, object]]:
        """Top-``k`` stages by cumulative SELF time, flattened across
        the tree (the console's ``STATS PROFILE`` view)."""
        agg: Dict[str, Dict[str, float]] = {}

        def walk(node: _Node) -> None:
            for c in node.children.values():
                a = agg.setdefault(
                    c.name, {"count": 0, "self_us": 0.0, "total_us": 0.0}
                )
                a["count"] += c.count
                a["self_us"] += c.self_us
                a["total_us"] += c.total_us
                walk(c)

        with self._lock:
            walk(self._root)
        rows = [
            {
                "name": name,
                "count": int(a["count"]),
                "self_ms": round(a["self_us"] / 1000.0, 3),
                "total_ms": round(a["total_us"] / 1000.0, 3),
            }
            for name, a in agg.items()
        ]
        rows.sort(key=lambda r: r["self_ms"], reverse=True)
        return rows[: max(k, 0)]

    def reset(self) -> None:
        with self._lock:
            self._pending.clear()
            self._pending_order.clear()
            self._root = _Node("")
            self._traces = 0


#: the process-wide aggregator, registered as a tracer listener on
#: import (obs/__init__ imports this module, and every stats consumer
#: imports through the package)
profiler = SpanProfileAggregator()


def _install() -> None:
    from orientdb_tpu.obs.trace import tracer

    tracer.add_listener(profiler.on_span)


_install()


# ---------------------------------------------------------------------------
# memory / process telemetry gauge providers
# ---------------------------------------------------------------------------

_providers: List[Callable[[], None]] = []
_providers_lock = threading.Lock()


def register_gauge_provider(fn: Callable[[], None]) -> None:
    """Register a callable run at every registry scrape to refresh
    gauges; exceptions are swallowed (telemetry must never fail a
    scrape)."""
    with _providers_lock:
        if fn not in _providers:
            _providers.append(fn)


def unregister_gauge_provider(fn: Callable[[], None]) -> None:
    with _providers_lock:
        try:
            _providers.remove(fn)
        except ValueError:
            pass


def run_gauge_providers() -> None:
    with _providers_lock:
        fns = list(_providers)
    for fn in fns:
        try:
            fn()
        except Exception:
            pass


def _rss_bytes() -> Optional[int]:
    try:  # /proc is the live number; getrusage's maxrss is a peak
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        return pages * os.sysconf("SC_PAGE_SIZE")
    except Exception:
        try:
            import resource

            return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
        except Exception:
            return None


def process_telemetry() -> None:
    """RSS / thread count / uptime / live jax buffer bytes — the
    default provider, registered at import."""
    from orientdb_tpu.utils.metrics import metrics

    rss = _rss_bytes()
    if rss is not None:
        metrics.gauge("proc.rss_bytes", rss)
    metrics.gauge("proc.threads", threading.active_count())
    metrics.gauge("proc.uptime_s", round(time.time() - _START_TS, 3))
    try:
        import jax

        arrs = jax.live_arrays()
        metrics.gauge(
            "jax.live_buffer_bytes",
            sum(int(getattr(a, "nbytes", 0)) for a in arrs),
        )
        metrics.gauge("jax.live_buffer_count", len(arrs))
    except Exception:
        pass


register_gauge_provider(process_telemetry)


def _snapshot_bytes(db) -> Dict[str, int]:
    """Host-side snapshot memory by category for one database: vertex
    property columns, adjacency (CSR arrays), edge property columns."""
    out = {"columns": 0, "adjacency": 0, "edge_columns": 0}
    snap = db.current_snapshot()
    if snap is None:
        return out
    for col in snap.v_columns.values():
        for arr in (getattr(col, "values", None), getattr(col, "present", None)):
            if arr is not None:
                out["columns"] += int(getattr(arr, "nbytes", 0))
    for dec in snap.edge_classes.values():
        for name in ("indptr_out", "indptr_in", "dst", "src", "edge_id_in"):
            arr = getattr(dec, name, None)
            if arr is not None:
                out["adjacency"] += int(getattr(arr, "nbytes", 0))
        for col in getattr(dec, "columns", {}).values():
            for arr in (
                getattr(col, "values", None),
                getattr(col, "present", None),
            ):
                if arr is not None:
                    out["edge_columns"] += int(getattr(arr, "nbytes", 0))
    return out


def _wal_bytes(db) -> int:
    """Live WAL file plus archived ``wal-*.log`` segments next to it."""
    wal = getattr(db, "_wal", None)
    path = getattr(wal, "path", None)
    if not path:
        return 0
    total = 0
    try:
        if os.path.exists(path):
            total += os.path.getsize(path)
        d = os.path.dirname(os.path.abspath(path))
        for f in os.listdir(d):
            if f.startswith("wal-") and f.endswith(".log"):
                total += os.path.getsize(os.path.join(d, f))
    except OSError:
        pass
    return total


def database_telemetry(dbs_fn: Callable[[], List]) -> Callable[[], None]:
    """Build a provider publishing per-process totals over ``dbs_fn()``:
    snapshot column/adjacency bytes and WAL segment bytes."""

    def provider() -> None:
        from orientdb_tpu.utils.metrics import metrics

        cols = adj = ecols = wal = 0
        for db in dbs_fn():
            b = _snapshot_bytes(db)
            cols += b["columns"]
            adj += b["adjacency"]
            ecols += b["edge_columns"]
            wal += _wal_bytes(db)
        metrics.gauge("snapshot.column_bytes", cols)
        metrics.gauge("snapshot.adjacency_bytes", adj)
        metrics.gauge("snapshot.edge_column_bytes", ecols)
        metrics.gauge("wal.segment_bytes", wal)

    return provider


def register_server_telemetry(server) -> Callable[[], None]:
    """Wire a server's databases into the scrape-time telemetry; returns
    the provider (callers keep it to unregister at shutdown). The
    provider holds the server WEAKLY: a server abandoned without
    shutdown() (crash-restart tests) must not be pinned — with its
    multi-GB snapshots — for process lifetime; a dead ref unregisters
    itself on the next scrape."""
    import weakref

    ref = weakref.ref(server)

    def dbs() -> List:
        srv = ref()
        if srv is None:
            unregister_gauge_provider(provider)
            return []
        return list(getattr(srv, "databases", {}).values())

    provider = database_telemetry(dbs)
    register_gauge_provider(provider)
    return provider
