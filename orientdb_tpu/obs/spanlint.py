"""AST lint: every span name literal in the codebase is cataloged.

The profile aggregator (``obs/profile.py``) groups stages by span NAME
and cross-node traces join on the names both sides emit — a typo'd
name in a new ``span("replication.aply")`` would silently split a
stage out of every profile and break trace joins, with no test to
notice. This lint (now the ``spanlint`` pass of ``orientdb_tpu/analysis``,
enforced tier-1 by ``tests/test_analysis.py``; ``lint_spans`` below
stays as a back-compat shim) makes that a build failure:

- every **string-literal** first argument of a ``span(...)`` /
  ``_span(...)`` / ``continue_trace(...)`` / ``_bench_span(...)``
  call under ``orientdb_tpu/`` and in ``bench.py`` must appear in
  :data:`SPAN_CATALOG`;
- every catalog entry must be used by at least one call site (a stale
  entry is dead documentation).

Dynamically named spans (f-strings like ``f"http.{verb}"``) cannot be
linted literal-by-literal; their families are documented in
:data:`DYNAMIC_FAMILIES` instead. Tests are exempt — ad-hoc span names
there are fixtures, not stages.

The catalog doubles as the span-name reference the README links.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Tuple

#: span name → what the stage covers. The profile aggregator's stage
#: names and the cross-node trace vocabulary, in one place.
SPAN_CATALOG: Dict[str, str] = {
    "query": "engine front door: one idempotent statement via query()",
    "command": "engine front door: one statement via command()",
    "query_batch": "batched front door: N statements, one dispatch wave",
    "profile": "EXPLAIN PROFILE execution of the inner statement",
    "tpu.load": "device-graph upload / fetch for a compiled execution",
    "tpu.solve": "compiled MATCH/TRAVERSE solve (recording execution)",
    "tpu.step": "one compiled plan step (root scan / expansion hop)",
    "tpu.marshal": "device results → host rows marshalling",
    "tpu.dispatch": "compiled replay dispatch (profile_execute)",
    "tpu.device": "device execution sync (profile_execute)",
    "tx.commit": "local transaction commit (MVCC checks + WAL append)",
    "tx2pc.coordinate": "2PC coordinator round (prepare + decide)",
    "tx2pc.participant.prepare": "2PC phase 1: validate + lock + stage",
    "tx2pc.participant.commit": "2PC phase 2: execute the staged batch",
    "tx2pc.participant.abort": "2PC abort: release the staged batch",
    "wal.append": "write-ahead-log append (+fsync when configured)",
    "replication.apply": "replica apply batch (push or pull)",
    "replication.apply_entry": "one WAL entry applied on a replica "
    "(joins the originating write's trace)",
    "forward.request": "non-owner → write-owner HTTP forward",
    "bench.block": "one measured bench block (evidence carries its "
    "trace id)",
    "coalesce.lane": "cross-session micro-batching: one item's stay in "
    "its fingerprint lane, enqueue through result (submitter side)",
    "coalesce.dispatch": "one lane micro-batch executed on the lane "
    "worker (continues the first submitter's trace; lane/batch attrs)",
    "snapshot.delta.apply": "one CDC delta batch applied device-side "
    "to a maintained snapshot (storage/deltas: packed scatter "
    "segments, no re-upload)",
    "snapshot.compact": "epoch compaction: slabs folded back into a "
    "clean CSR (rebuild + optional content-addressed epoch persist)",
    "cdc.catchup": "changefeed catch-up read: WAL entries above a "
    "consumer's cursor decoded to events",
    "cdc.push": "one changefeed delivery (binary push frame or HTTP "
    "/changes long-poll response)",
    "watchdog.tick": "one health-watchdog alert-rule evaluation round "
    "(obs/watchdog; never on the query hot path)",
    "workload.run": "one closed-loop traffic-simulator run "
    "(workloads/driver.TrafficSim: sessions + chaos + settle)",
    "workload.session": "one simulated client session's closed-loop "
    "op sequence (HTTP or binary transport)",
    "slo.evaluate": "one SLO-verdict evaluation over a run window "
    "(obs/slo: stats-table deltas + alert state + burn policy)",
    "timeline.overlap": "one overlap-accounting pass over the flight "
    "recorder's recent window (obs/timeline: scrape-time gauges, "
    "bench evidence, the alert rule's signal)",
    "timeline.export": "Chrome-trace/Perfetto export of the flight "
    "recorder window (GET /debug/timeline, debug bundle, bench "
    "TIMELINE artifact)",
    "tier.prefetch": "tiered snapshot cold-block upload wave "
    "(storage/tiering: recording fault or dispatch footprint ensure; "
    "recorded as prefetch-kind transfers in the flight recorder)",
    "tier.evict": "tiered snapshot block eviction (owner row cleared, "
    "page recycled under tier_hbm_cap_bytes pressure)",
    "memledger.reconcile": "device-memory ledger reconciliation pass "
    "(obs/memledger: ledger totals diffed against jax.live_arrays — "
    "untracked = instrumentation gap, tracked-but-dead = leak "
    "candidate, dead transients pruned)",
    "devicefault.escalate": "device fault escalation (exec/devicefault: "
    "retries exhausted or persistent fault — quarantine + optional "
    "admission shed; attrs carry stage, kind, relief actions)",
    "audit.shadow": "one shadow-oracle parity audit (exec/audit: "
    "oracle re-execution + digest compare on the background worker; "
    "attrs carry the verdict — parity / diverged / stale)",
    "scrub.sweep": "one budgeted device-state scrub rotation "
    "(storage/scrub: device blocks fetched + re-hashed against "
    "host-truth checksums under scrub_budget_bytes)",
    "scrub.repair": "one scrub repair-ladder walk for a corrupt device "
    "key (storage/scrub: tier-block reload → overlay poison/compact → "
    "full snapshot re-upload; attrs carry the rung taken)",
}

#: dynamically named span families (f-string call sites the literal
#: lint cannot see) — documented here so the catalog stays the one
#: reference for every name shape in the ring
DYNAMIC_FAMILIES: Dict[str, str] = {
    "http.<verb>": "HTTP listener request (server/http_server._traced)",
    "binary.<op>": "binary-protocol op (server/binary_server)",
}

#: call names whose first positional string argument is a span name
#: (bench's block_span() helper takes a block TAG, not a span name —
#: its inner _bench_span("bench.block", ...) literal is what's linted)
SPAN_CALLS = frozenset({"span", "_span", "continue_trace", "_bench_span"})


def _literal_span_names(tree: ast.Module) -> List[Tuple[int, str]]:
    out: List[Tuple[int, str]] = []
    for n in ast.walk(tree):
        if not isinstance(n, ast.Call):
            continue
        f = n.func
        if not (isinstance(f, ast.Name) and f.id in SPAN_CALLS):
            continue
        if (
            n.args
            and isinstance(n.args[0], ast.Constant)
            and isinstance(n.args[0].value, str)
        ):
            out.append((n.lineno, n.args[0].value))
    return out


def lint_spans(root: str = None) -> List[str]:
    """Legacy entry point — now a thin shim over the framework pass
    (``orientdb_tpu.analysis``, pass ``spanlint``): shared discovery,
    per-line suppressions, and reporting. Returns problems (empty =
    every literal span name is cataloged and every catalog entry is
    live)."""
    from orientdb_tpu.analysis import core

    rep = core.run(passes=["spanlint"], root=root)
    # the old contract also reported unparsable modules
    return [
        str(f)
        for f in rep.findings
        if f.pass_name in ("spanlint", "parse")
    ]
