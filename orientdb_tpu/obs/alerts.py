"""SLO alerting: a declarative rule engine over the obs registries.

Everything before this PR *exposes* state — spans, the Prometheus
exposition, per-fingerprint query stats, cluster fan-in — but nothing
*watches* it: a replica falling behind or a latency regression on a
hot fingerprint is only found when a human scrapes an endpoint. This
module closes the loop (the Monarch/Dapper-lineage "monitoring must
alert, not just record" argument):

- :data:`RULE_CATALOG` — the built-in rule set, one name + description
  per rule (the operator-facing index; ``alertlint`` keeps call sites
  and catalog in sync the way spanlint does for span names);
- :class:`AlertEngine` — evaluates every rule over one combined
  signal snapshot (``registry.snapshot_all()``: counters/gauges/
  histograms/query stats, plus breaker and cluster state) and drives
  the alert lifecycle **pending → firing → resolved** with dedupe by
  ``(rule, key)`` and a bounded resolved-history ring. Conditions are
  plain thresholds or two-window burn rates; the latency-regression
  rule learns an online EWMA+MAD baseline per fingerprint from the
  PR-4 stats table;
- **exemplars** — an alert that fires captures the trace id of the
  worst matching slowlog entry (latency/error rules) or the newest
  matching span in the tracer ring, so every alert links directly
  into the trace plane.

Evaluation happens ONLY at watchdog tick (obs/watchdog) or on demand —
the query hot path never touches this module. Reading state
(:meth:`AlertEngine.export` at scrape time, :meth:`AlertEngine.report`
for ``GET /alerts``) is a short lock + copy.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from orientdb_tpu.utils.config import config
from orientdb_tpu.utils.logging import get_logger
from orientdb_tpu.utils.metrics import metrics

log = get_logger("alerts")

#: rule name -> what it watches. The alert vocabulary in one place:
#: ``alertlint`` (orientdb_tpu/analysis) fails the build when a
#: ``_rule(...)`` call site names something not listed here, or a
#: catalog entry goes stale. Doubles as the README's rule reference.
RULE_CATALOG: Dict[str, str] = {
    "replication_lag": "a replica's applied LSN trails the source head "
    "by more than alert_repl_lag_entries entries",
    "breaker_open": "a circuit breaker (parallel/resilience) is OPEN — "
    "its channel is failing fast",
    "indoubt_2pc_age": "a prepared-undecided 2PC batch has been staged "
    "longer than alert_indoubt_age_s (locks held, outcome unknown)",
    "cdc_backlog": "a changefeed consumer's queue depth or entry lag "
    "exceeds alert_cdc_queue_depth (slow consumer / gap risk)",
    "wal_growth": "live WAL + archived segments exceed alert_wal_bytes "
    "(checkpointing is not keeping up)",
    "rss_watermark": "process RSS exceeds alert_rss_bytes",
    "jax_buffer_watermark": "live jax device-buffer bytes exceed "
    "alert_jax_buffer_bytes (HBM pressure)",
    "recompile_storm": "shape-overflow recompiles per minute exceed "
    "alert_recompiles_per_min (plan cache thrash)",
    "latency_regression": "a fingerprint's per-tick mean latency "
    "exceeds its online EWMA baseline by alert_latency_mads deviations; "
    "carries a critical-path blame annotation (obs/critpath) naming the "
    "segment(s) that grew, with the worst request's trace as exemplar",
    "error_burn_rate": "query error rate burns the SLO error budget at "
    "more than alert_burn_factor x in BOTH burn windows",
    "overlap_regression": "the dispatch timeline's device-idle "
    "fraction (obs/timeline overlap accounting) exceeds its online "
    "EWMA baseline by alert_overlap_idle_mads deviations — the "
    "overlap machinery (prefetch, rings, double buffering) stopped "
    "hiding work",
    "delta_slab_pressure": "a delta-maintained snapshot's fullest "
    "append slab (snapshot.delta.slab_fill, storage/deltas) exceeds "
    "alert_slab_fill — deltas are outpacing epoch compaction",
    "tier_thrash": "a tiered snapshot (storage/tiering) is reloading "
    "recently evicted blocks faster than alert_tier_thrash events per "
    "window (tier.thrash gauge) — the hot working set does not fit "
    "tier_hbm_cap_bytes and dispatches are churning the pool",
    "hbm_epoch_leak": "a snapshot epoch's dispatch lease "
    "(GraphSnapshot.retain) has been outstanding longer than "
    "memledger_leak_s — its refcount pins device buffers with no "
    "dispatch retiring it (obs/memledger; the exemplar is the "
    "retaining lease's own trace id)",
    "hbm_headroom": "attributed device-memory ledger bytes "
    "(hbm.ledger_bytes) crossed memledger_headroom_fraction of the "
    "tier plane's HBM budget (tier.cap_bytes / tier_hbm_cap_bytes) — "
    "the next pool grow or snapshot upload may not fit",
    "device_fault_storm": "classified device faults (exec/devicefault: "
    "oom + transient + persistent across every dispatch path) per "
    "minute exceed alert_device_faults_per_min — the device is failing "
    "faster than the escalation ladder can contain",
    "parity_divergence": "the shadow-oracle parity auditor "
    "(exec/audit) convicted a fingerprint: a compiled result's "
    "canonical digest disagrees with the oracle's — the fingerprint "
    "is quarantined (oracle serves degraded-but-correct) until a "
    "clean probe; the exemplar is the divergent request's trace id",
    "scrub_corruption": "the device-state scrubber (storage/scrub) "
    "found device bytes that disagree with their host-truth checksum "
    "since the last clean sweep — the repair ladder (tier reload → "
    "overlay poison/compaction → full re-upload) was engaged",
}

#: two-window burn-rate windows (seconds): the short window catches the
#: spike, the long window keeps a transient blip from paging
BURN_SHORT_S = 60.0
BURN_LONG_S = 600.0

#: EWMA smoothing for the latency baseline (per tick interval)
_EWMA_ALPHA = 0.3
#: intervals a baseline must absorb before it can flag a regression
_BASELINE_WARMUP = 3
#: deviation floor (seconds): sub-100µs MADs are pure jitter
_MAD_FLOOR_S = 1e-4


def alert_gauge(name: str, value: float) -> None:
    """Publish one watchdog summary gauge into the process registry
    (so ``/metrics`` and the ``/cluster/metrics`` fan-in carry the
    alert plane's own health). promlint's AST half checks literal
    names at these call sites exactly like ``metrics.gauge`` ones."""
    metrics.gauge(name, value)


class Breach:
    """One rule violation observed in one tick: the dedupe key (e.g. a
    member name, breaker name, or fingerprint id), the measured value,
    the threshold it crossed, and a human detail line."""

    __slots__ = ("key", "value", "threshold", "detail", "trace_id", "blame")

    def __init__(
        self,
        key: str,
        value: float,
        threshold: float,
        detail: str,
        trace_id: Optional[str] = None,
        blame: Optional[Dict] = None,
    ) -> None:
        self.key = key
        self.value = value
        self.threshold = threshold
        self.detail = detail
        #: a breach that KNOWS its exemplar (e.g. the retaining lease's
        #: trace id for hbm_epoch_leak) carries it; _exemplar prefers
        #: this over the slowlog/span-ring heuristics
        self.trace_id = trace_id
        #: critical-path blame annotation (obs/critpath.plane.blame):
        #: which segment(s) of the fingerprint's decomposition grew
        self.blame = blame


class AlertRule:
    """One declarative rule: a check callable returning this tick's
    breaches, plus how to find an exemplar trace when it fires
    (``exemplar="slowlog"`` joins the worst matching slowlog entry;
    ``exemplar_spans`` prefixes match the newest span in the ring)."""

    __slots__ = ("name", "severity", "check", "exemplar", "exemplar_spans")

    def __init__(
        self,
        name: str,
        severity: str,
        check: Callable[["AlertEngine", "AlertContext"], Iterable[Breach]],
        exemplar: str = "span",
        exemplar_spans: Tuple[str, ...] = (),
    ) -> None:
        self.name = name
        self.severity = severity
        self.check = check
        self.exemplar = exemplar
        self.exemplar_spans = exemplar_spans


class AlertContext:
    """The signals one evaluation tick sees: a ``snapshot_all()``
    registry snapshot plus this server's databases and cluster."""

    __slots__ = ("now", "snap", "dbs", "cluster")

    def __init__(self, now: float, snap: Dict, dbs, cluster) -> None:
        self.now = now
        self.snap = snap
        self.dbs = list(dbs)
        self.cluster = cluster

    @property
    def gauges(self) -> Dict[str, float]:
        return self.snap.get("gauges", {})

    @property
    def query_stats(self) -> Dict[str, Dict]:
        return self.snap.get("query_stats", {}) or {}


class Alert:
    """One deduped alert instance through its lifecycle."""

    __slots__ = (
        "rule",
        "key",
        "severity",
        "state",
        "value",
        "threshold",
        "detail",
        "since_ts",
        "last_ts",
        "resolved_ts",
        "streak",
        "exemplar_trace_id",
        "blame",
    )

    def __init__(self, rule: AlertRule, br: Breach, now: float) -> None:
        self.rule = rule.name
        self.key = br.key
        self.severity = rule.severity
        self.state = "pending"
        self.value = br.value
        self.threshold = br.threshold
        self.detail = br.detail
        self.since_ts = now
        self.last_ts = now
        self.resolved_ts: Optional[float] = None
        self.streak = 1
        self.exemplar_trace_id: Optional[str] = None
        self.blame: Optional[Dict] = br.blame

    def to_dict(self) -> Dict[str, object]:
        out: Dict[str, object] = {
            "rule": self.rule,
            "key": self.key,
            "severity": self.severity,
            "state": self.state,
            "value": round(float(self.value), 6),
            "threshold": round(float(self.threshold), 6),
            "detail": self.detail,
            "since_ts": round(self.since_ts, 3),
            "last_ts": round(self.last_ts, 3),
            "exemplar_trace_id": self.exemplar_trace_id,
        }
        if self.blame is not None:
            out["blame"] = self.blame
        if self.resolved_ts is not None:
            out["resolved_ts"] = round(self.resolved_ts, 3)
        return out


class _Baseline:
    """Online EWMA + EWMA-of-absolute-deviation (the streaming MAD
    analog) of one fingerprint's per-tick mean latency."""

    __slots__ = ("ewma_s", "mad_s", "n")

    def __init__(self) -> None:
        self.ewma_s = 0.0
        self.mad_s = 0.0
        self.n = 0

    def update(self, mean_s: float) -> None:
        if self.n == 0:
            self.ewma_s = mean_s
        else:
            dev = abs(mean_s - self.ewma_s)
            self.mad_s += _EWMA_ALPHA * (dev - self.mad_s)
            self.ewma_s += _EWMA_ALPHA * (mean_s - self.ewma_s)
        self.n += 1

    def exceeds(self, value: float, mads: float, floor: float) -> bool:
        """True when ``value`` sits more than ``mads`` deviations above
        the learned level (deviations floored at ``floor`` — the
        signal's jitter scale)."""
        if self.n < _BASELINE_WARMUP:
            return False
        return value > self.ewma_s + mads * max(self.mad_s, floor)

    def threshold(self, mads: float, floor: float) -> float:
        return self.ewma_s + mads * max(self.mad_s, floor)

    def breaches(self, mean_s: float) -> bool:
        return self.exceeds(
            mean_s, config.alert_latency_mads, _MAD_FLOOR_S
        )


class AlertEngine:
    """The process-wide rule evaluator + alert lifecycle store."""

    def __init__(self, history_capacity: Optional[int] = None) -> None:
        self._mu = threading.Lock()
        #: serializes whole evaluation ticks: several in-process
        #: servers each run a watchdog over this shared engine (the
        #: process-singleton compromise every obs registry makes), so
        #: the learning state below must never see two interleaved
        #: rule phases. Readers only ever need _mu.
        self._eval_mu = threading.Lock()
        self._active: Dict[Tuple[str, str], Alert] = {}
        self._history: deque = deque()
        #: None = read config.alert_history_capacity live (retunable)
        self._history_capacity = history_capacity
        self._ticks = 0
        self._fired_total = 0
        self._resolved_total = 0
        self._last_tick_ts: Optional[float] = None
        # online learning / windowed state (written only under
        # _eval_mu; read under _mu by summary())
        self._baselines: Dict[str, _Baseline] = {}
        self._overlap_baseline = _Baseline()
        self._prev_qs: Dict[str, Tuple[int, float, int]] = {}
        self._prev_recompiles: Optional[int] = None
        self._prev_recompiles_ts = 0.0
        self._prev_device_faults: Optional[int] = None
        self._prev_device_faults_ts = 0.0
        self._indoubt_seen: Dict[Tuple[str, str], float] = {}
        self._burn_samples: deque = deque()  # (ts, calls, errors)

    # -- evaluation (tick-time only, never the query hot path) ---------------

    def evaluate(
        self, dbs=(), cluster=None, snap: Optional[Dict] = None
    ) -> Dict[str, int]:
        """One tick: gather signals, run every rule, advance alert
        lifecycles. Returns ``{"fired": n, "resolved": n}`` for this
        tick (the watchdog logs transitions). Whole ticks serialize
        under ``_eval_mu`` — concurrent watchdogs (one per in-process
        server) must never interleave rule phases over the shared
        learning state."""
        with self._eval_mu:
            return self._evaluate_locked(dbs, cluster, snap)

    def _evaluate_locked(self, dbs, cluster, snap):
        from orientdb_tpu.obs.registry import snapshot_all

        now = time.time()
        if snap is None:
            snap = snapshot_all()
        ctx = AlertContext(now, snap, dbs, cluster)
        breaches: Dict[Tuple[str, str], Tuple[AlertRule, Breach]] = {}
        for rule in BUILTIN_RULES:
            try:
                for br in rule.check(self, ctx):
                    breaches[(rule.name, br.key)] = (rule, br)
            except Exception:  # a broken signal must not kill the tick
                log.exception("alert rule %s evaluation failed", rule.name)
        # fold the per-fingerprint cumulative table forward ONCE per
        # tick, after EVERY rule consumed this tick's deltas — the
        # latency and burn rules both difference against it, so the
        # update cannot live inside either rule's generator (a
        # reordered or failed rule would silently stale the deltas)
        for fid, row in ctx.query_stats.items():
            self._prev_qs[fid] = (
                int(row.get("calls", 0)),
                float(row.get("total_s", 0.0)),
                int(row.get("errors", 0)),
            )
        fired = resolved = 0
        pending_ticks = max(int(config.alert_pending_ticks), 1)
        with self._mu:
            self._ticks += 1
            self._last_tick_ts = now
            for ident, (rule, br) in breaches.items():
                a = self._active.get(ident)
                if a is None:
                    a = self._active[ident] = Alert(rule, br, now)
                else:
                    a.value = br.value
                    a.threshold = br.threshold
                    a.detail = br.detail
                    if br.blame is not None:
                        a.blame = br.blame
                    a.last_ts = now
                    a.streak += 1
                if a.state == "pending" and a.streak >= pending_ticks:
                    a.state = "firing"
                    a.exemplar_trace_id = self._exemplar(rule, br)
                    fired += 1
            for ident in list(self._active):
                if ident in breaches:
                    continue
                a = self._active.pop(ident)
                if a.state == "firing":
                    a.state = "resolved"
                    a.resolved_ts = now
                    resolved += 1
                    self._push_history(a)
                # a pending alert that clears before firing drops
                # silently (it never alerted anyone)
            n_firing = sum(
                1 for a in self._active.values() if a.state == "firing"
            )
            n_pending = len(self._active) - n_firing
            self._fired_total += fired
            self._resolved_total += resolved
        if fired:
            metrics.incr("alerts.fired", fired)
        if resolved:
            metrics.incr("alerts.resolved", resolved)
        alert_gauge("alerts.firing", n_firing)
        alert_gauge("alerts.pending", n_pending)
        alert_gauge("alerts.baselines", len(self._baselines))
        return {"fired": fired, "resolved": resolved}

    def _push_history(self, a: Alert) -> None:
        cap = (
            self._history_capacity
            if self._history_capacity is not None
            else config.alert_history_capacity
        )
        self._history.append(a.to_dict())
        while len(self._history) > max(int(cap), 1):
            self._history.popleft()

    def _exemplar(self, rule: AlertRule, br: Breach) -> Optional[str]:
        """The trace id this alert links to: the worst matching
        slowlog entry for latency/error rules, else the newest span
        whose name matches the rule's families, else the newest span
        at all (something recent beats nothing)."""
        from orientdb_tpu.obs.slowlog import slowlog
        from orientdb_tpu.obs.trace import tracer

        if br.trace_id is not None:
            return br.trace_id
        if rule.exemplar == "slowlog":
            best = None
            for e in slowlog.entries():
                if e.get("trace_id") is None:
                    continue
                if e.get("fingerprint") not in (None, br.key):
                    continue
                if best is None or e["ms"] > best["ms"]:
                    best = e
            if best is not None:
                return best["trace_id"]
        spans = tracer.spans()
        if rule.exemplar_spans:
            for sp in reversed(spans):
                if sp.name.startswith(rule.exemplar_spans):
                    return sp.trace_id
        return spans[-1].trace_id if spans else None

    # -- reading (scrape-time) ----------------------------------------------

    def export(self) -> Dict[str, Dict[str, int]]:
        """Scalar per-rule counts for ``registry.snapshot_all`` — the
        unit the exposition renders (``orienttpu_alert_firing{rule=…}``)
        and ``/cluster/metrics`` fans in per member. Every catalog rule
        is present (zeros included) so the series always exist."""
        out = {r: {"firing": 0, "pending": 0} for r in RULE_CATALOG}
        with self._mu:
            for a in self._active.values():
                slot = out.setdefault(a.rule, {"firing": 0, "pending": 0})
                slot[a.state if a.state == "firing" else "pending"] += 1
        return out

    def active(self) -> List[Dict]:
        """Active (pending + firing) alerts, firing first."""
        with self._mu:
            items = [a.to_dict() for a in self._active.values()]
        items.sort(key=lambda a: (a["state"] != "firing", a["rule"], a["key"]))
        return items

    def history(self, limit: Optional[int] = None) -> List[Dict]:
        """Resolved alerts, most recent first."""
        with self._mu:
            items = list(self._history)
        items.reverse()
        return items if limit is None else items[:limit]

    def summary(self) -> Dict[str, object]:
        """The watchdog evidence record: rules evaluated, lifecycle
        totals, learned-baseline count, and tick freshness."""
        with self._mu:
            n_firing = sum(
                1 for a in self._active.values() if a.state == "firing"
            )
            last = self._last_tick_ts
            return {
                "rules": len(RULE_CATALOG),
                "ticks": self._ticks,
                "firing": n_firing,
                "pending": len(self._active) - n_firing,
                "fired_total": self._fired_total,
                "resolved_total": self._resolved_total,
                "baselines": len(self._baselines),
                "last_tick_ts": round(last, 3) if last else None,
                "tick_age_s": (
                    round(time.time() - last, 3) if last else None
                ),
            }

    def report(self) -> Dict[str, object]:
        """The ``GET /alerts`` JSON document."""
        return {
            "ts": round(time.time(), 3),
            "summary": self.summary(),
            "alerts": self.active(),
            "history": self.history(50),
        }

    def reset(self) -> None:
        with self._eval_mu:  # never mid-tick: ticks see reset state whole
            with self._mu:
                self._active.clear()
                self._history.clear()
                self._ticks = 0
                self._fired_total = 0
                self._resolved_total = 0
                self._last_tick_ts = None
                self._baselines.clear()
                self._overlap_baseline = _Baseline()
                self._prev_qs.clear()
                self._indoubt_seen.clear()
                self._burn_samples.clear()
            self._prev_recompiles = None
            self._prev_device_faults = None

    # -- rule conditions -----------------------------------------------------

    def _check_replication_lag(self, ctx: AlertContext) -> Iterable[Breach]:
        thr = config.alert_repl_lag_entries
        if thr <= 0:
            return
        if ctx.cluster is not None:
            with ctx.cluster._lock:
                members = list(ctx.cluster.members.values())
                primary = ctx.cluster.primary
            pdb = next(
                (m.db for m in members if m.name == primary), None
            )
            head = getattr(getattr(pdb, "_wal", None), "next_lsn", 1) - 1
            for m in members:
                if m.role != "REPLICA":
                    continue
                applied = max(
                    m.puller.applied_lsn if m.puller is not None else 0,
                    getattr(m.db, "_repl_applied_lsn", 0),
                )
                lag = head - applied
                if lag > thr:
                    yield Breach(
                        m.name, lag, thr,
                        f"replica {m.name} applied lsn {applied} trails "
                        f"head {head} by {lag} entries",
                    )
            return
        lag = ctx.gauges.get("replication.lag_entries", 0)
        if lag > thr:
            yield Breach(
                "local", lag, thr,
                f"replication lag {int(lag)} entries (gauge)",
            )

    def _check_breaker_open(self, ctx: AlertContext) -> Iterable[Breach]:
        from orientdb_tpu.parallel.resilience import breaker_snapshot

        for name, snap in breaker_snapshot().items():
            if snap.get("state") == "open":
                yield Breach(
                    name, 1, 0,
                    f"circuit breaker {name} is open "
                    f"(failures={snap.get('failures')})",
                )

    def _check_indoubt_age(self, ctx: AlertContext) -> Iterable[Breach]:
        thr = config.alert_indoubt_age_s
        seen_now = set()
        for db in ctx.dbs:
            reg = getattr(db, "_tx2pc_registry", None)
            if reg is None:
                continue
            for st in reg.staged_report():
                ident = (db.name, st["txid"])
                seen_now.add(ident)
                first = self._indoubt_seen.setdefault(ident, ctx.now)
                age = ctx.now - first
                if age >= thr:
                    yield Breach(
                        f"{db.name}/{st['txid']}", age, thr,
                        f"2PC batch {st['txid']} on '{db.name}' staged "
                        f"for {age:.1f}s ({st['ops']} ops, "
                        f"{len(st['locked_rids'])} locks held)",
                    )
        for ident in list(self._indoubt_seen):
            if ident not in seen_now:
                del self._indoubt_seen[ident]

    def _check_cdc_backlog(self, ctx: AlertContext) -> Iterable[Breach]:
        thr = config.alert_cdc_queue_depth
        if thr <= 0:
            return
        for db in ctx.dbs:
            feed = db.__dict__.get("_cdc_feed")
            if feed is None:
                continue
            for c in feed.stats()["consumers"]:
                worst = max(c["queue_depth"], c["lag_entries"])
                if worst > thr:
                    name = c["name"] or f"#{c['token']}"
                    yield Breach(
                        f"{db.name}/{name}", worst, thr,
                        f"cdc consumer {name} on '{db.name}': queue "
                        f"{c['queue_depth']}, lag {c['lag_entries']} "
                        f"entries, {c['shed_events']} shed",
                    )

    def _check_wal_growth(self, ctx: AlertContext) -> Iterable[Breach]:
        thr = config.alert_wal_bytes
        v = ctx.gauges.get("wal.segment_bytes", 0)
        if thr > 0 and v > thr:
            yield Breach(
                "wal", v, thr, f"WAL segments at {int(v)} bytes"
            )

    def _check_rss(self, ctx: AlertContext) -> Iterable[Breach]:
        thr = config.alert_rss_bytes
        v = ctx.gauges.get("proc.rss_bytes", 0)
        if thr > 0 and v > thr:
            yield Breach("rss", v, thr, f"RSS at {int(v)} bytes")

    def _check_jax_buffers(self, ctx: AlertContext) -> Iterable[Breach]:
        thr = config.alert_jax_buffer_bytes
        v = ctx.gauges.get("jax.live_buffer_bytes", 0)
        if thr > 0 and v > thr:
            yield Breach(
                "jax", v, thr, f"live jax buffers at {int(v)} bytes"
            )

    def _check_slab_pressure(self, ctx: AlertContext) -> Iterable[Breach]:
        thr = config.alert_slab_fill
        v = ctx.gauges.get("snapshot.delta.slab_fill", 0.0)
        if thr > 0 and v > thr:
            yield Breach(
                "snapshot",
                v,
                thr,
                f"delta slab {v:.0%} full (compaction falling behind)",
            )

    def _check_hbm_epoch_leak(self, ctx: AlertContext) -> Iterable[Breach]:
        """One breach per stale snapshot lease (obs/memledger): a
        retain() outstanding past memledger_leak_s pins device buffers
        with no dispatch retiring it. The breach carries the retaining
        lease's own trace id — the exemplar joins the exact dispatch
        that never released."""
        leak_s = config.memledger_leak_s
        if leak_s <= 0:
            return
        from orientdb_tpu.obs.memledger import memledger

        for lease in memledger.stale_leases():
            yield Breach(
                f"e{lease['epoch']}",
                lease["age_s"],
                leak_s,
                f"epoch {lease['epoch']} lease outstanding "
                f"{lease['age_s']:.1f}s ({lease['outstanding']} pins) — "
                "device buffers cannot free",
                trace_id=lease["trace_id"],
            )

    def _check_hbm_headroom(self, ctx: AlertContext) -> Iterable[Breach]:
        """Attributed ledger bytes vs the tier plane's HBM budget
        (``tier_hbm_cap_bytes`` — the config value, NOT the published
        ``tier.cap_bytes`` gauge: gauges outlive a detached tier, and a
        stale cap from a long-gone plane must not keep this rule armed).
        0 cap = unbounded plane = rule off."""
        cap = float(config.tier_hbm_cap_bytes)
        frac = config.memledger_headroom_fraction
        if cap <= 0 or frac <= 0:
            return
        v = ctx.gauges.get("hbm.ledger_bytes", 0.0)
        thr = cap * frac
        if v > thr:
            yield Breach(
                "hbm",
                v,
                thr,
                f"attributed HBM {v / (1 << 20):.1f} MiB past "
                f"{frac:.0%} of the {cap / (1 << 20):.1f} MiB cap",
            )

    def _check_tier_thrash(self, ctx: AlertContext) -> Iterable[Breach]:
        thr = config.alert_tier_thrash
        v = ctx.gauges.get("tier.thrash", 0.0)
        if thr > 0 and v > thr:
            yield Breach(
                "tier",
                v,
                thr,
                f"{v:.0f} block reloads in the thrash window (working "
                "set over tier_hbm_cap_bytes)",
            )

    def _check_recompile_storm(self, ctx: AlertContext) -> Iterable[Breach]:
        thr = config.alert_recompiles_per_min
        total = sum(
            int(row.get("recompiles", 0))
            for row in ctx.query_stats.values()
        )
        prev, prev_ts = self._prev_recompiles, self._prev_recompiles_ts
        self._prev_recompiles = total
        self._prev_recompiles_ts = ctx.now
        if prev is None or thr <= 0:
            return
        dt = max(ctx.now - prev_ts, 1e-3)
        rate = (total - prev) * 60.0 / dt
        if rate > thr:
            yield Breach(
                "recompiles", rate, thr,
                f"{rate:.1f} shape-overflow recompiles/min",
            )

    def _check_device_fault_storm(self, ctx: AlertContext) -> Iterable[Breach]:
        thr = config.alert_device_faults_per_min
        from orientdb_tpu.exec.devicefault import domain as _fault_domain

        total = _fault_domain.fault_total()
        prev, prev_ts = self._prev_device_faults, self._prev_device_faults_ts
        self._prev_device_faults = total
        self._prev_device_faults_ts = ctx.now
        if prev is None or thr <= 0:
            return
        dt = max(ctx.now - prev_ts, 1e-3)
        rate = (total - prev) * 60.0 / dt
        if rate > thr:
            yield Breach(
                "device", rate, thr,
                f"{rate:.1f} classified device faults/min "
                "(exec/devicefault escalation ladder engaged)",
            )

    def _check_parity_divergence(self, ctx: AlertContext) -> Iterable[Breach]:
        """Active while any fingerprint sits in quarantine on a parity
        conviction (exec/audit → devicefault.quarantine_parity): the
        breach persists across ticks so the pending dwell can elapse,
        and resolves when a clean probe re-admits the plan."""
        from orientdb_tpu.exec.audit import auditor
        from orientdb_tpu.exec.devicefault import domain as _fault_domain

        n = _fault_domain.parity_quarantined()
        if n <= 0:
            return
        yield Breach(
            "parity", float(n), 0.0,
            f"{n} fingerprint(s) quarantined on parity divergence "
            f"({auditor.snapshot()['diverged']} divergence record(s)); "
            "oracle serving degraded-but-correct traffic",
            trace_id=auditor.last_divergence_trace(),
        )

    def _check_scrub_corruption(self, ctx: AlertContext) -> Iterable[Breach]:
        """Active while the last completed scrub sweep (or any sweep
        since the last clean one) found corrupt device bytes; a later
        fully clean sweep resolves it — deterministic, no wall-clock
        window."""
        from orientdb_tpu.storage.scrub import scrubber

        st = scrubber.alert_state()
        if st is None:
            return
        yield Breach(
            "scrub", float(st["corruptions"]), 0.0,
            f"device-state scrub found {st['corruptions']} corrupt "
            f"key(s) since the last clean sweep (latest: "
            f"{st['last_key']}); repair ladder engaged "
            f"({st['last_repair'] or 'repair pending'})",
        )

    def _check_latency_regression(
        self, ctx: AlertContext
    ) -> Iterable[Breach]:
        min_calls = max(int(config.alert_latency_min_calls), 1)
        for fid, row in ctx.query_stats.items():
            calls = int(row.get("calls", 0))
            total_s = float(row.get("total_s", 0.0))
            pc, pt, _pe = self._prev_qs.get(fid, (0, 0.0, 0))
            d_calls = calls - pc
            d_total = total_s - pt
            if d_calls <= 0:
                continue
            mean_s = d_total / d_calls
            base = self._baselines.setdefault(fid, _Baseline())
            if base.breaches(mean_s):
                # a regressed tick must NOT fold into its own baseline:
                # with alert_pending_ticks > 1 a sustained step would
                # otherwise teach the EWMA the new level before the
                # dwell elapses and the alert could never reach firing
                if d_calls >= min_calls:
                    detail = (
                        f"fingerprint {fid}: tick mean "
                        f"{mean_s * 1e3:.2f} ms vs baseline "
                        f"{base.ewma_s * 1e3:.2f} ms "
                        f"(±{max(base.mad_s, _MAD_FLOOR_S) * 1e3:.2f})"
                    )
                    # critical-path blame: which segment of this
                    # fingerprint's decomposition grew (obs/critpath
                    # window diff), with the worst recent request's
                    # trace id as the exemplar join key
                    blame = None
                    try:
                        from orientdb_tpu.obs.critpath import plane

                        blame = plane.blame(fid)
                    except Exception:
                        log.debug(
                            "critpath blame failed for %s",
                            fid, exc_info=True,
                        )
                    if blame:
                        detail += "; blame: " + ", ".join(
                            f"{g['segment']} +{g['delta_ms']:.2f}ms"
                            for g in blame["segments"]
                        )
                    yield Breach(
                        fid, mean_s * 1000.0,
                        (base.ewma_s
                         + config.alert_latency_mads
                         * max(base.mad_s, _MAD_FLOOR_S)) * 1000.0,
                        detail,
                        trace_id=(
                            blame.get("trace_id") if blame else None
                        ),
                        blame=blame,
                    )
            else:
                base.update(mean_s)

    #: deviation floor for the device-idle fraction baseline — idle
    #: fractions are [0,1]; sub-2% wiggle is scheduler jitter
    _IDLE_MAD_FLOOR = 0.02

    def _check_overlap_regression(
        self, ctx: AlertContext
    ) -> Iterable[Breach]:
        """Device-idle fraction (the obs/timeline overlap gauges,
        refreshed by the scrape-time provider inside this tick's
        ``snapshot_all``) vs its online EWMA baseline — the same
        learn-unless-breaching discipline as the latency rule, so a
        sustained regression cannot teach the baseline its own level
        before the pending dwell elapses."""
        mads = config.alert_overlap_idle_mads
        min_records = max(int(config.alert_overlap_min_records), 1)
        if mads <= 0:
            return
        idle = ctx.gauges.get("overlap.device_idle_fraction")
        n_rec = ctx.gauges.get("overlap.window_records", 0)
        if idle is None or n_rec < min_records:
            return
        base = self._overlap_baseline
        if base.exceeds(idle, mads, self._IDLE_MAD_FLOOR):
            yield Breach(
                "device_idle", idle,
                base.threshold(mads, self._IDLE_MAD_FLOOR),
                f"device-idle fraction {idle:.3f} vs baseline "
                f"{base.ewma_s:.3f} "
                f"(±{max(base.mad_s, self._IDLE_MAD_FLOOR):.3f}) over "
                f"{int(n_rec)} timeline records",
            )
        else:
            base.update(idle)

    def _check_error_burn(self, ctx: AlertContext) -> Iterable[Breach]:
        slo = config.alert_slo_error_rate
        factor = config.alert_burn_factor
        calls = sum(
            int(r.get("calls", 0)) for r in ctx.query_stats.values()
        )
        errors = sum(
            int(r.get("errors", 0)) for r in ctx.query_stats.values()
        )
        samples = self._burn_samples
        samples.append((ctx.now, calls, errors))
        # prune, but KEEP the newest sample at-or-before the long
        # window's floor — it is that window's differencing base
        while (
            len(samples) >= 2
            and samples[1][0] <= ctx.now - BURN_LONG_S
        ):
            samples.popleft()
        if slo <= 0 or factor <= 0:
            return

        def window_rate(width_s: float) -> Optional[float]:
            """Error rate over the trailing window, or None while the
            sample history does not yet SPAN it — a young history must
            not let the long window degenerate into the short one
            (that would page on exactly the transient blip the long
            window exists to absorb)."""
            floor = ctx.now - width_s
            base = None
            for ts, c, e in samples:
                if ts <= floor:
                    base = (c, e)
                else:
                    break
            if base is None:
                return None
            dc, de = calls - base[0], errors - base[1]
            return (de / dc) if dc > 0 else None

        short = window_rate(BURN_SHORT_S)
        long_ = window_rate(BURN_LONG_S)
        if short is None or long_ is None:
            return
        if short >= slo * factor and long_ >= slo * factor:
            yield Breach(
                "queries", short / slo, factor,
                f"error rate {short:.3f} (short) / {long_:.3f} (long) "
                f"burns the {slo:.3f} SLO budget at "
                f"{short / slo:.1f}x / {long_ / slo:.1f}x",
            )


def _rule(
    name: str,
    severity: str,
    check: Callable[[AlertEngine, AlertContext], Iterable[Breach]],
    exemplar: str = "span",
    exemplar_spans: Tuple[str, ...] = (),
) -> AlertRule:
    """Declare one built-in rule (the literal ``name`` is what
    ``alertlint`` cross-checks against :data:`RULE_CATALOG`)."""
    if name not in RULE_CATALOG:
        raise ValueError(f"alert rule {name!r} is not in RULE_CATALOG")
    return AlertRule(name, severity, check, exemplar, exemplar_spans)


#: the built-in catalog, evaluated in order every tick
BUILTIN_RULES: Tuple[AlertRule, ...] = (
    _rule(
        "replication_lag", "critical",
        AlertEngine._check_replication_lag,
        exemplar_spans=("replication.", "wal.append"),
    ),
    _rule(
        "breaker_open", "critical", AlertEngine._check_breaker_open,
        exemplar_spans=("forward.request", "replication.", "tx2pc."),
    ),
    _rule(
        "indoubt_2pc_age", "critical", AlertEngine._check_indoubt_age,
        exemplar_spans=("tx2pc.",),
    ),
    _rule(
        "cdc_backlog", "warning", AlertEngine._check_cdc_backlog,
        exemplar_spans=("cdc.",),
    ),
    _rule("wal_growth", "warning", AlertEngine._check_wal_growth,
          exemplar_spans=("wal.append",)),
    _rule("rss_watermark", "warning", AlertEngine._check_rss),
    _rule(
        "jax_buffer_watermark", "warning", AlertEngine._check_jax_buffers,
        exemplar_spans=("tpu.",),
    ),
    _rule(
        "recompile_storm", "warning", AlertEngine._check_recompile_storm,
        exemplar="slowlog",
    ),
    _rule(
        "latency_regression", "warning",
        AlertEngine._check_latency_regression, exemplar="slowlog",
    ),
    _rule(
        "error_burn_rate", "critical", AlertEngine._check_error_burn,
        exemplar="slowlog",
    ),
    _rule(
        "overlap_regression", "warning",
        AlertEngine._check_overlap_regression,
        exemplar_spans=("coalesce.", "tpu.", "query"),
    ),
    _rule(
        "delta_slab_pressure", "warning",
        AlertEngine._check_slab_pressure,
        exemplar_spans=("snapshot.",),
    ),
    _rule(
        "tier_thrash", "warning",
        AlertEngine._check_tier_thrash,
        exemplar_spans=("tier.",),
    ),
    _rule(
        "hbm_epoch_leak", "critical",
        AlertEngine._check_hbm_epoch_leak,
        exemplar_spans=("tpu.", "query"),
    ),
    _rule(
        "hbm_headroom", "warning",
        AlertEngine._check_hbm_headroom,
        exemplar_spans=("tier.", "tpu.load"),
    ),
    _rule(
        "device_fault_storm", "warning",
        AlertEngine._check_device_fault_storm,
        exemplar_spans=("devicefault.", "tpu."),
    ),
    _rule(
        "parity_divergence", "critical",
        AlertEngine._check_parity_divergence,
        exemplar_spans=("audit.", "query"),
    ),
    _rule(
        "scrub_corruption", "critical",
        AlertEngine._check_scrub_corruption,
        exemplar_spans=("scrub.", "tier."),
    ),
)


#: the process-wide engine (mirrors stats/profiler/tracer singletons);
#: the watchdog ticks it, the HTTP/console/bundle surfaces read it
engine = AlertEngine()


# ---------------------------------------------------------------------------
# Prometheus rendering (shared by /alerts and the registry fan-in)
# ---------------------------------------------------------------------------

#: exported per-rule families: (export field, family suffix)
ALERT_FAMILIES: Tuple[Tuple[str, str], ...] = (
    ("firing", "alert_firing"),
    ("pending", "alert_pending"),
)


def render_alerts_into(
    lines: List[str],
    snapshots: Dict[Optional[str], Dict[str, Dict[str, int]]],
) -> None:
    """Render per-rule alert-state gauges in exposition order (family
    outer, members+rules inner). ``snapshots`` maps a member name (or
    None for the single-process form) to that member's
    :meth:`AlertEngine.export` dict — the ``render_stats_into``
    convention, so the fan-in joins on the ``rule`` label."""
    members = sorted(snapshots, key=lambda m: m or "")
    for field, fam in ALERT_FAMILIES:
        m = f"orienttpu_{fam}"
        header_done = False
        for mem in members:
            for rule in sorted(snapshots[mem] or {}):
                v = snapshots[mem][rule].get(field)
                if v is None:
                    continue
                if not header_done:
                    lines.append(f"# HELP {m} orientdb-tpu metric {m}")
                    lines.append(f"# TYPE {m} gauge")
                    header_done = True
                labels = f'rule="{rule}"'
                if mem is not None:
                    labels += f',member="{mem}"'
                lines.append(f"{m}{{{labels}}} {v}")


def render_alerts_prometheus() -> str:
    """``GET /alerts?format=prometheus``: the per-rule state gauges."""
    lines: List[str] = []
    render_alerts_into(lines, {None: engine.export()})
    return "\n".join(lines) + "\n"
