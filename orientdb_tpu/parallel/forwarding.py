"""Write-ownership forwarding: any cluster member accepts writes.

Analog of the reference's cluster-ownership write routing ([E]
``ODistributedConfiguration`` per-cluster server-owner lists: a write
arriving at a server that does not own the record's cluster is
forwarded to the owner; SURVEY.md §2 "Distributed"). v1 ownership: the
PRIMARY owns every cluster — so concurrent writers on different NODES
all succeed (serialized at the owner, replicated back), which is the
client-visible multi-master property; per-class ownership with multiple
concurrent owner streams is the documented delta (it needs per-owner
WAL streams, not this engine's single LSN sequence).

Wire shape: the owner's existing REST write surface (POST/PUT/DELETE
/document, POST /command for edges) with the cluster's credentials.
Replication then carries the committed write back to every member,
including the forwarding one."""

from __future__ import annotations

import base64
import json
import urllib.error
import urllib.parse
import urllib.request
from typing import Dict, Optional

from orientdb_tpu.models.rid import RID
from orientdb_tpu.utils.logging import get_logger
from orientdb_tpu.utils.metrics import metrics

log = get_logger("forwarding")


class WriteOwner:
    """Forwarding target attached to a non-owner member's database
    (``db._write_owner``). Cleared on promotion."""

    __slots__ = ("base_url", "dbname", "user", "password", "timeout")

    def __init__(self, base_url, dbname, user, password, timeout=10.0):
        self.base_url = base_url
        self.dbname = dbname
        self.user = user
        self.password = password
        self.timeout = timeout

    @staticmethod
    def _json_enc(v):
        if isinstance(v, (bytes, bytearray)):  # blob payloads
            from orientdb_tpu.storage.durability import bytes_to_wire

            return bytes_to_wire(v)
        raise TypeError(f"not JSON-forwardable: {type(v).__name__}")

    def _req(self, method: str, path: str, payload: Optional[Dict] = None):
        cred = base64.b64encode(
            f"{self.user}:{self.password}".encode()
        ).decode()
        req = urllib.request.Request(
            f"{self.base_url}{path}",
            data=None
            if payload is None
            else json.dumps(payload, default=self._json_enc).encode(),
            headers={
                "Authorization": f"Basic {cred}",
                "Content-Type": "application/json",
            },
            method=method,
        )
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            body = r.read()
            return json.loads(body) if body else {}

    # -- the forwarded record operations ------------------------------------

    def create(
        self, class_name: str, fields: Dict, kind: str = "document"
    ) -> Dict:
        metrics.incr("forwarding.create")
        return self._req(
            "POST",
            f"/document/{self.dbname}",
            {"@class": class_name, "@type": kind, **fields},
        )

    def update(
        self,
        rid: RID,
        fields: Dict,
        base_version: Optional[int],
        replace: bool = True,
    ) -> Dict:
        """MVCC travels with the forward: the owner rejects (409) when
        its stored version differs from the caller's base version —
        the same ConcurrentModificationError a local save raises.

        ``replace`` marks the payload as the record's FULL field set
        (the ``_forward_save`` case): the owner clears fields absent
        from it, so ``remove_field()`` + ``save()`` on a non-owner
        propagates the removal instead of silently resurrecting the
        field (local save semantics). Chain-forwards of partial REST
        updates pass ``replace=False``."""
        metrics.incr("forwarding.update")
        # the '#' in a RID would otherwise parse as a URL fragment
        q = urllib.parse.quote(str(rid), safe="")
        body = dict(fields)
        if base_version is not None:
            body["@base_version"] = base_version
        if replace:
            body["@replace"] = True
        try:
            return self._req("PUT", f"/document/{self.dbname}/{q}", body)
        except urllib.error.HTTPError as e:
            if e.code == 409:
                from orientdb_tpu.models.database import (
                    ConcurrentModificationError,
                )

                raise ConcurrentModificationError(
                    e.read().decode(errors="replace")
                ) from None
            raise

    def delete(self, rid: RID) -> None:
        metrics.incr("forwarding.delete")
        q = urllib.parse.quote(str(rid), safe="")
        self._req("DELETE", f"/document/{self.dbname}/{q}")

    def create_edge(
        self, class_name: str, src: RID, dst: RID, fields: Dict
    ) -> Dict:
        # a typed REST route, not SQL text: field values (unicode,
        # nested maps) must round-trip exactly
        metrics.incr("forwarding.edge")
        return self._req(
            "POST",
            f"/edge/{self.dbname}",
            {
                "@class": class_name,
                "from": str(src),
                "to": str(dst),
                "fields": fields,
            },
        )
