"""Write-ownership forwarding: any cluster member accepts writes.

Analog of the reference's cluster-ownership write routing ([E]
``ODistributedConfiguration`` per-cluster server-owner lists: a write
arriving at a server that does not own the record's cluster is
forwarded to the owner; SURVEY.md §2 "Distributed"). v1 ownership: the
PRIMARY owns every cluster — so concurrent writers on different NODES
all succeed (serialized at the owner, replicated back), which is the
client-visible multi-master property; per-class ownership with multiple
concurrent owner streams is the documented delta (it needs per-owner
WAL streams, not this engine's single LSN sequence).

Wire shape: the owner's existing REST write surface (POST/PUT/DELETE
/document, POST /command for edges) with the cluster's credentials.
Replication then carries the committed write back to every member,
including the forwarding one."""

from __future__ import annotations

import base64
import json
import urllib.error
import urllib.parse
import urllib.request
from typing import Dict, Optional

from orientdb_tpu.chaos import fault
from orientdb_tpu.models.rid import RID
from orientdb_tpu.parallel.resilience import RetryPolicy, breaker
from orientdb_tpu.utils.logging import get_logger
from orientdb_tpu.utils.metrics import metrics

log = get_logger("forwarding")

#: shared backoff for the IDEMPOTENT 2PC phases (prepare/abort): a
#: transient channel blip must not turn a clean round into an abort (or
#: a lingering staged batch). Commit is NOT retried here — the resolver
#: owns post-decision replay with its own at-least-once semantics.
_2PC_RETRY = RetryPolicy(attempts=3, base_s=0.05, cap_s=0.5, budget_s=3.0)


def member_key(owner) -> str:
    """The MEMBER identity a route object points at. Per-class
    assignment mints one WriteOwner per class, so anything grouping
    work per member (2PC sub-batches in BOTH tx paths) must key on
    this, never on the route object — two prepares of one txid at one
    member collide in its registry."""
    return f"{owner.base_url}/{owner.dbname}"


class WriteOwner:
    """Forwarding target attached to a non-owner member's database
    (``db._write_owner``). Cleared on promotion."""

    __slots__ = ("base_url", "dbname", "user", "password", "timeout")

    def __init__(self, base_url, dbname, user, password, timeout=10.0):
        self.base_url = base_url
        self.dbname = dbname
        self.user = user
        self.password = password
        self.timeout = timeout

    @staticmethod
    def _json_enc(v):
        if isinstance(v, (bytes, bytearray)):  # blob payloads
            from orientdb_tpu.storage.durability import bytes_to_wire

            return bytes_to_wire(v)
        raise TypeError(f"not JSON-forwardable: {type(v).__name__}")

    def _req(self, method: str, path: str, payload: Optional[Dict] = None):
        from orientdb_tpu.obs.propagation import inject_headers
        from orientdb_tpu.obs.trace import span

        cred = base64.b64encode(
            f"{self.user}:{self.password}".encode()
        ).decode()
        # the forward is a client span; its context travels in the
        # request headers so the owner's server span CONTINUES this
        # trace instead of minting an unrelated one (obs/propagation)
        with span(
            "forward.request", method=method, path=path.split("?")[0][:80]
        ):
            req = urllib.request.Request(
                f"{self.base_url}{path}",
                data=None
                if payload is None
                else json.dumps(payload, default=self._json_enc).encode(),
                headers=inject_headers(
                    {
                        "Authorization": f"Basic {cred}",
                        "Content-Type": "application/json",
                    }
                ),
                method=method,
            )

            def _send():
                # the fault point sits INSIDE the breaker so injected
                # drops/errors count as channel failures and can trip it
                with fault.point("fwd.req"):
                    with urllib.request.urlopen(
                        req, timeout=self.timeout
                    ) as r:
                        body = r.read()
                        return json.loads(body) if body else {}

            # per-target fuse: a dead owner fails fast after the
            # threshold instead of charging every forwarder a timeout;
            # HTTPError (a 409/404/...) proves the channel HEALTHY
            return breaker(f"fwd:{self.base_url}").call(
                _send, success_on=(urllib.error.HTTPError,)
            )

    # -- the forwarded record operations ------------------------------------

    def create(
        self, class_name: str, fields: Dict, kind: str = "document"
    ) -> Dict:
        metrics.incr("forwarding.create")
        return self._req(
            "POST",
            f"/document/{self.dbname}",
            {"@class": class_name, "@type": kind, **fields},
        )

    def update(
        self,
        rid: RID,
        fields: Dict,
        base_version: Optional[int],
        replace: bool = True,
    ) -> Dict:
        """MVCC travels with the forward: the owner rejects (409) when
        its stored version differs from the caller's base version —
        the same ConcurrentModificationError a local save raises.

        ``replace`` marks the payload as the record's FULL field set
        (the ``_forward_save`` case): the owner clears fields absent
        from it, so ``remove_field()`` + ``save()`` on a non-owner
        propagates the removal instead of silently resurrecting the
        field (local save semantics). Chain-forwards of partial REST
        updates pass ``replace=False``."""
        metrics.incr("forwarding.update")
        # the '#' in a RID would otherwise parse as a URL fragment
        q = urllib.parse.quote(str(rid), safe="")
        body = dict(fields)
        if base_version is not None:
            body["@base_version"] = base_version
        if replace:
            body["@replace"] = True
        try:
            return self._req("PUT", f"/document/{self.dbname}/{q}", body)
        except urllib.error.HTTPError as e:
            if e.code == 409:
                from orientdb_tpu.models.database import (
                    ConcurrentModificationError,
                )

                raise ConcurrentModificationError(
                    e.read().decode(errors="replace")
                ) from None
            raise

    def delete(self, rid: RID) -> None:
        metrics.incr("forwarding.delete")
        q = urllib.parse.quote(str(rid), safe="")
        self._req("DELETE", f"/document/{self.dbname}/{q}")

    def transaction(self, ops) -> Dict:
        """Ship a buffered transaction to the owner as ONE atomic
        request ([E] the reference's distributed tx task: the whole op
        batch executes in one owner-side transaction — all-or-nothing).
        Returns {"results": [...]} with owner-assigned rids/versions; a
        version conflict surfaces as ConcurrentModificationError."""
        metrics.incr("forwarding.tx")
        try:
            return self._req("POST", f"/tx/{self.dbname}", {"ops": ops})
        except urllib.error.HTTPError as e:
            if e.code == 409:
                from orientdb_tpu.models.database import (
                    ConcurrentModificationError,
                )

                raise ConcurrentModificationError(
                    e.read().decode(errors="replace")
                ) from None
            raise

    def tx2pc(
        self,
        phase: str,
        txid: str,
        ops=None,
        rid_map: Optional[Dict] = None,
        ttl: Optional[float] = None,
    ) -> Dict:
        """One 2PC phase at this owner (parallel/twophase; [E] the
        reference's 2-phase distributed tx, SURVEY.md:126). A version
        conflict or a lock held by another in-flight distributed tx
        surfaces as ConcurrentModificationError."""
        metrics.incr(f"forwarding.tx2pc_{phase}")
        payload: Dict = {"phase": phase, "txid": txid}
        if ops is not None:
            payload["ops"] = ops
        if rid_map:
            payload["rid_map"] = rid_map
        if ttl is not None:
            payload["ttl"] = ttl
        try:
            if phase in ("prepare", "abort"):
                # idempotent phases (a re-delivered prepare of the same
                # txid+ops answers "prepared" again server-side; a
                # double abort is a no-op): retry transient channel
                # failures under the shared policy instead of aborting
                # the whole round
                from orientdb_tpu.parallel.resilience import (
                    CircuitOpenError,
                    RetryBudgetExceeded,
                )

                try:
                    return _2PC_RETRY.call(
                        self._req,
                        "POST",
                        f"/tx2pc/{self.dbname}",
                        payload,
                        give_up_on=(
                            urllib.error.HTTPError,
                            CircuitOpenError,
                        ),
                    )
                except RetryBudgetExceeded as e:
                    raise (
                        e.__cause__
                        if isinstance(e.__cause__, Exception)
                        else e
                    )
            return self._req("POST", f"/tx2pc/{self.dbname}", payload)
        except urllib.error.HTTPError as e:
            if e.code == 409:
                from orientdb_tpu.models.database import (
                    ConcurrentModificationError,
                )

                raise ConcurrentModificationError(
                    e.read().decode(errors="replace")
                ) from None
            raise

    def create_edge(
        self, class_name: str, src: RID, dst: RID, fields: Dict
    ) -> Dict:
        # a typed REST route, not SQL text: field values (unicode,
        # nested maps) must round-trip exactly
        metrics.incr("forwarding.edge")
        return self._req(
            "POST",
            f"/edge/{self.dbname}",
            {
                "@class": class_name,
                "from": str(src),
                "to": str(dst),
                "fields": fields,
            },
        )


class ForwardedTransaction:
    """A transaction on a NON-OWNER member (VERDICT r4 #9: forwarded
    transactions EXECUTE at the owner instead of being rejected).

    Operations buffer locally with NO local schema or store mutation —
    the divergence hazard that used to force rejection — and ship to the
    owner at commit as one atomic request (`WriteOwner.transaction`),
    where they run inside a real owner-side transaction: all-or-nothing,
    MVCC-checked against the forwarder's base versions ([E] the
    reference wraps a client tx as a distributed task batch executed at
    the owning server, SURVEY.md:126).

    Read semantics: reads see this replica's committed state plus this
    tx's OWN creates/updates (read-your-writes within the buffer);
    other sessions' concurrent owner-side commits become visible after
    replication, like any replica read."""

    def __init__(self, db) -> None:
        import itertools

        self.db = db
        self.active = True
        self._temp_seq = itertools.count(2)
        self.ops: list = []
        #: temp rid string -> (doc, op) for rid/version adoption
        self._created: Dict[str, tuple] = {}
        #: rid -> buffered updated doc (read-your-writes)
        self._updated: Dict[RID, Document] = {}
        #: rid -> tx-local CLONE handed out by load() (version frozen
        #: at read time; the store object stays untouched)
        self._workspace: Dict[RID, Document] = {}
        #: rid -> (fields copy, version) captured at FIRST in-place
        #: mutation of a SHARED store object (scan results bypass
        #: load()'s clone): the version freezes the MVCC base the tx
        #: actually read, and rollback / failed commit restores the
        #: fields so uncommitted dirt never outlives the tx
        self._preimages: Dict[RID, tuple] = {}
        self._deleted: set = set()
        #: owner-key -> WriteOwner for ops tagged "@owner" (per-class
        #: owner streams: one tx may span owners → 2PC at commit)
        self._owners: Dict[str, WriteOwner] = {}

    # -- buffering (the Database tx protocol) -------------------------------

    def _temp_rid(self) -> RID:
        from orientdb_tpu.models.rid import NEW_RID  # noqa: F401

        return RID(-1, -next(self._temp_seq))

    @staticmethod
    def _enc_fields(doc: Document) -> Dict:
        from orientdb_tpu.storage.durability import _enc_fields

        return _enc_fields(doc)

    def _owner_key(self, class_name: str) -> str:
        """Tag value routing this op to its owner's sub-batch at commit:
        'local' = THIS member owns the class (per-class owner streams)
        and the sub-batch commits here; otherwise a key into
        ``self._owners``. One owner → the one-shot forwarded batch;
        several → 2PC (parallel/twophase)."""
        owner = self.db._owner_for(class_name)
        if owner is None:
            return "local"
        key = f"o:{member_key(owner)}"
        self._owners[key] = owner
        return key

    def save(self, doc: Document) -> Document:
        self._check_active()
        from orientdb_tpu.models.record import Blob, Vertex

        if not doc.rid.is_persistent and str(doc.rid) not in self._created:
            doc.rid = self._temp_rid()
            doc.version = 0
            doc._db = self.db
            op = {
                "kind": "create",
                "type": "vertex"
                if isinstance(doc, Vertex)
                else "blob" if isinstance(doc, Blob) else "document",
                "class": doc.class_name,
                "temp": str(doc.rid),
                "fields": self._enc_fields(doc),
                "@owner": self._owner_key(doc.class_name),
            }
            self.ops.append(op)
            self._created[str(doc.rid)] = (doc, op)
            return doc
        key = str(doc.rid)
        if key in self._created:
            # still uncommitted: refresh the buffered create's fields
            self._created[key][1]["fields"] = self._enc_fields(doc)
            return doc
        if doc.rid in self._updated:
            # refresh the buffered op in place (mirrors the create
            # branch): N saves of one doc ship ONE update
            for o in self.ops:
                if o.get("kind") == "update" and o["rid"] == key:
                    o["fields"] = self._enc_fields(doc)
                    break
            self._updated[doc.rid] = doc
            return doc
        op = {
            "kind": "update",
            "rid": str(doc.rid),
            # the MVCC base is the version this tx READ: for a shared
            # store object mutated in place that is the touch()-time
            # preimage version, not the object's current (possibly
            # apply-bumped) one
            "base_version": self._preimages.get(
                doc.rid, (None, doc.version)
            )[1],
            "fields": self._enc_fields(doc),
            "@owner": self._owner_key(doc.class_name),
        }
        self.ops.append(op)
        self._updated[doc.rid] = doc
        return doc

    def new_edge(self, class_name: str, src, dst, **fields):
        self._check_active()
        from orientdb_tpu.models.record import Edge

        e = Edge(class_name, fields)
        e._db = self.db
        e.rid = self._temp_rid()
        e.out_rid = src.rid
        e.in_rid = dst.rid
        op = {
            "kind": "edge",
            "class": class_name,
            "temp": str(e.rid),
            "from": str(src.rid),
            "to": str(dst.rid),
            "fields": self._enc_fields(e),
            "@owner": self._owner_key(class_name),
        }
        self.ops.append(op)
        self._created[str(e.rid)] = (e, op)
        return e

    def delete(self, doc: Document) -> None:
        self._check_active()
        key = str(doc.rid)
        if key in self._created:
            # delete of an uncommitted record: drop its buffered op
            _d, op = self._created.pop(key)
            self.ops = [o for o in self.ops if o is not op]
            return
        self.ops.append(
            {
                "kind": "delete",
                "rid": str(doc.rid),
                "@owner": self._owner_key(doc.class_name),
            }
        )
        self._deleted.add(doc.rid)
        doc._deleted = True

    def touch(self, doc: Document) -> None:
        """First in-place mutation of a SHARED store object (a scan
        result that bypassed load()'s clone): capture (fields, version)
        BEFORE the write — the version is the MVCC base this tx
        actually read (a replication apply bumping the object between
        read and save must conflict, not silently win), and the fields
        let rollback erase the uncommitted dirt."""
        rid = doc.rid
        if not rid.is_persistent or rid in self._preimages:
            return
        if self.db._load_raw(rid) is doc:
            self._preimages[rid] = (dict(doc.fields()), doc.version)

    def load(self, rid: RID):
        if rid in self._deleted:
            return None
        hit = self._updated.get(rid)
        if hit is not None:
            return hit
        doc, _op = self._created.get(str(rid), (None, None))
        if doc is not None:
            return doc
        stored = self.db._load_raw(rid)
        if stored is None:
            return None
        # CLONE (the exec.tx.Transaction.load discipline): mutating the
        # shared store object in place would (a) leak uncommitted state
        # to other sessions and the owner-apply path, and (b) let a
        # concurrent replication apply bump the object's version AFTER
        # this tx read its fields — the buffered update would then ship
        # a FRESH base_version with a STALE read, silently losing the
        # concurrent write (caught by the racing-coordinators test)
        hit = self._workspace.get(rid)
        if hit is None:
            from orientdb_tpu.exec.tx import _clone

            hit = self._workspace[rid] = _clone(stored)
        return hit

    def overlay(self, doc: Document):
        """Scan view: buffered update wins; buffered delete hides."""
        if doc.rid in self._deleted:
            return None
        return self._updated.get(doc.rid, doc)

    def browse_extra(self, class_name: str, polymorphic: bool):
        for doc, _op in self._created.values():
            cls = self.db.schema.get_class(doc.class_name)
            if cls is None:
                # class unknown on this replica yet (owner will create
                # it at commit): exact name match only
                if doc.class_name.lower() == class_name.lower():
                    yield doc
                continue
            if cls.name.lower() == class_name.lower() or (
                polymorphic and cls.is_subclass_of(class_name)
            ):
                yield doc

    # -- terminal states ----------------------------------------------------

    def _check_active(self) -> None:
        if not self.active:
            raise RuntimeError("transaction no longer active")

    def _finish(self) -> None:
        self.active = False
        if self.db.tx is self:
            self.db._tx_local.tx = None

    def _adopt(self, ops, results, mapping: Optional[Dict] = None) -> Dict:
        """Fold owner-assigned rids/versions back onto buffered docs."""
        mapping = {} if mapping is None else mapping
        for op, res in zip(ops, results):
            if op["kind"] in ("create", "edge") and res:
                doc, _ = self._created.get(op["temp"], (None, None))
                if doc is None:
                    continue
                old = doc.rid
                doc.rid = RID.parse(res["@rid"])
                doc.version = res.get("@version", 1)
                mapping[old] = doc.rid
            elif op["kind"] == "update" and res:
                d = self._updated.get(RID.parse(op["rid"]))
                if d is not None:
                    d.version = res.get("@version", d.version)
        return mapping

    def commit(self) -> Dict:
        """Ship the buffer; adopt assigned rids/versions. Returns
        {temp_rid: real_rid} like the local tx commit. One owner → one
        atomic forwarded batch; a LOCAL-owned group commits here; ops
        spanning owners run 2PC (parallel/twophase)."""
        self._check_active()
        # unbind first: a local sub-commit opens its own exec.tx
        # Transaction on this thread
        self._finish()
        if not self.ops:
            return {}
        try:
            return self._commit_groups()
        except BaseException:
            # nothing (or only part, for in-doubt) applied: erase the
            # uncommitted in-place dirt — the owner's authoritative
            # state replicates back over restored fields either way
            self._restore_preimages()
            raise

    def _commit_groups(self) -> Dict:
        groups: Dict[str, list] = {}
        for op in self.ops:
            key = op.pop("@owner", None)
            if key is None:  # pre-tag op (defensive): default owner
                wo = self.db._write_owner
                # wo may be None (cleared on promotion mid-tx): keep
                # the key resolvable so the single-group path below
                # raises its explicit "no write owner" TxErrorProxy
                key = "o:none" if wo is None else f"o:{member_key(wo)}"
                self._owners[key] = wo
            groups.setdefault(key, []).append(op)
        if len(groups) == 1:
            key, ops = next(iter(groups.items()))
            if key == "local":
                from orientdb_tpu.parallel.twophase import execute_tx_ops

                results, _tm = execute_tx_ops(self.db, ops)
                return self._adopt(ops, results)
            owner = self._owners.get(key) or self.db._write_owner
            if owner is None:
                raise TxErrorProxy("no write owner to forward to")
            resp = owner.transaction(ops)
            return self._adopt(ops, resp["results"])
        return self._commit_two_phase(groups)

    def _restore_preimages(self) -> None:
        for rid, (fields, version) in self._preimages.items():
            live = self.db._load_raw(rid)
            if live is not None:
                live._fields = dict(fields)
                live.version = version
        self._preimages.clear()

    def _commit_two_phase(self, groups: Dict[str, list]) -> Dict:
        """Coordinator for a forwarded tx spanning write owners ([E]
        the reference's 2-phase distributed tx, SURVEY.md:126), driven
        by twophase.run_coordinator. The LOCAL group (classes THIS
        member owns) participates through the same registry/lock
        machinery a remote owner uses."""
        import uuid

        from orientdb_tpu.parallel import twophase as tp

        txid = uuid.uuid4().hex
        rows = [(k, *tp.batch_temp_sets(ops)) for k, ops in groups.items()]
        mapping: Dict = {}

        def _adopt(ops, results):
            self._adopt(ops, results, mapping)

        parts: Dict[object, tp.Participant] = {}
        for key, ops in groups.items():
            if key == "local":
                parts[key] = tp.LocalRegistryParticipant(
                    self.db, ops, _adopt
                )
            else:
                parts[key] = tp.RemoteParticipant(
                    self._owners[key], ops, _adopt
                )
        tp.run_coordinator(txid, parts, rows, coord_db=self.db)
        return mapping

    def rollback(self) -> None:
        """Drop the buffer; restore in-place mutations of shared store
        objects (the touch()-time preimages)."""
        self._restore_preimages()
        self._finish()


class TxErrorProxy(Exception):
    pass
