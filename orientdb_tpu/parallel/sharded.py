"""Sharded graph execution over a device mesh.

The reference scales out with Hazelcast replication and per-cluster server
ownership ([E] OHazelcastPlugin / ODistributedConfiguration, SURVEY.md §2
"Distributed"); the TPU-native design shards the **CSR by source-vertex
range across chips** and merges per-hop frontiers with XLA collectives over
ICI (`psum` OR-merge of frontier bitmaps — SURVEY.md §5.7's ring-attention
analog for deep traversal).

Mesh axes (the DP×TP analog for a graph engine):
  - ``replicas`` — independent query streams (each replica holds a block of
    the query batch; the data-parallel axis);
  - ``shards`` — CSR row ranges (each shard owns vertices
    [s·rows_per_shard, (s+1)·rows_per_shard) and their out-edges; the
    model-parallel axis).

Everything compiles under one `jit(shard_map(...))`: the per-hop schedule is
  local edge-activation gather → scatter-OR into a [Q, V] bitmap → psum
over `shards`, iterated by `lax.fori_loop` for multi-hop BFS with a visited
bitmap (the columnar analog of [E] OTraverseStatement's visited set).
"""

from __future__ import annotations

import math
from functools import partial
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from orientdb_tpu.parallel.shard_compat import shard_map

from orientdb_tpu.storage.snapshot import GraphSnapshot
from orientdb_tpu.utils.config import config
from orientdb_tpu.utils.logging import get_logger

log = get_logger("sharded")



def provision_devices(n_devices: int) -> list:
    """Return >= n_devices JAX devices, self-provisioning virtual CPU
    devices when the default backend (e.g. the single tunneled TPU chip)
    has fewer.

    `jax.config.update('jax_num_cpu_devices', n)` works even with a TPU
    plugin active and after jax import, as long as the CPU backend has not
    been initialized yet — unlike XLA_FLAGS/JAX_PLATFORMS env vars, which
    the axon plugin ignores once its sitecustomize has imported jax.
    """
    # Must run BEFORE any backend is initialized (any jax.devices() call
    # anywhere): once backends exist the update raises, and we can only
    # fall through to whatever device count is already live.
    try:
        jax.config.update("jax_num_cpu_devices", n_devices)
    except Exception:
        # backends already initialized: the update is rejected and we
        # fall through to whatever device count is live
        log.debug("jax_num_cpu_devices update rejected", exc_info=True)
    devs = jax.devices()
    if len(devs) >= n_devices:
        return devs
    cpus = jax.devices("cpu")
    if len(cpus) >= n_devices:
        return cpus
    raise ValueError(
        f"need {n_devices} devices, have {len(devs)} "
        f"(and only {len(cpus)} CPU devices could be provisioned)"
    )


def make_mesh(
    n_devices: Optional[int] = None,
    replicas: int = 1,
    devices: Optional[list] = None,
) -> Mesh:
    """1-D or 2-D mesh: (replicas, shards). `n_devices` defaults to all."""
    if devices is not None:
        devs = devices
        n = n_devices or len(devs)
        if n > len(devs):
            raise ValueError(
                f"need {n} devices but explicit list has {len(devs)}"
            )
    elif n_devices is not None:
        # provision BEFORE jax.devices(): initializing any backend blocks
        # the jax_num_cpu_devices update provision_devices relies on
        devs = provision_devices(n_devices)
        n = n_devices
    else:
        devs = jax.devices()
        n = len(devs)
    if n % replicas:
        raise ValueError(f"{n} devices not divisible into {replicas} replicas")
    arr = np.array(devs[:n]).reshape(replicas, n // replicas)
    return Mesh(arr, (config.mesh_replica_axis, config.mesh_shard_axis))


class ShardedCSR:
    """One edge class's out-CSR, row-sharded by vertex range.

    Host layout: [n_shards, rows_per_shard+1] locally-rebased indptr and
    [n_shards, max_local_edges] destination arrays (-1 padded), placed with
    a NamedSharding so each device holds exactly its shard.
    """

    def __init__(self, mesh: Mesh, indptr: np.ndarray, dst: np.ndarray):
        self.mesh = mesh
        n_shards = mesh.shape[config.mesh_shard_axis]
        V = int(indptr.shape[0]) - 1
        rows = max(1, math.ceil(V / n_shards))
        V_pad = rows * n_shards
        self.num_vertices = V
        self.rows_per_shard = rows
        self.padded_vertices = V_pad
        ind_l = np.zeros((n_shards, rows + 1), np.int32)
        counts = []
        locals_ = []
        for s in range(n_shards):
            r0 = min(s * rows, V)
            r1 = min(r0 + rows, V)
            seg = indptr[r0 : r1 + 1] - indptr[r0]
            ind_l[s, : seg.shape[0]] = seg
            if seg.shape[0] < rows + 1:
                ind_l[s, seg.shape[0] :] = seg[-1] if seg.shape[0] else 0
            locals_.append(dst[indptr[r0] : indptr[r1]])
            counts.append(int(indptr[r1] - indptr[r0]))
        e_max = max(max(counts), 1)
        dst_l = np.full((n_shards, e_max), -1, np.int32)
        for s, seg in enumerate(locals_):
            dst_l[s, : seg.shape[0]] = seg
        shard_spec = NamedSharding(mesh, P(config.mesh_shard_axis, None))
        self.indptr = jax.device_put(jnp.asarray(ind_l), shard_spec)
        self.dst = jax.device_put(jnp.asarray(dst_l), shard_spec)

    @classmethod
    def from_snapshot(
        cls, snap: GraphSnapshot, mesh: Mesh, edge_class: str
    ) -> "ShardedCSR":
        csr = snap.edge_classes[edge_class]
        return cls(mesh, csr.indptr_out, csr.dst)


def _local_hop(indptr_l, dst_l, frontier, rows_per_shard, v_pad, shard_axis):
    """One shard's contribution to the next frontier.

    indptr_l [rows+1] local CSR; dst_l [E_max] global dst (-1 pad);
    frontier [Q, V_pad] replicated bitmap; ``shard_axis`` is the mesh
    axis NAME, read from config on the host before the trace boundary.
    Returns [Q, V_pad] bitmap of vertices reached through this shard's
    edges.
    """
    e_max = dst_l.shape[0]
    epos = jnp.arange(e_max, dtype=jnp.int32)
    src_local = jnp.clip(
        jnp.searchsorted(indptr_l, epos, side="right").astype(jnp.int32) - 1,
        0,
        rows_per_shard - 1,
    )
    shard_id = jax.lax.axis_index(shard_axis)
    src_global = src_local + shard_id * rows_per_shard
    edge_live = (dst_l >= 0) & (epos < indptr_l[-1])
    # [Q, E_max]: edge active iff its source is in that query's frontier
    active = frontier[:, src_global] & edge_live[None, :]
    dst_c = jnp.clip(dst_l, 0, v_pad - 1)
    contrib = jnp.zeros(frontier.shape, bool).at[:, dst_c].max(active)
    return contrib


#: (mesh, axes, geometry) → jitted BFS step. Un-memoized, every
#: bfs_reachability call built a FRESH jax.jit wrapper — a fresh trace
#: cache, so every query paid a full retrace+recompile (jaxlint's
#: un-memoized-jit finding, confirmed by deviceguard's re-record
#: counters). Meshes per process are few; the cache is unbounded.
_BFS_STEP_CACHE: Dict[Tuple, object] = {}


def build_bfs_step(
    mesh: Mesh, rows_per_shard: int, v_pad: int, max_depth: int
):
    """Compile the sharded multi-hop BFS step (the framework's
    `dryrun_multichip` "training step": DP over query replicas × TP over
    CSR shards, psum OR-merge per hop over ICI)."""
    # axis names are host-side trace constants: read them here, not
    # inside the traced closure (they also key the memo — a retuned
    # axis name must not serve a stale executable)
    shard_ax = config.mesh_shard_axis
    rep_ax = config.mesh_replica_axis
    key = (mesh, shard_ax, rep_ax, rows_per_shard, v_pad, max_depth)
    cached = _BFS_STEP_CACHE.get(key)
    if cached is not None:
        return cached

    def step(indptr_sh, dst_sh, roots):
        # roots: [Q, V_pad] bool, replica-sharded on axis 0
        def inner(indptr_l, dst_l, frontier0):
            indptr_l = indptr_l[0]  # drop the size-1 sharded block dims
            dst_l = dst_l[0]

            def body(_, state):
                frontier, visited = state
                contrib = _local_hop(
                    indptr_l, dst_l, frontier, rows_per_shard, v_pad,
                    shard_ax,
                )
                merged = (
                    jax.lax.psum(contrib.astype(jnp.int32), shard_ax) > 0
                )
                nxt = merged & ~visited
                return nxt, visited | nxt

            frontier, visited = jax.lax.fori_loop(
                0, max_depth, body, (frontier0, frontier0)
            )
            return visited

        return shard_map(
            inner,
            mesh=mesh,
            in_specs=(P(shard_ax, None), P(shard_ax, None), P(rep_ax, None)),
            out_specs=P(rep_ax, None),
            check_vma=True,
        )(indptr_sh, dst_sh, roots)

    fn = jax.jit(step)
    _BFS_STEP_CACHE[key] = fn
    return fn


def bfs_reachability(
    scsr: ShardedCSR, roots: np.ndarray, max_depth: int
) -> np.ndarray:
    """Multi-source BFS closure: roots [Q, V] bool → visited [Q, V] bool
    (roots included at depth 0, like TRAVERSE / MATCH-WHILE emit-origin
    semantics)."""
    mesh = scsr.mesh
    Q = roots.shape[0]
    reps = mesh.shape[config.mesh_replica_axis]
    q_pad = max(1, math.ceil(Q / reps)) * reps
    fr = np.zeros((q_pad, scsr.padded_vertices), bool)
    fr[:Q, : roots.shape[1]] = roots
    fr_dev = jax.device_put(
        jnp.asarray(fr), NamedSharding(mesh, P(config.mesh_replica_axis, None))
    )
    step = build_bfs_step(
        mesh, scsr.rows_per_shard, scsr.padded_vertices, max_depth
    )
    visited = step(scsr.indptr, scsr.dst, fr_dev)
    return np.asarray(visited)[:Q, : scsr.num_vertices]
