"""Sharded graph execution over a device mesh.

The reference scales out with Hazelcast replication and per-cluster server
ownership ([E] OHazelcastPlugin / ODistributedConfiguration, SURVEY.md §2
"Distributed"); the TPU-native design shards the **CSR by source-vertex
range across chips** and merges per-hop frontiers with XLA collectives over
ICI (SURVEY.md §5.7's ring-attention analog for deep traversal).

Mesh axes (the DP×TP analog for a graph engine):
  - ``replicas`` — independent query streams (each replica holds a block of
    the query batch; the data-parallel axis);
  - ``shards`` — CSR row ranges (each shard owns vertices
    [s·rows_per_shard, (s+1)·rows_per_shard) and their out-edges; the
    model-parallel axis).

Frontier-sparse schedule (the "invert the mesh" rework): the BFS state is
**vertex-sharded, never replicated** — each shard carries only its own
[Q, rows_per_shard] slice of the frontier and visited bitmaps, so the
per-hop collective is ONE ``psum_scatter`` of the hop's contribution
(the reduce half of the old psum all-reduce; the broadcast half is gone
because no shard ever needs the full [Q, V_pad] bitmap again). A shard
whose local frontier slice is empty skips its gather/scatter entirely
(``lax.cond`` on a device-side liveness scalar), the loop early-exits the
moment the global frontier drains (a scalar ``psum`` carried through a
``lax.while_loop`` — ``max_depth`` is a device operand, not a trace
constant), and the loop body is double-buffered: hop N's ring merge is
issued on the carried contribution slot BEFORE the local gather of the
next frontier consumes it, so XLA's async-collective scheduler can
overlap the merge with the expansion compute in front of it. The final
[Q, V] assembly happens HOST-side after the last hop (per-shard
``copy_to_host_async`` in :func:`fetch_sharded`) — the merge that used
to ride an all-gather inside every hop.

Recompile-free geometry: ``_BFS_STEP_CACHE`` keys executables by
(mesh, axis names) only — padded dims ride the jit cache's shape key,
and the scattered-state design removes shard row-range trace constants
from the BFS entirely (the engine-side expansion kernels in
``parallel/mesh_graph.py`` take their row spans as device operands for
the same reason) — so a shard sweep or an elastic re-shard back to a
previously-seen geometry never retraces.
"""

from __future__ import annotations

import math
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from orientdb_tpu.parallel.shard_compat import WHILE_CHECK_OK, shard_map

from orientdb_tpu.storage.snapshot import GraphSnapshot
from orientdb_tpu.utils.config import config
from orientdb_tpu.utils.logging import get_logger
from orientdb_tpu.utils.metrics import metrics

log = get_logger("sharded")



def provision_devices(n_devices: int) -> list:
    """Return >= n_devices JAX devices, self-provisioning virtual CPU
    devices when the default backend (e.g. the single tunneled TPU chip)
    has fewer.

    `jax.config.update('jax_num_cpu_devices', n)` works even with a TPU
    plugin active and after jax import, as long as the CPU backend has not
    been initialized yet — unlike XLA_FLAGS/JAX_PLATFORMS env vars, which
    the axon plugin ignores once its sitecustomize has imported jax.
    """
    # Must run BEFORE any backend is initialized (any jax.devices() call
    # anywhere): once backends exist the update raises, and we can only
    # fall through to whatever device count is already live.
    try:
        jax.config.update("jax_num_cpu_devices", n_devices)
    except Exception:
        # backends already initialized: the update is rejected and we
        # fall through to whatever device count is live
        log.debug("jax_num_cpu_devices update rejected", exc_info=True)
    devs = jax.devices()
    if len(devs) >= n_devices:
        return devs
    cpus = jax.devices("cpu")
    if len(cpus) >= n_devices:
        return cpus
    raise ValueError(
        f"need {n_devices} devices, have {len(devs)} "
        f"(and only {len(cpus)} CPU devices could be provisioned)"
    )


def make_mesh(
    n_devices: Optional[int] = None,
    replicas: int = 1,
    devices: Optional[list] = None,
) -> Mesh:
    """1-D or 2-D mesh: (replicas, shards). `n_devices` defaults to all."""
    if devices is not None:
        devs = devices
        n = n_devices or len(devs)
        if n > len(devs):
            raise ValueError(
                f"need {n} devices but explicit list has {len(devs)}"
            )
    elif n_devices is not None:
        # provision BEFORE jax.devices(): initializing any backend blocks
        # the jax_num_cpu_devices update provision_devices relies on
        devs = provision_devices(n_devices)
        n = n_devices
    else:
        devs = jax.devices()
        n = len(devs)
    if n % replicas:
        raise ValueError(f"{n} devices not divisible into {replicas} replicas")
    arr = np.array(devs[:n]).reshape(replicas, n // replicas)
    return Mesh(arr, (config.mesh_replica_axis, config.mesh_shard_axis))


def fetch_sharded(arr) -> np.ndarray:
    """Host-side assembly of a fully-sharded device result: start every
    shard's device→host copy together (``copy_to_host_async`` per
    addressable shard), then assemble — the per-shard result-page merge
    moved OFF the hot loop, where it used to be the broadcast half of a
    per-hop all-reduce. The blocking wall records as one transfer
    interval on the active flight record (obs/timeline): the sharded
    path's drain is compute+copy fused (no extra sync is inserted just
    to split them), so it scores as hidden only where OTHER dispatches'
    device work overlapped it."""
    import time as _time

    t0 = _time.monotonic()
    shards = getattr(arr, "addressable_shards", None)
    if shards is not None:
        for sh in shards:
            fn = getattr(sh.data, "copy_to_host_async", None)
            if fn is not None:
                fn()
    out = np.asarray(arr)
    from orientdb_tpu.obs.timeline import add_transfer

    add_transfer(t0, _time.monotonic(), int(out.nbytes), "fetch")
    return out


class ShardedCSR:
    """One edge class's out-CSR, row-sharded by vertex range.

    Host layout: [n_shards, rows_per_shard+1] locally-rebased indptr and
    [n_shards, max_local_edges] destination arrays (-1 padded), placed with
    a NamedSharding so each device holds exactly its shard.
    """

    def __init__(self, mesh: Mesh, indptr: np.ndarray, dst: np.ndarray):
        self.mesh = mesh
        n_shards = mesh.shape[config.mesh_shard_axis]
        V = int(indptr.shape[0]) - 1
        rows = max(1, math.ceil(V / n_shards))
        V_pad = rows * n_shards
        self.num_vertices = V
        self.rows_per_shard = rows
        self.padded_vertices = V_pad
        ind_l = np.zeros((n_shards, rows + 1), np.int32)
        counts = []
        locals_ = []
        for s in range(n_shards):
            r0 = min(s * rows, V)
            r1 = min(r0 + rows, V)
            seg = indptr[r0 : r1 + 1] - indptr[r0]
            ind_l[s, : seg.shape[0]] = seg
            if seg.shape[0] < rows + 1:
                ind_l[s, seg.shape[0] :] = seg[-1] if seg.shape[0] else 0
            locals_.append(dst[indptr[r0] : indptr[r1]])
            counts.append(int(indptr[r1] - indptr[r0]))
        e_max = max(max(counts), 1)
        dst_l = np.full((n_shards, e_max), -1, np.int32)
        for s, seg in enumerate(locals_):
            dst_l[s, : seg.shape[0]] = seg
        shard_spec = NamedSharding(mesh, P(config.mesh_shard_axis, None))
        self.indptr = jax.device_put(jnp.asarray(ind_l), shard_spec)
        self.dst = jax.device_put(jnp.asarray(dst_l), shard_spec)

    @classmethod
    def from_snapshot(
        cls, snap: GraphSnapshot, mesh: Mesh, edge_class: str
    ) -> "ShardedCSR":
        csr = snap.edge_classes[edge_class]
        return cls(mesh, csr.indptr_out, csr.dst)


#: (mesh, axis names) → jitted BFS step. Padded dims (rows_per_shard,
#: v_pad, query block) key the jit's OWN shape cache, and max_depth is a
#: device operand — so a shard sweep revisiting a geometry, a re-shard,
#: or a depth change NEVER retraces (the deviceguard-visible contract;
#: tests/test_sharded.py asserts it). Meshes per process are few; the
#: cache is unbounded.
_BFS_STEP_CACHE: Dict[Tuple, object] = {}


def build_bfs_step(mesh: Mesh):
    """Compile the sharded multi-hop BFS step (the framework's
    `dryrun_multichip` "training step": DP over query replicas × TP over
    CSR shards, one psum_scatter ring merge per hop over ICI). Geometry
    rides operand shapes; depth rides a device operand."""
    from orientdb_tpu.parallel.mesh_graph import _merge_dtype

    # axis names are host-side trace constants: read them here, not
    # inside the traced closure (they also key the memo — a retuned
    # axis name must not serve a stale executable)
    shard_ax = config.mesh_shard_axis
    rep_ax = config.mesh_replica_axis
    key = (mesh, shard_ax, rep_ax)
    cached = _BFS_STEP_CACHE.get(key)
    if cached is not None:
        return cached
    S = mesh.shape[shard_ax]
    cdtype = _merge_dtype(mesh)
    metrics.incr("mesh.kernel_builds")

    def step(indptr_sh, dst_sh, roots, depth_cap):
        # roots: [Q, V_pad] bool — replica-sharded rows, SHARD-sharded
        # columns: the frontier/visited state lives scattered by vertex
        # range and is never replicated across shards
        def inner(indptr_l, dst_l, frontier0_l, cap):
            indptr_l = indptr_l[0]  # drop the size-1 sharded block dims
            dst_l = dst_l[0]
            R = indptr_l.shape[0] - 1
            v_pad = R * S
            Q = frontier0_l.shape[0]
            # loop-invariant edge geometry, hoisted out of the hop loop
            e_max = dst_l.shape[0]
            epos = jnp.arange(e_max, dtype=jnp.int32)
            src_local = jnp.clip(
                jnp.searchsorted(indptr_l, epos, side="right").astype(
                    jnp.int32
                )
                - 1,
                0,
                R - 1,
            )
            edge_live = (dst_l >= 0) & (epos < indptr_l[-1])
            dst_c = jnp.clip(dst_l, 0, v_pad - 1)

            def expand(frontier_l):
                # [Q, R] local frontier slice → [Q, v_pad] contribution:
                # edge active iff its (locally-owned) source is lit
                active = frontier_l[:, src_local] & edge_live[None, :]
                return (
                    jnp.zeros((Q, v_pad), cdtype)
                    .at[:, dst_c]
                    .max(active.astype(cdtype))
                )

            def contrib_of(frontier_l, go):
                # frontier-sparse: a shard whose local frontier slice is
                # empty — or a hop the depth cap will discard anyway —
                # skips its gather/scatter entirely. The frontier half
                # of the predicate varies per shard and the branches
                # carry no collective, so each device decides alone.
                return jax.lax.cond(
                    go & frontier_l.any(),
                    expand,
                    lambda _f: jnp.zeros((Q, v_pad), cdtype),
                    frontier_l,
                )

            live0 = jax.lax.psum(
                frontier0_l.any().astype(jnp.int32), shard_ax
            )
            contrib0 = contrib_of(frontier0_l, jnp.int32(0) < cap[0])

            def cond_fn(state):
                depth, live, _contrib, _visited = state
                return (depth < cap[0]) & (live > 0)

            def body(state):
                depth, _live, contrib, visited_l = state
                # hop N's ring merge is ISSUED here on the carried
                # (double-buffered) contribution slot, before the local
                # gather of the NEXT frontier at the bottom of the body
                # consumes its result — the reduce-scatter leaves each
                # shard exactly its own [Q, R] slice of the merged
                # frontier, so no broadcast half ever runs
                merged_l = jax.lax.psum_scatter(
                    contrib, shard_ax, scatter_dimension=1, tiled=True
                )
                nxt_l = (merged_l > 0) & ~visited_l
                # scalar liveness psum: independent of the expansion
                # below, so it overlaps the gather/scatter compute
                live = jax.lax.psum(
                    nxt_l.any().astype(jnp.int32), shard_ax
                )
                return (
                    depth + 1,
                    live,
                    contrib_of(nxt_l, depth + 1 < cap[0]),
                    visited_l | nxt_l,
                )

            _d, _l, _c, visited_l = jax.lax.while_loop(
                cond_fn,
                body,
                (jnp.int32(0), live0, contrib0, frontier0_l),
            )
            return visited_l

        return shard_map(
            inner,
            mesh=mesh,
            in_specs=(
                P(shard_ax, None),
                P(shard_ax, None),
                P(rep_ax, shard_ax),
                P(None),
            ),
            out_specs=P(rep_ax, shard_ax),
            # legacy check_rep has no replication rule for while_loop;
            # newer check_vma analyzes it — shard_compat gates the check
            check_vma=WHILE_CHECK_OK,
        )(indptr_sh, dst_sh, roots, depth_cap)

    fn = jax.jit(step)
    _BFS_STEP_CACHE[key] = fn
    return fn


def bfs_reachability(
    scsr: ShardedCSR, roots: np.ndarray, max_depth: int
) -> np.ndarray:
    """Multi-source BFS closure: roots [Q, V] bool → visited [Q, V] bool
    (roots included at depth 0, like TRAVERSE / MATCH-WHILE emit-origin
    semantics). ``max_depth`` is a device operand — sweeping it reuses
    one executable — and the loop exits early when the global frontier
    drains before the cap."""
    mesh = scsr.mesh
    Q = roots.shape[0]
    reps = mesh.shape[config.mesh_replica_axis]
    q_pad = max(1, math.ceil(Q / reps)) * reps
    fr = np.zeros((q_pad, scsr.padded_vertices), bool)
    fr[:Q, : roots.shape[1]] = roots
    fr_dev = jax.device_put(
        jnp.asarray(fr),
        NamedSharding(
            mesh, P(config.mesh_replica_axis, config.mesh_shard_axis)
        ),
    )
    cap_dev = jax.device_put(
        np.asarray([max_depth], np.int32),
        NamedSharding(mesh, P(None)),
    )
    step = build_bfs_step(mesh)
    visited = step(scsr.indptr, scsr.dst, fr_dev, cap_dev)
    return fetch_sharded(visited)[:Q, : scsr.num_vertices]
