"""jax.shard_map compatibility shim.

Newer jax exports ``shard_map`` at top level with a ``check_vma``
kwarg; older releases (e.g. 0.4.x) only have
``jax.experimental.shard_map.shard_map`` whose equivalent kwarg is
``check_rep``. Import :func:`shard_map` from here so the sharded
execution plane runs on either.
"""

from __future__ import annotations

try:  # jax >= 0.4.31 area: top-level export, check_vma kwarg
    from jax import shard_map as _shard_map

    _LEGACY = False
except ImportError:  # older jax: the experimental home, check_rep kwarg
    from jax.experimental.shard_map import shard_map as _shard_map

    _LEGACY = True


def shard_map(f, **kw):
    if _LEGACY and "check_vma" in kw:
        kw["check_rep"] = kw.pop("check_vma")
    return _shard_map(f, **kw)
