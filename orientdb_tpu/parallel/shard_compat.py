"""jax.shard_map compatibility shim.

Newer jax exports ``shard_map`` at top level with a ``check_vma``
kwarg; older releases (e.g. 0.4.x) only have
``jax.experimental.shard_map.shard_map`` whose equivalent kwarg is
``check_rep``. Import :func:`shard_map` from here so the sharded
execution plane runs on either.

``WHILE_CHECK_OK`` gates the replication check for kernels whose body
carries a ``lax.while_loop``: the legacy ``check_rep`` machinery has no
replication rule for ``while`` (it raises NotImplementedError at trace
time), while the modern ``check_vma`` path handles it. The
frontier-sparse BFS step (parallel/sharded.py) early-exits with a
``while_loop`` and passes ``check_vma=WHILE_CHECK_OK`` so the check
stays on wherever the runtime supports it.
"""

from __future__ import annotations

try:  # jax >= 0.4.31 area: top-level export, check_vma kwarg
    from jax import shard_map as _shard_map

    _LEGACY = False
except ImportError:  # older jax: the experimental home, check_rep kwarg
    from jax.experimental.shard_map import shard_map as _shard_map

    _LEGACY = True

#: True when the active shard_map's replication check can analyze a
#: lax.while_loop body (legacy check_rep cannot)
WHILE_CHECK_OK = not _LEGACY


def shard_map(f, **kw):
    if _LEGACY and "check_vma" in kw:
        kw["check_rep"] = kw.pop("check_vma")
    return _shard_map(f, **kw)
