"""Mesh-sharded graph layout for the compiled MATCH engine.

The reference distributes a database by Hazelcast-replicating clusters to
server nodes ([E] OHazelcastPlugin / ODistributedStorage, SURVEY.md §2
"Distributed"); the TPU-native design instead **shards the adjacency
structure itself across the device mesh** and lets XLA collectives do the
merging:

- **out-CSR** row-sharded by source-vertex range ``[s·R, (s+1)·R)``:
  each shard holds a locally-rebased ``indptr`` and its slice of ``dst``;
- **in-CSR** row-sharded by destination-vertex range (reverse walks);
- the flat **edge list** (``edge_src``/``edge_dst``/``edge_id``) sliced
  into equal ranges for edge-parallel kernels (variable-depth bitmap hops,
  COUNT-pushdown segment sums).

Vertex and edge property columns are row-sharded too (vertex- /
edge-range ownership, `ops/device_graph.py`): per-device memory is
O(V/S + E/S), the SURVEY.md §7 SF100 per-chip budget. Property gathers
run in jit global view and XLA's SPMD partitioner inserts the
cross-shard collectives. Binding tables stay replicated (they are
query-sized, not graph-sized); each expansion step computes its shard's
local contribution under ``shard_map`` and the shards merge with
``all_gather`` (tables) or ``psum`` (bitmaps / weights) over ICI — the
SURVEY.md §5.7 frontier-merge design applied to the *real* engine.

All sharded buffers live in the owning ``DeviceGraph.arrays`` dict (keys
prefixed ``sh:``), placed with a ``NamedSharding`` over the mesh's
``shards`` axis, so compiled plans still receive ONE arg pytree shared by
every cached executable.
"""

from __future__ import annotations

import math
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from orientdb_tpu.parallel.shard_compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from orientdb_tpu.ops import csr as K
from orientdb_tpu.utils.config import config
from orientdb_tpu.utils.metrics import metrics



class ShardedEdgeArrays:
    """Host metadata for one edge class's sharded adjacency (the arrays
    themselves live in the DeviceGraph's flat dict)."""

    __slots__ = ("class_name", "prefix", "e_slice", "out_emax", "in_emax")

    def __init__(self, class_name: str, prefix: str):
        self.class_name = class_name
        self.prefix = prefix
        self.e_slice = 0  # edge-list slice width per shard
        self.out_emax = 0  # max local out-CSR edges across shards
        self.in_emax = 0


class MeshGraph:
    """Sharding context attached to a DeviceGraph."""

    def __init__(self, mesh: Mesh) -> None:
        if config.mesh_shard_axis not in mesh.shape:
            raise ValueError(
                f"mesh must have a {config.mesh_shard_axis!r} axis"
            )
        self.mesh = mesh
        self.n_shards = mesh.shape[config.mesh_shard_axis]
        self.rows_per_shard = 0
        self.edge: Dict[str, ShardedEdgeArrays] = {}

    def _spec(self) -> NamedSharding:
        return NamedSharding(self.mesh, P(config.mesh_shard_axis, None))

    def build(self, dg) -> None:
        """Populate ``dg.arrays`` with sharded adjacency for every edge
        class of the snapshot behind ``dg``."""
        S = self.n_shards
        V = dg.num_vertices
        self.rows_per_shard = max(1, math.ceil(max(V, 1) / S))
        # shard row-ranges as a DEVICE OPERAND [S, 2] (lo, hi): the
        # expansion kernels read their range from this array instead of
        # baking `shard_id * rows_per_shard` as a trace constant, so an
        # elastic re-shard (same padded dims, moved boundaries) reuses
        # every cached executable
        R = self.rows_per_shard
        spans = np.stack(
            [
                np.arange(S, dtype=np.int32) * R,
                (np.arange(S, dtype=np.int32) + 1) * R,
            ],
            axis=1,
        )
        dg.arrays["sh:rowspan"] = jax.device_put(spans, self._spec())
        for name, dec in dg.edges.items():
            csr = dg.snap.edge_classes[name]
            sea = ShardedEdgeArrays(name, f"sh:{name}")
            self.edge[name] = sea
            self._put_csr(
                dg, sea, "out", csr.indptr_out, csr.dst, eid_map=None
            )
            self._put_csr(
                dg, sea, "in", csr.indptr_in, csr.src, eid_map=csr.edge_id_in
            )
            self._put_edge_list(dg, sea, csr)

    # -- layout builders -----------------------------------------------------

    def _shard_rows(self, indptr: np.ndarray):
        """Split a global CSR into per-shard locally-rebased rows."""
        S, R = self.n_shards, self.rows_per_shard
        V = indptr.shape[0] - 1
        ind_l = np.zeros((S, R + 1), np.int64)
        bases = np.zeros(S, np.int32)
        slices = []
        for s in range(S):
            r0 = min(s * R, V)
            r1 = min(r0 + R, V)
            seg = indptr[r0 : r1 + 1].astype(np.int64) - int(indptr[r0])
            ind_l[s, : seg.shape[0]] = seg
            if seg.shape[0] < R + 1:
                ind_l[s, seg.shape[0] :] = seg[-1] if seg.shape[0] else 0
            bases[s] = int(indptr[r0])
            slices.append((int(indptr[r0]), int(indptr[r1])))
        return ind_l.astype(np.int32), bases, slices

    def _put_csr(self, dg, sea, tag, indptr, nbrs, eid_map):
        spec = self._spec()
        S = self.n_shards
        ind_l, bases, slices = self._shard_rows(indptr)
        emax = max(1, max((b - a) for a, b in slices))
        nbr_l = np.full((S, emax), -1, np.int32)
        eid_l = np.full((S, emax), -1, np.int32) if eid_map is not None else None
        for s, (a, b) in enumerate(slices):
            nbr_l[s, : b - a] = nbrs[a:b]
            if eid_l is not None:
                eid_l[s, : b - a] = eid_map[a:b]
        p = sea.prefix
        dg.arrays[f"{p}:{tag}:indptr"] = jax.device_put(jnp.asarray(ind_l), spec)
        dg.arrays[f"{p}:{tag}:nbr"] = jax.device_put(jnp.asarray(nbr_l), spec)
        dg.arrays[f"{p}:{tag}:ebase"] = jax.device_put(
            jnp.asarray(bases[:, None]), spec
        )
        if eid_l is not None:
            dg.arrays[f"{p}:{tag}:eid"] = jax.device_put(jnp.asarray(eid_l), spec)
        if tag == "out":
            sea.out_emax = emax
        else:
            sea.in_emax = emax

    def _put_edge_list(self, dg, sea, csr):
        """Equal edge-range slices for edge-parallel kernels."""
        spec = self._spec()
        S = self.n_shards
        E = csr.num_edges
        W = max(1, math.ceil(max(E, 1) / S))
        sea.e_slice = W
        src_l = np.full((S, W), -1, np.int32)
        dst_l = np.full((S, W), -1, np.int32)
        eid_l = np.full((S, W), -1, np.int32)
        edge_src = csr.edge_src_np()
        for s in range(S):
            a, b = min(s * W, E), min((s + 1) * W, E)
            src_l[s, : b - a] = edge_src[a:b]
            dst_l[s, : b - a] = csr.dst[a:b]
            eid_l[s, : b - a] = np.arange(a, b, dtype=np.int32)
        p = sea.prefix
        dg.arrays[f"{p}:el:src"] = jax.device_put(jnp.asarray(src_l), spec)
        dg.arrays[f"{p}:el:dst"] = jax.device_put(jnp.asarray(dst_l), spec)
        dg.arrays[f"{p}:el:eid"] = jax.device_put(jnp.asarray(eid_l), spec)


# ---------------------------------------------------------------------------
# sharded execution kernels (called from TpuMatchSolver when a mesh is
# attached; all run under shard_map inside the solver's eager record run
# and inside the compiled replay's single jit alike)
#
# Every kernel is a MEMOIZED jax.jit keyed by (kernel, mesh, axis names,
# structural statics) — operand shapes (the padded dims) ride the jit's
# own shape cache, and shard row-ranges arrive as the `sh:rowspan`
# device operand. Before the memo, the eager recording executed each
# shard_map body primitive-by-primitive (a fresh SPMD program compile
# per primitive per call — 171 XLA compiles for ONE probe query, the
# dominant term of BENCH_r04's anti-scaling 35.9→95.4 s mesh_scaling
# curve); now a recording costs one cached Execute per kernel call, a
# shard sweep compiles each geometry once, and revisiting a geometry
# compiles NOTHING (the zero-retrace contract tests/test_sharded.py
# asserts via the mesh.kernel_builds counter — it counts memoized
# wrapper BUILDS, the trace-cache roots; operand shapes ride each
# build's jit cache, so with an unchanged workload a zero delta means
# no new executables either, which the tests additionally pin through
# build identity).
# ---------------------------------------------------------------------------

_MESH_KERNEL_CACHE: Dict[Tuple, object] = {}


def _mesh_kernel(name: str, mesh: Mesh, builder, *static):
    """Memoized jitted shard_map kernel for one (mesh, axes, statics)
    geometry. ``builder(mesh, shard_ax, *static)`` constructs the
    callable only on a miss."""
    ax = config.mesh_shard_axis
    key = (name, mesh, ax, config.mesh_replica_axis) + static
    fn = _MESH_KERNEL_CACHE.get(key)
    if fn is None:
        fn = jax.jit(builder(mesh, ax, *static))
        _MESH_KERNEL_CACHE[key] = fn
        # geometry-compile observability: the zero-retrace tests and the
        # mesh_scaling evidence read this counter's deltas; the flight
        # record gets the event so a compile-tainted dispatch is
        # distinguishable from a steady-state replay on the timeline
        metrics.incr("mesh.kernel_builds")
        from orientdb_tpu.obs.timeline import mark as _tl_mark

        _tl_mark("kernel_build")
    return fn


def _merge_dtype(mesh: Mesh):
    """psum element type for 0/1 bitmap contributions: int8 carries
    sums ≤ n_shards exactly up to 127 shards at a quarter of int32's
    ring bytes."""
    return (
        jnp.int8 if mesh.shape[config.mesh_shard_axis] <= 127 else jnp.int32
    )


def _build_expand_totals(mesh: Mesh, ax: str):
    def local(ind_l, span_l, srcs_rep):
        ind_l = ind_l[0]
        lo, hi = span_l[0, 0], span_l[0, 1]  # row-range device operand
        owned = (srcs_rep >= lo) & (srcs_rep < hi)
        ls = jnp.where(owned, srcs_rep - lo, -1)
        counts = K.degree_counts(ind_l, ls)
        tot = counts.sum()[None]
        return jax.lax.all_gather(tot, ax).reshape(-1)

    def kern(ind_sh, span_sh, srcs):
        return shard_map(
            local,
            mesh=mesh,
            in_specs=(P(ax, None), P(ax, None), P(None)),
            out_specs=P(None),
            # the output IS replicated (it is an all_gather over the
            # shard axis), but VMA's static inference marks all_gather
            # results as varying — unlike psum — so the check cannot
            # hold here; the psum-output kernels below run with it ON
            check_vma=False,
        )(ind_sh, span_sh, srcs)

    return kern


def expand_totals(mesh: Mesh, ind_sh, span_sh, srcs) -> jnp.ndarray:
    """Per-shard expansion totals [S] (replicated on every device).

    Each shard counts the out-degrees of the binding-table sources it
    owns (global ids inside its ``sh:rowspan`` row range); the result
    sizes the static expansion cap and the global total for the
    SizeSchedule. The gathered payload is one scalar per shard — the
    live extent — never a capacity block (jaxlint's full-capacity
    all_gather rule guards the distinction)."""
    return _mesh_kernel("expand_totals", mesh, _build_expand_totals)(
        ind_sh, span_sh, srcs
    )


def _build_expand_gather(
    mesh: Mesh, ax: str, cap: int, cap_total: int, is_out: bool
):
    def local(ind_l, nbr_l, extra_l, span_l, srcs_rep):
        ind_l, nbr_l, extra_l = ind_l[0], nbr_l[0], extra_l[0]
        sid = jax.lax.axis_index(ax)
        lo, hi = span_l[0, 0], span_l[0, 1]
        owned = (srcs_rep >= lo) & (srcs_rep < hi)
        ls = jnp.where(owned, srcs_rep - lo, -1)
        counts = K.degree_counts(ind_l, ls)
        tot = counts.sum()
        # the offset prefix is collective (every shard needs it), the
        # expansion itself is not: issue the scalar all_gather FIRST so
        # it flies while the local gather below runs
        all_tot = jax.lax.all_gather(tot, ax)
        my_off = jnp.cumsum(all_tot)[sid] - tot

        def expand(_):
            offsets = K.exclusive_cumsum(counts)
            row, epos, nbr = K.gather_expand(
                ind_l, nbr_l, ls, offsets, tot, cap
            )
            if is_out:
                eid = jnp.where(epos >= 0, epos + extra_l[0], -1)
            else:
                eid = K.take_pad(extra_l, epos, jnp.int32(-1))
            # gather_expand front-packs: rows [0, tot) are live. Scatter
            # them at this shard's exclusive offset in the global
            # segment (values shifted +1 so the zero identity becomes
            # the -1 padding after the merge).
            pos = jnp.arange(cap, dtype=jnp.int32)
            dest = jnp.where(pos < tot, pos + my_off, cap_total)
            z = jnp.zeros(cap_total, jnp.int32)
            return (
                z.at[dest].add(row + 1, mode="drop"),
                z.at[dest].add(eid + 1, mode="drop"),
                z.at[dest].add(nbr + 1, mode="drop"),
            )

        def skip(_):
            # frontier-sparse: a shard owning NO live sources skips its
            # gather/scatter entirely (the cond predicate varies per
            # shard; the branches carry no collective)
            z = jnp.zeros(cap_total, jnp.int32)
            return z, z, z

        seg_row, seg_eid, seg_nbr = jax.lax.cond(
            tot > 0, expand, skip, jnp.int32(0)
        )
        # ONE fused ring reduce for the three packed segments: psum
        # merges the disjoint per-shard writes — O(pow2(global total))
        # bytes, never S·pow2(max local) capacity blocks
        m_row, m_eid, m_nbr = jax.lax.psum((seg_row, seg_eid, seg_nbr), ax)
        return m_row - 1, m_eid - 1, m_nbr - 1

    def kern(ind_sh, nbr_sh, extra_sh, span_sh, srcs):
        return shard_map(
            local,
            mesh=mesh,
            in_specs=(
                P(ax, None),
                P(ax, None),
                P(ax, None),
                P(ax, None),
                P(None),
            ),
            out_specs=(P(None), P(None), P(None)),
            check_vma=True,  # psum-merged outputs are provably replicated
        )(ind_sh, nbr_sh, extra_sh, span_sh, srcs)

    return kern


def expand_gather(
    mesh: Mesh,
    ind_sh,
    nbr_sh,
    extra_sh,
    span_sh,
    srcs,
    cap: int,
    cap_total: int,
    is_out: bool,
):
    """Sharded CSR expansion with a RING-compacted merge: every shard
    expands its owned sources into a static ``cap``-row local block,
    front-packs the live rows, scatters them at its global offset into a
    ``[cap_total]`` zero buffer, and the buffers merge with a ``psum``
    over the shard axis — XLA lowers it to the bandwidth-optimal ring
    reduce over ICI (SURVEY.md §5.7's ring exchange for binding-carrying
    expansions).

    vs the old ``all_gather`` of whole ``cap`` blocks, the merged
    segment is ``O(pow2(global total))`` instead of ``O(S·pow2(max
    local))``: under supernode skew (one shard's cap ≫ total/S) that is
    an up-to-S× saving in merge bytes and merged-table size. A shard
    whose local frontier slice is empty contributes a ``lax.cond``-
    skipped zero segment — its gather/scatter never runs.

    ``extra_sh`` is the per-shard global-edge-offset column (out-CSR:
    ``eid = local edge pos + base``) or the sharded ``edge_id_in`` map
    (in-CSR: local pos → out-order id); ``span_sh`` is the
    ``sh:rowspan`` row-range operand."""
    return _mesh_kernel(
        "expand_gather", mesh, _build_expand_gather, cap, cap_total, is_out
    )(ind_sh, nbr_sh, extra_sh, span_sh, srcs)


def _build_bitmap_hop(mesh: Mesh, ax: str):
    cdtype = _merge_dtype(mesh)

    def local(act_l, emit_l, eid_l, emask_rep, frontier_rep):
        act_l, emit_l, eid_l = act_l[0], emit_l[0], eid_l[0]
        em = K.take_pad(emask_rep, eid_l, False) & (act_l >= 0)

        def hop(_):
            return K.bitmap_hop(act_l, emit_l, em, frontier_rep).astype(
                cdtype
            )

        def skip(_):
            # frontier-sparse: dead frontier or mask-killed edge slice →
            # skip the [C, E_slice] gather and [C, vb] scatter entirely.
            # The predicate is deliberately gather-free (edge-list
            # slices see arbitrary sources, so per-shard frontier
            # locality does not exist here — the row-sharded BFS in
            # parallel/sharded.py owns that case).
            return jnp.zeros(frontier_rep.shape, cdtype)

        contrib = jax.lax.cond(
            em.any() & frontier_rep.any(), hop, skip, jnp.int32(0)
        )
        # packed-dtype psum: int8 0/1 contributions, a quarter of the
        # old int32 all-reduce bytes per hop
        return jax.lax.psum(contrib, ax) > 0

    def kern(act_sh, emit_sh, eid_sh, emask_global, frontier):
        return shard_map(
            local,
            mesh=mesh,
            in_specs=(
                P(ax, None),
                P(ax, None),
                P(ax, None),
                P(None),
                P(None, None),
            ),
            out_specs=P(None, None),
            check_vma=True,
        )(act_sh, emit_sh, eid_sh, emask_global, frontier)

    return kern


def sharded_bitmap_hop(
    mesh: Mesh, act_sh, emit_sh, eid_sh, emask_global, frontier
) -> jnp.ndarray:
    """One variable-depth frontier hop over the sharded edge list: each
    shard scatter-ORs its edge slice's activations, and the [C, vb]
    bitmaps merge with a packed (int8) psum over the shards axis
    (SURVEY.md §5.7); a shard with no live activations cond-skips its
    scatter."""
    return _mesh_kernel("bitmap_hop", mesh, _build_bitmap_hop)(
        act_sh, emit_sh, eid_sh, emask_global, frontier
    )


def _build_weight_pass(mesh: Mesh, ax: str):
    def local(seg_l, emit_l, eid_l, emask_rep, ok_rep, w_rep):
        seg_l, emit_l, eid_l = seg_l[0], emit_l[0], eid_l[0]
        vb = w_rep.shape[0]

        def wpass(_):
            em = K.take_pad(emask_rep, eid_l, False) & (seg_l >= 0)
            ok = K.take_pad(ok_rep, emit_l, False)
            vals = (em & ok).astype(w_rep.dtype) * K.take_pad(
                w_rep, emit_l, jnp.zeros((), w_rep.dtype)
            )
            return jax.ops.segment_sum(
                vals, jnp.clip(seg_l, 0, vb - 1), num_segments=vb
            )

        def skip(_):
            # padding-only edge slice (E < S·W rounding): nothing to sum
            return jnp.zeros(vb, w_rep.dtype)

        part = jax.lax.cond((seg_l >= 0).any(), wpass, skip, jnp.int32(0))
        return jax.lax.psum(part, ax)

    def kern(seg_sh, emit_sh, eid_sh, emask_global, dst_ok_global, w):
        return shard_map(
            local,
            mesh=mesh,
            in_specs=(
                P(ax, None),
                P(ax, None),
                P(ax, None),
                P(None),
                P(None),
                P(None),
            ),
            out_specs=P(None),
            check_vma=True,
        )(seg_sh, emit_sh, eid_sh, emask_global, dst_ok_global, w)

    return kern


def sharded_weight_pass(
    mesh: Mesh, seg_sh, emit_sh, eid_sh, emask_global, dst_ok_global, w
):
    """One COUNT-pushdown weight pass over the sharded edge list:
    ``new_w[v] = Σ_{local edges v→u} emask(e)·dst_ok(u)·w[u]`` per shard,
    psum-merged. ``dst_ok_global`` is the destination node-admission mask
    over the vertex universe (replicated); ``w`` [vb] carries the weights
    of the level below (all-ones for the last hop; its length IS vb)."""
    return _mesh_kernel("weight_pass", mesh, _build_weight_pass)(
        seg_sh, emit_sh, eid_sh, emask_global, dst_ok_global, w
    )
