"""Cluster membership, failure detection, and elastic failover.

Analog of the reference's cluster manager ([E] distributed/
``ODistributedServerManager`` + ``OHazelcastPlugin``: membership views,
node status machine NOT_AVAILABLE→ONLINE, and the failover step that
reassigns cluster ownership when the owner drops out of the view;
SURVEY.md §2 "Distributed", §5.3 "Failure detection / elastic
recovery"). Redesigned on this package's WAL-shipping replication
(`parallel/replication.py`) instead of Hazelcast group messaging:

- **membership**: one PRIMARY + N REPLICA members, each an HTTP server
  fronting a local database; replicas run `ReplicaPuller`s whose pulls
  double as heartbeats.
- **failure detection**: `down_after` consecutive failed pulls mark the
  primary DOWN (the node-status collapse) and notify the coordinator.
- **election**: the most-caught-up ONLINE replica wins — max applied
  LSN, ties broken by member name for determinism ([E] the "server with
  the newest database" rule of the reference's resync, not a vote: the
  stream is single-writer so the longest prefix is authoritative).
- **elastic recovery**: the winner promotes (its database becomes the
  writable source, WAL armed to CONTINUE the primary's LSN sequence);
  surviving replicas repoint to it. A replica whose delta range no
  longer exists (it lagged past the new primary's base) is rebuilt
  fresh and full-syncs — availability over resync cost, the v1 policy.

The coordinator is an in-process controller object: run it anywhere
with HTTP reach of the members (tests run all members in one process,
the same multi-server-in-one-JVM strategy the reference's distributed
tests use per SURVEY.md §4).
"""

from __future__ import annotations

import threading
import time as _time
from typing import Dict, List, Optional

from orientdb_tpu.models.database import Database
from orientdb_tpu.parallel.replication import (
    ReplicaPuller,
    ReplicationGap,
    enable_replication_source,
)
from orientdb_tpu.utils.logging import get_logger
from orientdb_tpu.utils.metrics import metrics

log = get_logger("cluster")


class ClusterMember:
    """One node: an HTTP server fronting a local database."""

    __slots__ = ("name", "server", "db", "role", "puller", "stream_pullers")

    def __init__(self, name: str, server, db: Database) -> None:
        self.name = name
        self.server = server
        self.db = db
        self.role = "REPLICA"  # PRIMARY | REPLICA | DOWN
        self.puller: Optional[ReplicaPuller] = None
        #: owner-name -> named-stream puller (multi-owner mode)
        self.stream_pullers: Dict[str, ReplicaPuller] = {}

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.server.http_port}"


def arm_promoted_source(db: Database, applied_lsn: int) -> None:
    """Make a promoted replica a replication source whose WAL CONTINUES
    the failed primary's LSN sequence.

    Without continuity a freshly armed WAL restarts at LSN 1 and a
    surviving replica at applied_lsn=N>1 silently never applies anything
    again. The base marker records "state as of ``applied_lsn``", and
    ``_wal_base_exact_ok`` says a replica AT exactly that LSN already
    holds the base state (unlike the late-armed-source marker, where
    LSN 0 state is non-empty and a fresh replica needs the checkpoint).
    """
    enable_replication_source(db)
    db._wal.next_lsn = max(db._wal.next_lsn, applied_lsn + 1)
    db._wal_base_lsn = applied_lsn
    db._wal_has_base = True
    db._wal_base_exact_ok = True


class Cluster:
    """Coordinator for one replicated database across member servers."""

    def __init__(
        self,
        dbname: str,
        user: str = "admin",
        password: str = "admin",
        interval: float = 0.25,
        down_after: int = 4,
        write_quorum: Optional[str] = None,
        quorum_timeout: float = 2.0,
    ) -> None:
        self.dbname = dbname
        self.user = user
        self.password = password
        self.interval = interval
        self.down_after = down_after
        #: None = async replication (v1); "majority" = every write blocks
        #: until a majority of the cluster holds it ([E] the per-database
        #: distributed config's writeQuorum:"majority")
        self.write_quorum = write_quorum
        self.quorum_timeout = quorum_timeout
        self.members: Dict[str, ClusterMember] = {}
        self.primary: Optional[str] = None
        self._lock = threading.RLock()
        self.failovers = 0
        #: periodic maintenance probe (partial-failure hardening): every
        #: probe_interval it sweeps each member's 2PC registry (so an
        #: IDLE member's expired staged locks release — presumed abort
        #: needs no traffic) and drives the in-doubt resolver
        #: (parallel/twophase.resolver) toward termination
        self.probe_interval = max(interval, 0.25)
        self._probe_stop = threading.Event()
        self._probe_thread: Optional[threading.Thread] = None

    # -- quorum plumbing ----------------------------------------------------

    def _replica_targets(self):
        with self._lock:
            return [
                (m.name, m.url)
                for m in self.members.values()
                if m.role == "REPLICA"
            ]

    def _cluster_size(self) -> int:
        with self._lock:
            # DOWN members still count toward the majority denominator: a
            # 3-node cluster that lost a node needs 2 acks, not 1-of-1
            return len(self.members)

    def _arm_quorum(self, db: Database) -> None:
        if self.write_quorum != "majority":
            return
        from orientdb_tpu.parallel.replication import QuorumPusher

        db._repl_quorum = QuorumPusher(
            self.dbname,
            self._replica_targets,
            self._cluster_size,
            user=self.user,
            password=self.password,
            timeout=self.quorum_timeout,
            # failovers counts completed promotions: the initial primary
            # writes at term 1, each successor at failovers+1 — replicas
            # fence any push below their highest seen term
            term=self.failovers + 1,
            source_db=db,
        )

    # -- membership ---------------------------------------------------------

    def set_primary(self, name: str, server, db: Database) -> ClusterMember:
        m = ClusterMember(name, server, db)
        m.role = "PRIMARY"
        enable_replication_source(db)
        # every member's HTTP listener can now serve the fleet view
        # (/cluster/health, /cluster/metrics — obs/cluster_view)
        server.cluster = self
        with self._lock:
            self.members[name] = m
            self.primary = name
        self._arm_quorum(db)
        return m

    def add_replica(self, name: str, server) -> ClusterMember:
        """Register a replica member; its local database lives on (and is
        served by) its own server so it can become a source later."""
        db = server.get_database(self.dbname)
        if db is None:
            db = server.create_database(self.dbname)
        m = ClusterMember(name, server, db)
        server.cluster = self
        with self._lock:
            self.members[name] = m
        return m

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "Cluster":
        with self._lock:
            for m in self.members.values():
                if m.role == "REPLICA" and m.puller is None:
                    self._start_puller(m)
            # under the lock: two concurrent start() calls must not
            # each observe None and spawn duplicate probe loops (the
            # overwritten handle would never be joined by stop())
            if self._probe_thread is None:
                self._probe_stop.clear()
                self._probe_thread = threading.Thread(
                    target=self._probe_loop,
                    name="cluster-probe",
                    daemon=True,
                )
                self._probe_thread.start()
        return self

    def probe_once(self) -> None:
        """One maintenance round: sweep every member's 2PC registry
        (releasing expired staged locks on QUIET members — before this,
        presumed abort only fired when another registry call happened
        to arrive) and give the in-doubt resolver a resolution round."""
        from orientdb_tpu.parallel.twophase import resolver

        with self._lock:
            dbs = [m.db for m in self.members.values()]
        for db in dbs:
            reg = getattr(db, "_tx2pc_registry", None)
            if reg is not None:
                try:
                    reg.sweep()
                except Exception:  # pragma: no cover - keep probing
                    log.exception("2pc sweep failed on a member")
        try:
            resolver.resolve_once()
        except Exception:  # pragma: no cover - keep probing
            log.exception("in-doubt resolution round failed")

    def _probe_loop(self) -> None:
        while not self._probe_stop.is_set():
            try:
                self.probe_once()
            except Exception:  # pragma: no cover - the loop must live
                log.exception("cluster probe round failed")
            self._probe_stop.wait(self.probe_interval)

    def stop(self) -> None:
        self._probe_stop.set()
        with self._lock:
            t = self._probe_thread
            self._probe_thread = None
        if t is not None and t is not threading.current_thread():
            t.join(timeout=5)
        with self._lock:
            members = list(self.members.values())
        for m in members:
            if m.puller is not None:
                m.puller.stop()
            for p in m.stream_pullers.values():
                p.stop()  # named-stream pullers (multi-owner mode)
            q = getattr(m.db, "_repl_quorum", None)
            if q is not None:
                m.db._repl_quorum = None
                q.close()

    def _start_puller(self, m: ClusterMember, applied_lsn: int = 0) -> None:
        primary = self.members[self.primary]
        # write-ownership routing ([E] per-cluster server-owner lists;
        # v1: the primary owns every cluster): writes arriving at this
        # non-owner member forward to the owner instead of diverging
        from orientdb_tpu.parallel.forwarding import WriteOwner

        m.db._write_owner = WriteOwner(
            primary.url, self.dbname, self.user, self.password
        )
        m.puller = ReplicaPuller(
            primary.url,
            self.dbname,
            m.db,
            user=self.user,
            password=self.password,
            interval=self.interval,
            down_after=self.down_after,
            # the report names WHICH primary this puller was watching so a
            # late report about an already-replaced primary can't demote
            # its healthy successor
            on_source_down=lambda name=m.name, watched=primary.name: (
                self._primary_down(name, watched)
            ),
        )
        m.puller.applied_lsn = applied_lsn
        m.puller.start()

    def stop_replica(self, name: str) -> None:
        """Stop ``name``'s puller — a simulated member death (the
        chaos/simulator hook): the member stops replicating and its
        applied LSN freezes while the primary's head advances, so the
        replication-lag alert sees exactly what a dead replica looks
        like. The primary keeps serving; no failover triggers (a dead
        REPLICA must never cause an election)."""
        with self._lock:
            m = self.members.get(name)
        if m is not None and m.puller is not None:
            m.puller.stop()

    def restart_replica(self, name: str) -> None:
        """Bring a stopped replica back (simulated rejoin): a fresh
        puller resumes from the member's settled cursor — the max of
        the old puller's applied LSN and the db-level floor — and the
        normal pull path takes it from there (including the gap/
        full-resync handling a long outage may need)."""
        with self._lock:
            m = self.members.get(name)
            if m is None or m.role != "REPLICA":
                return
            applied = max(
                m.puller.applied_lsn if m.puller is not None else 0,
                getattr(m.db, "_repl_applied_lsn", 0),
            )
            self._start_puller(m, applied_lsn=applied)

    # -- failure handling ---------------------------------------------------

    def _primary_down(self, reporter: str, watched: str) -> None:
        """A replica's failure detector collapsed the primary's status.

        First reporter wins the right to run the election; later reports
        about the SAME dead primary (``watched`` no longer the current
        primary) find the view already updated and return — a stale
        report must never demote the freshly promoted successor."""
        with self._lock:
            old = self.primary
            if old is None or old != watched:
                return  # failover already ran; stale report
            if self.members[old].role != "PRIMARY":
                return
            live = self.members[old]
            live.role = "DOWN"
            metrics.incr("cluster.primary_down")
            log.warning(
                "primary %s marked DOWN (reported by %s); electing", old, reporter
            )
            winner = self._elect()
            if winner is None:
                log.error("no ONLINE replica to promote; cluster is read-only")
                self.primary = None
                return
            self._promote_locked(winner)

    def _settled_lsn(self, m: ClusterMember) -> int:
        """Stop m's puller and return its applied LSN with no apply still
        in flight.

        `request_stop` + acquiring the db's apply lock once is a barrier:
        `pull_once` re-checks the stop flag under that lock, so after this
        returns the old puller can never apply another entry. Without the
        barrier a survivor could finish applying a fetched batch after the
        coordinator sampled its LSN, end up AHEAD of the elected primary,
        and silently diverge (its dedup floor skips the new primary's
        conflicting entries at the same LSNs)."""
        m.puller.request_stop()
        with m.db._repl_lock:
            return max(
                m.puller.applied_lsn,
                getattr(m.db, "_repl_applied_lsn", 0),
            )

    def _elect(self) -> Optional[str]:
        """Most-caught-up replica: max settled applied LSN, name-ordered
        ties. Stops every candidate's puller (they are all about to be
        promoted or repointed anyway) so the sampled LSNs are final."""
        best: Optional[ClusterMember] = None
        best_lsn = -1
        for m in sorted(self.members.values(), key=lambda m: m.name):
            if m.role != "REPLICA" or m.puller is None:
                continue
            lsn = self._settled_lsn(m)
            m.puller.applied_lsn = lsn  # promotion/repoint read this
            if lsn > best_lsn:
                best, best_lsn = m, lsn
        return best.name if best is not None else None

    def promote(self, name: str) -> None:
        """Manual failover entry point (planned maintenance)."""
        with self._lock:
            old = self.primary
            if old is not None and old in self.members:
                self.members[old].role = "DOWN"
            self._promote_locked(name)

    def _promote_locked(self, name: str) -> None:
        m = self.members[name]
        # settle, not just read: the manual promote() path reaches here
        # without _elect's stop-and-settle pass
        lsn = self._settled_lsn(m) if m.puller is not None else 0
        if m.puller is not None:
            # signal-only stop: sibling puller threads may be blocked on
            # this cluster's lock to report the same dead primary — a
            # joining stop() would stall failover 5 s per such thread
            m.puller.request_stop()
            m.puller.status = "PROMOTED"
            m.puller = None
        arm_promoted_source(m.db, lsn)
        m.db._write_owner = None  # the successor OWNS writes now
        m.role = "PRIMARY"
        self.primary = name
        self.failovers += 1  # before arming: the successor's term must
        self._arm_quorum(m.db)  # exceed every predecessor's
        if self.write_quorum is not None:
            # fence the successor's OWN apply endpoint too: a deposed
            # primary pushing a CONTIGUOUS entry at its stale term would
            # otherwise be applied here (replicas are fenced in _repoint,
            # but nothing raised the new primary's term)
            m.db._repl_term = max(
                getattr(m.db, "_repl_term", 0), self.failovers + 1
            )
        metrics.incr("cluster.failover")
        log.warning("promoted %s to PRIMARY at lsn %d", name, lsn)
        for other in self.members.values():
            if other.name == name:
                continue
            # EVERY other member — including the deposed/DOWN old primary
            # — forwards writes to the successor from now on: a falsely-
            # declared-down primary that resumes must not keep accepting
            # local writes with _write_owner=None (silent divergence)
            from orientdb_tpu.parallel.forwarding import WriteOwner

            other.db._write_owner = WriteOwner(
                m.url, self.dbname, self.user, self.password
            )
            if other.role != "REPLICA":
                continue
            self._repoint(other)

    def _repoint(self, m: ClusterMember) -> None:
        """Point a surviving replica at the new primary, preserving its
        applied LSN; if its delta range is gone (it lagged past the new
        primary's base) OR it got AHEAD of the new primary (applied more
        of the dead primary's stream than the winner — divergence the
        dedup floor would otherwise hide), rebuild it fresh and
        full-sync."""
        applied = self._settled_lsn(m) if m.puller is not None else 0
        if m.puller is not None:
            m.puller.request_stop()  # signal-only: see _promote_locked
            m.puller = None
        if self.write_quorum is not None:
            # fence the dead primary NOW, not at the successor's first
            # write: a partitioned predecessor pushing at its old term
            # must never be acked by a repointed survivor
            m.db._repl_term = max(
                getattr(m.db, "_repl_term", 0), self.failovers + 1
            )
        new_primary = self.members[self.primary]
        base = getattr(new_primary.db, "_wal_base_lsn", 0)
        if applied > base:
            log.warning(
                "replica %s applied past the new primary's base "
                "(%d > %d); rebuilding fresh for full sync",
                m.name,
                applied,
                base,
            )
            metrics.incr("cluster.replica_rebuild")
            m.server.drop_database(self.dbname)
            m.db = m.server.create_database(self.dbname)
            if self.write_quorum is not None:
                m.db._repl_term = self.failovers + 1
            self._start_puller(m, applied_lsn=0)
            return
        self._start_puller(m, applied_lsn=applied)
        try:
            m.puller.pull_once()  # synchronous probe: surfaces a gap now
        except ReplicationGap:
            log.warning(
                "replica %s lagged past the new primary's base; "
                "rebuilding fresh for full sync",
                m.name,
            )
            metrics.incr("cluster.replica_rebuild")
            m.puller.request_stop()
            m.server.drop_database(self.dbname)
            m.db = m.server.create_database(self.dbname)
            if self.write_quorum is not None:
                m.db._repl_term = self.failovers + 1
            self._start_puller(m, applied_lsn=0)
        except Exception:
            # transient; the puller thread keeps retrying — but the
            # probe failure itself must leave a trail
            metrics.incr("cluster.probe_pull_error")
            log.warning(
                "synchronous pull probe for %s failed", m.name,
                exc_info=True,
            )

    # -- per-class owner streams (multi-owner writes) -----------------------

    def assign_class_owner(self, class_name: str, member_name: str) -> None:
        """Give ``member_name`` WRITE OWNERSHIP of one class ([E] the
        reference's per-cluster server-owner lists,
        ``ODistributedConfiguration``, SURVEY.md:126): that member then
        accepts local writes for the class CONCURRENTLY with the
        primary's writes to everything else — two owner streams instead
        of one write-serialization point.

        Mechanics: the owner's database arms as a second replication
        source (its WAL carries ONLY its own locally-committed ops —
        foreign-stream applies suppress re-logging); every other member
        starts a NAMED-stream puller on it (delta-only, per-stream
        floor) and forwards writes of this class to the new owner.

        Scope (documented v2 limits): async replication mode only (no
        quorum interplay); conflict semantics for two streams touching
        one record are last-writer-wins by arrival; a dead SECONDARY
        owner is not auto-detected — reassign its classes to a live
        member by calling this again (routes and pullers update in
        place). Transactions MAY span owners: both tx paths commit
        cross-owner batches through 2PC (parallel/twophase)."""
        if self.write_quorum is not None:
            raise ValueError(
                "per-class owner streams need async mode (write_quorum "
                "None): quorum counting is single-stream"
            )
        from orientdb_tpu.parallel.forwarding import WriteOwner

        # DDL flows through the PRIMARY stream, never the owner's:
        # record entries carry explicit rids, so cluster-id allocation
        # must be identical on every member — two streams allocating
        # clusters independently would silently collide rid spaces.
        # The owner must HOLD the class before it accepts local writes.
        owner = self.members[member_name]
        pdb = self.members[self.primary].db
        if not pdb.schema.exists_class(class_name):
            pdb.schema.create_vertex_class(class_name)
        deadline = _time.time() + 15.0
        while (
            not owner.db.schema.exists_class(class_name)
            and _time.time() < deadline
        ):
            _time.sleep(0.02)
        if not owner.db.schema.exists_class(class_name):
            raise RuntimeError(
                f"owner '{member_name}' did not replicate class "
                f"'{class_name}' in time; cannot assign ownership"
            )
        with self._lock:
            key = class_name.lower()
            # arm the owner as a delta-only replication source: members
            # already hold its base state via the primary stream. Its
            # WAL carries ONLY locally-committed ops — applies of the
            # primary (or any foreign) stream suppress re-logging
            enable_replication_source(owner.db)
            owner.db._wal_base_exact_ok = True
            owner.db._wal_foreign_suppress = True
            # the owner commits this class locally even though it
            # forwards everything else
            owner.db._class_owners[key] = None
            route = WriteOwner(
                owner.url, self.dbname, self.user, self.password
            )
            for m in self.members.values():
                if m.name == member_name:
                    continue
                m.db._class_owners[key] = route
                # one named-stream puller per (consumer, owner) pair
                streams = m.stream_pullers
                if member_name not in streams:
                    p = ReplicaPuller(
                        owner.url,
                        self.dbname,
                        m.db,
                        user=self.user,
                        password=self.password,
                        interval=self.interval,
                        down_after=self.down_after,
                        stream=member_name,
                    )
                    streams[member_name] = p
                    p.start()
            metrics.incr("cluster.class_owner_assigned")

    # -- introspection ------------------------------------------------------

    def ownership(self) -> Dict[str, str]:
        """Per-class write-owner map ([E] ODistributedConfiguration's
        server-owner lists). Default policy: the primary owns every
        class's clusters; `assign_class_owner` overrides per class."""
        with self._lock:
            if self.primary is None:
                return {}
            pdb = self.members[self.primary].db
            assigned = {}  # lower -> (display name, owner member)
            for m in self.members.values():
                for cls, owner in m.db._class_owners.items():
                    if owner is None:
                        c = m.db.schema.get_class(cls)
                        assigned[cls] = (c.name if c else cls, m.name)
            out = {
                c.name: assigned.get(c.name.lower(), (None, self.primary))[1]
                for c in pdb.schema.classes()
                if not c.abstract
            }
            for _key, (disp, owner) in assigned.items():
                # an assigned class may not have replicated into the
                # primary's schema yet — it is still owned
                out.setdefault(disp, owner)
            return out

    def status(self) -> Dict:
        with self._lock:
            return {
                "dbname": self.dbname,
                "primary": self.primary,
                "failovers": self.failovers,
                "members": {
                    m.name: {
                        "role": m.role,
                        "url": m.url,
                        **(m.puller.lag() if m.puller is not None else {}),
                    }
                    for m in self.members.values()
                },
            }

    def primary_db(self) -> Optional[Database]:
        with self._lock:
            if self.primary is None:
                return None
            return self.members[self.primary].db
