"""Shared retry + circuit-breaker policy for inter-node channels.

Every inter-node call (forwarding ``_req``, ``QuorumPusher._post``,
2PC RPCs, the client's failover reconnect) used to fail hard on its
first timeout and reconnect with zero backoff — a flapping member got
hammered by every peer in lockstep, and a dead one cost every caller a
full timeout per call. This module is the one place that policy lives:

- :class:`RetryPolicy` — capped exponential backoff with full jitter
  drawn from an optional seeded rng (deterministic chaos runs), a
  per-call attempt cap AND a total wall-clock budget, honoring a
  server-provided ``retry_after`` hint (the admission-control 503s)
  over the computed delay.
- :class:`CircuitBreaker` — the classic closed → open → half-open
  machine, one per named channel (:func:`breaker` get-or-creates from
  a process-wide registry). While open, calls fail fast with
  :class:`CircuitOpenError` (an ``OSError``, so existing channel-error
  handling applies) instead of burning a timeout each. State and trip
  counts export through the PR-1 metrics registry
  (``breaker.<name>.state`` gauge: 0 closed / 1 open / 2 half-open;
  ``breaker.trip`` counter) and through ``/cluster/health``
  (:func:`breaker_snapshot`).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Iterator, Optional, Tuple, Type

from orientdb_tpu.utils.logging import get_logger
from orientdb_tpu.utils.metrics import metrics

log = get_logger("resilience")


class RetryBudgetExceeded(OSError):
    """The retry policy ran out of attempts or wall-clock budget; the
    ``__cause__`` chain carries the last underlying failure."""


class CircuitOpenError(OSError):
    """The channel's breaker is open: failing fast instead of waiting
    out another timeout against a member already known unhealthy."""


class RetryPolicy:
    """Capped jittered exponential backoff with a total budget.

    ``delays()`` yields the sleep before retry *i* (full jitter:
    ``uniform(0, min(cap, base * 2**i))``, never below ``floor_s``);
    :meth:`call` runs a function under the policy. A raised exception
    with a ``retry_after`` attribute (the admission-control 503s)
    overrides the computed delay for that step.
    """

    def __init__(
        self,
        attempts: int = 4,
        base_s: float = 0.05,
        cap_s: float = 2.0,
        budget_s: Optional[float] = 10.0,
        floor_s: float = 0.005,
        seed: Optional[int] = None,
    ) -> None:
        import random

        self.attempts = max(1, attempts)
        self.base_s = base_s
        self.cap_s = cap_s
        self.budget_s = budget_s
        self.floor_s = floor_s
        self._rng = random.Random(seed) if seed is not None else random

    def delays(self) -> Iterator[float]:
        for i in range(self.attempts - 1):
            hi = min(self.cap_s, self.base_s * (2 ** i))
            yield max(self.floor_s, self._rng.uniform(0.0, hi))

    def call(
        self,
        fn: Callable,
        *args,
        retry_on: Tuple[Type[BaseException], ...] = (OSError,),
        give_up_on: Tuple[Type[BaseException], ...] = (),
        sleep: Callable[[float], None] = time.sleep,
        **kw,
    ):
        """Run ``fn`` with retries on ``retry_on`` exceptions.
        ``give_up_on`` wins over ``retry_on`` (e.g. retry OSError but
        never a CircuitOpenError). Exhaustion raises
        :class:`RetryBudgetExceeded` from the last failure."""
        deadline = (
            None
            if self.budget_s is None
            else time.monotonic() + self.budget_s
        )
        last: Optional[BaseException] = None
        it = self.delays()
        for attempt in range(self.attempts):
            try:
                return fn(*args, **kw)
            except give_up_on:
                raise
            except retry_on as e:
                last = e
                delay = next(it, None)
                if delay is None:
                    break
                hint = getattr(e, "retry_after", None)
                if hint is not None:
                    delay = max(delay, float(hint))
                if deadline is not None and (
                    time.monotonic() + delay >= deadline
                ):
                    break
                metrics.incr("resilience.retry")
                sleep(delay)
        raise RetryBudgetExceeded(
            f"retries exhausted after {self.attempts} attempt(s): {last}"
        ) from last


#: CircuitBreaker.state codes for the exported gauge
STATE_CLOSED, STATE_OPEN, STATE_HALF_OPEN = 0, 1, 2
_STATE_NAMES = {0: "closed", 1: "open", 2: "half_open"}


class CircuitBreaker:
    """Per-channel failure fuse (closed → open → half-open → closed).

    ``allow()`` is the admission check: True in closed, True for ONE
    probe call per ``reset_s`` window while open (that call runs
    half-open), False otherwise. ``record_success``/``record_failure``
    report the outcome; ``failure_threshold`` consecutive failures trip
    the breaker. :meth:`call` bundles the three for the common shape.
    """

    def __init__(
        self,
        name: str,
        failure_threshold: int = 5,
        reset_s: float = 2.0,
    ) -> None:
        self.name = name
        self.failure_threshold = max(1, failure_threshold)
        self.reset_s = reset_s
        self._lock = threading.Lock()
        self._state = STATE_CLOSED
        self._failures = 0  # consecutive
        self._opened_at = 0.0
        self._probing = False  # a half-open trial is in flight
        self.trips = 0
        self._export()

    # -- state machine -------------------------------------------------------

    def _export(self) -> None:
        metrics.gauge(f"breaker.{self.name}.state", self._state)

    def _set(self, state: int) -> None:
        if state != self._state:
            log.warning(
                "breaker %s: %s -> %s",
                self.name,
                _STATE_NAMES[self._state],
                _STATE_NAMES[state],
            )
        self._state = state
        self._export()

    def allow(self) -> bool:
        with self._lock:
            if self._state == STATE_CLOSED:
                return True
            now = time.monotonic()
            if (
                self._state == STATE_OPEN
                and now - self._opened_at >= self.reset_s
            ):
                self._set(STATE_HALF_OPEN)
                self._probing = False
            if self._state == STATE_HALF_OPEN and not self._probing:
                self._probing = True  # exactly one probe at a time
                return True
            metrics.incr("breaker.fast_fail")
            return False

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._probing = False
            if self._state != STATE_CLOSED:
                self._set(STATE_CLOSED)

    def record_failure(self) -> None:
        with self._lock:
            self._failures += 1
            self._probing = False
            if self._state == STATE_HALF_OPEN or (
                self._state == STATE_CLOSED
                and self._failures >= self.failure_threshold
            ):
                self._opened_at = time.monotonic()
                if self._state != STATE_OPEN:
                    self.trips += 1
                    metrics.incr("breaker.trip")
                self._set(STATE_OPEN)

    # -- call wrapper --------------------------------------------------------

    def call(
        self,
        fn: Callable,
        *args,
        failure_on: Tuple[Type[BaseException], ...] = (OSError,),
        success_on: Tuple[Type[BaseException], ...] = (),
        **kw,
    ):
        """Run ``fn`` under the breaker: fast-fail while open, count
        ``failure_on`` exceptions (anything else records success: the
        CHANNEL worked). ``success_on`` wins over ``failure_on`` — an
        application-level ``urllib.error.HTTPError`` is an OSError by
        inheritance but proves the channel healthy."""
        if not self.allow():
            raise CircuitOpenError(
                f"circuit '{self.name}' is open "
                f"(trips={self.trips}); failing fast"
            )
        try:
            out = fn(*args, **kw)
        except success_on:
            self.record_success()
            raise
        except failure_on:
            self.record_failure()
            raise
        except BaseException:
            self.record_success()
            raise
        self.record_success()
        return out

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            return {
                "state": _STATE_NAMES[self._state],
                "consecutive_failures": self._failures,
                "trips": self.trips,
            }


# -- process-wide breaker registry ------------------------------------------

_breakers: Dict[str, CircuitBreaker] = {}
_breakers_lock = threading.Lock()


def breaker(
    name: str, failure_threshold: int = 5, reset_s: float = 2.0
) -> CircuitBreaker:
    """Get-or-create the named channel's breaker. Names are
    ``<channel>:<target>`` (e.g. ``fwd:http://127.0.0.1:40213``) so one
    dead member's fuse never blocks a healthy sibling."""
    br = _breakers.get(name)
    if br is None:
        with _breakers_lock:
            br = _breakers.get(name)
            if br is None:
                br = _breakers[name] = CircuitBreaker(
                    name, failure_threshold, reset_s
                )
    return br


def breaker_snapshot() -> Dict[str, Dict[str, object]]:
    """Every breaker's state for ``/cluster/health`` / the bundle."""
    with _breakers_lock:
        items = list(_breakers.items())
    return {name: br.snapshot() for name, br in items}


def reset_breakers() -> None:
    """Drop every registered breaker (test isolation)."""
    with _breakers_lock:
        _breakers.clear()
