"""Two-phase commit for cross-owner distributed transactions.

Analog of the reference's distributed transaction protocol ([E]
``ONewDistributedTxContextImpl`` / 2-phase task batches shipped to each
involved cluster-owner server, SURVEY.md:126): a transaction whose
operations resolve to MORE THAN ONE write owner (per-class owner
streams, ``Cluster.assign_class_owner``) executes as coordinator-driven
2PC instead of being rejected.

Protocol:

- **Phase 1 (prepare)** — the coordinator partitions the buffered ops
  by resolved owner and ships each remote sub-batch to its owner
  (``POST /tx2pc/<db>`` ``phase=prepare``). The owner validates MVCC
  base versions, acquires record locks on every updated/deleted rid
  (``db._tx2pc_locks``), and stages the batch with a deadline. Locks
  are honored by every local write path: a concurrent save/delete (or
  local tx commit) touching a locked rid raises
  ``ConcurrentModificationError`` until the stage resolves.
- **Phase 2 (commit/abort)** — once every participant has prepared,
  the coordinator commits participants in temp-reference dependency
  order (a participant creating records referenced by another's edge
  ops commits first), threading the accumulated ``{temp rid → real
  rid}`` map through each commit. An abort (any prepare failing)
  releases locks with nothing applied anywhere.
- **Expiry (presumed abort)** — a staged batch whose coordinator
  vanishes self-aborts after its TTL, releasing locks; a late commit
  for an expired txid raises and the coordinator surfaces
  ``TxInDoubtError``.

The owner-side commit executes the sub-batch through one ordinary
LOCAL transaction (``execute_tx_ops``), so it hits the WAL as a single
atomic entry and replicates through the owner's own stream exactly
like a directly-forwarded transaction.

Durability & recovery (partial-failure hardening):

- a durable participant WAL-logs every prepare (``tx2pc_prepare``) and
  every abort decision (``tx2pc_decision``); a phase-2 commit's ``tx``
  entry carries ``txid2pc``, so the three records together classify any
  txid after a crash. ``recover_from_wal`` (called by
  ``storage/durability.open_database``) RE-STAGES prepared-undecided
  transactions — locks and all — instead of silently losing them.
- the coordinator WAL-logs its commit decision (``tx2pc_coord`` with
  participant descriptors) before phase 2 and ``tx2pc_coord_done``
  after, so an interrupted round is re-drivable.
- :data:`resolver` (an :class:`IndoubtResolver`) terminates every
  in-doubt transaction with no human in the loop: it replays the
  recorded commit at participants that missed phase 2 (with backoff),
  treats a participant's "unknown txid" as its presumed abort, and is
  driven from the cluster's periodic probe
  (``parallel/cluster.Cluster.probe_once``).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from orientdb_tpu.models.rid import RID
from orientdb_tpu.utils.logging import get_logger
from orientdb_tpu.utils.metrics import metrics

log = get_logger("twophase")

#: default seconds a prepared (locked) batch survives without a
#: coordinator decision before presumed-abort releases its locks
DEFAULT_TTL = 60.0


class TwoPhaseError(Exception):
    """Protocol error: unknown/expired txid, double prepare, etc."""


class TxInDoubtError(Exception):
    """A participant failed AFTER the commit decision: some
    participants applied, this one did not. The coordinator surfaces
    the partial state instead of pretending either outcome.

    ``report`` is the structured in-doubt record (txid, trace id,
    committed/failed/skipped participants, unresolved temp rids) — the
    same dict logged to :data:`INDOUBT_LOG` for the debug bundle."""

    def __init__(self, msg: str, report: Optional[Dict] = None) -> None:
        super().__init__(msg)
        self.report = report or {}


#: recent coordinator-side in-doubt reports, newest last — the debug
#: bundle (obs/bundle) and /cluster/health read it; bounded so an
#: unlucky fleet can't grow it without limit
from collections import deque as _deque  # noqa: E402

INDOUBT_LOG: "_deque" = _deque(maxlen=64)


class TxOpError(Exception):
    """An op inside a batch is malformed or references a missing
    record; carries the HTTP status the wire route should answer."""

    def __init__(self, code: int, msg: str) -> None:
        super().__init__(msg)
        self.code = code
        self.msg = msg


def _is_temp(rid_str: str) -> bool:
    """Temp rids are '#-1:-N' — cluster -1, negative position."""
    return rid_str.startswith("#-1:")


def substitute_rids(ops: List[Dict], rid_map: Dict[str, str]) -> None:
    """Rewrite edge endpoints through the accumulated temp→real map
    (in place). Only edge from/to carry cross-participant temps; link
    FIELD values holding temps are a documented v1 non-feature."""
    if not rid_map:
        return
    for op in ops:
        if op.get("kind") == "edge":
            op["from"] = rid_map.get(op["from"], op["from"])
            op["to"] = rid_map.get(op["to"], op["to"])


def _load_with_wait(db, rid: RID, deadline: float):
    """Load a record, polling until ``deadline`` — a cross-owner edge
    endpoint committed at another participant arrives here via async
    replication moments after that participant's phase-2."""
    doc = db.load(rid)
    while doc is None and time.time() < deadline:
        time.sleep(0.02)
        doc = db.load(rid)
    return doc


def execute_tx_ops(
    db, ops: List[Dict], endpoint_wait: float = 0.0
) -> Tuple[List[Dict], Dict[str, str]]:
    """Run a JSON op batch as ONE local transaction — all-or-nothing,
    MVCC-checked against the shipped base versions. Shared by the
    forwarded-tx route (``POST /tx``) and the 2PC commit phase.

    Forces a LOCAL ``exec.tx.Transaction`` even on a member whose
    ``db.begin()`` would hand back a ForwardedTransaction: by
    construction every op in the batch is for a class THIS member owns.

    Returns ``(results, temp_map)`` — ``results`` aligned with ``ops``
    as ``{"@rid": ..., "@version": ...}`` dicts (``{}`` for deletes),
    ``temp_map`` mapping shipped temp rid strings to real rid strings.
    """
    from orientdb_tpu.exec.tx import Transaction
    from orientdb_tpu.storage.durability import _dec

    if db.tx is not None:
        raise TwoPhaseError("transaction already active on this thread")
    deadline = time.time() + endpoint_wait
    results: List[Optional[object]] = []
    temp_map: Dict[str, object] = {}
    t = Transaction(db)
    db._tx_local.tx = t
    try:
        for op in ops:
            kind = op["kind"]
            fields = {k: _dec(v) for k, v in op.get("fields", {}).items()}
            if kind == "create":
                if op.get("type") == "vertex":
                    doc = db.new_vertex(op["class"], **fields)
                elif op.get("type") == "blob":
                    doc = db.new_blob(fields.pop("data", b"") or b"")
                    for k, v in fields.items():
                        doc.set(k, v)
                    db.save(doc)
                else:
                    doc = db.new_element(op["class"], **fields)
                temp_map[op["temp"]] = doc
                results.append(doc)
            elif kind == "edge":
                src = temp_map.get(op["from"]) or _load_with_wait(
                    db, RID.parse(op["from"]), deadline
                )
                dst = temp_map.get(op["to"]) or _load_with_wait(
                    db, RID.parse(op["to"]), deadline
                )
                if src is None or dst is None:
                    raise TxOpError(404, "edge endpoint not found")
                e = db.new_edge(op["class"], src, dst, **fields)
                temp_map[op["temp"]] = e
                results.append(e)
            elif kind == "update":
                cur = db.load(RID.parse(op["rid"]))
                if cur is None:
                    raise TxOpError(404, f"record {op['rid']} not found")
                base = op.get("base_version")
                if base is not None and cur.version != base:
                    from orientdb_tpu.models.database import (
                        ConcurrentModificationError,
                    )

                    raise ConcurrentModificationError(
                        f"{op['rid']}: stored v{cur.version} != base v{base}"
                    )
                sent = set(fields)
                for k in list(cur.fields()):
                    if k not in sent:
                        cur.remove_field(k)
                for k, v in fields.items():
                    cur.set(k, v)
                db.save(cur)
                results.append(cur)
            elif kind == "delete":
                cur = db.load(RID.parse(op["rid"]))
                if cur is not None:
                    base = op.get("base_version")
                    if base is not None and cur.version != base:
                        # a forwarded delete carries the version its tx
                        # read: deleting over a concurrent update would
                        # be a lost update — conflict, matching the
                        # local _commit_locked path (ADVICE r5)
                        from orientdb_tpu.models.database import (
                            ConcurrentModificationError,
                        )

                        raise ConcurrentModificationError(
                            f"{op['rid']}: stored v{cur.version} != "
                            f"base v{base}"
                        )
                    db.delete(cur)
                results.append(None)
            else:
                raise TxOpError(400, f"unknown tx op {kind!r}")
        mapping = db.commit()
        # the local tx remaps created rids in place, but a buffered
        # edge object may keep its temp rid — the mapping carries it
        for d in results:
            if d is not None and not d.rid.is_persistent:
                d.rid = mapping.get(d.rid, d.rid)
    except BaseException:
        try:
            if db.tx is t:
                t.rollback()
        except Exception:
            # the original failure is what propagates; a rollback
            # that ALSO failed must still be visible to operators
            metrics.incr("tx.rollback_error")
            log.warning("tx rollback failed during unwind",
                        exc_info=True)
        raise
    return (
        [
            {}
            if d is None
            else {"@rid": str(d.rid), "@version": d.version}
            for d in results
        ],
        {
            temp: str(doc.rid)
            for temp, doc in temp_map.items()
            if doc is not None
        },
    )


class _Staged:
    __slots__ = ("txid", "ops", "locks", "deadline")

    def __init__(self, txid, ops, locks, deadline):
        self.txid = txid
        self.ops = ops
        self.locks = locks
        self.deadline = deadline


#: decided-txid memory entries kept per registry — late/retried
#: coordinator RPCs for an already-terminated txid get a sane answer
#: ("commit" → idempotent success, "abort" → TwoPhaseError) instead of
#: being indistinguishable from never-prepared
_DECIDED_CAP = 512


class TwoPhaseRegistry:
    """Participant-side staging: one per Database, created lazily by
    :func:`get_registry`. Thread-safe and thread-AGNOSTIC — prepare and
    commit arrive on different server threads."""

    def __init__(self, db) -> None:
        self.db = db
        self._mu = threading.Lock()
        self._staged: Dict[str, _Staged] = {}
        #: txid -> "commit" | "abort", bounded FIFO (_DECIDED_CAP)
        self._decided: "OrderedDict[str, str]" = OrderedDict()
        #: txids whose phase-2 commit is EXECUTING right now (popped
        #: from _staged, not yet in _decided): a replayed commit landing
        #: in that window must answer "retry later", not "never
        #: prepared" — the resolver would record a presumed abort for a
        #: transaction that is in fact committing
        self._committing: set = set()

    def _mark_decided(self, txid: str, decision: str) -> None:
        """Caller holds self._mu (or is single-threaded recovery)."""
        self._decided[txid] = decision
        self._decided.move_to_end(txid)
        while len(self._decided) > _DECIDED_CAP:
            self._decided.popitem(last=False)

    def _log_decision(self, txid: str, decision: str) -> None:
        """Durable decision record — callers must NOT hold self._mu or
        db._lock (the append may quorum-push to the network)."""
        try:
            self.db._wal_log(
                {"op": "tx2pc_decision", "txid": txid,
                 "decision": decision}
            )
        except Exception:  # pragma: no cover - in-memory dbs, torn logs
            log.exception("2pc decision log failed for %s", txid)

    # -- lifecycle -----------------------------------------------------------

    def prepare(self, txid: str, ops: List[Dict], ttl: float = DEFAULT_TTL):
        """Validate MVCC bases and lock every written rid. Raises
        ConcurrentModificationError on a version mismatch or a live
        lock held by another in-flight distributed tx. Locks carry the
        stage's deadline so writers treat an expired lock as free even
        if no registry call ever sweeps it (presumed abort needs no
        timer thread).

        On a durable database the stage is WAL-logged
        (``tx2pc_prepare``) BEFORE the call returns: the coordinator
        only ever sees "prepared" once a restart would re-stage it.

        Idempotent for a RETRIED delivery: a coordinator whose prepare
        request landed but whose ack was lost re-sends the same txid +
        ops — that must answer "prepared" again, not error the round
        into an abort with this participant's locks stranded for the
        full TTL."""
        from orientdb_tpu.chaos import fault
        from orientdb_tpu.obs.trace import span as _span

        with _span(
            "tx2pc.participant.prepare", txid=txid, ops=len(ops)
        ), fault.point("tx2pc.prepare"):
            fresh = self._prepare_inner(txid, ops, ttl)
            if fresh:
                self.db._wal_log(
                    {"op": "tx2pc_prepare", "txid": txid, "ops": ops,
                     "ttl": ttl}
                )

    def _prepare_inner(
        self, txid: str, ops: List[Dict], ttl: float = DEFAULT_TTL
    ) -> bool:
        from orientdb_tpu.models.database import ConcurrentModificationError

        self.sweep()
        deadline = time.time() + ttl
        lock_rids = []
        for op in ops:
            if op.get("kind") in ("update", "delete") and "rid" in op:
                lock_rids.append(RID.parse(op["rid"]))
        db = self.db
        with self._mu:
            existing = self._staged.get(txid)
            if existing is not None:
                if existing.ops == ops:
                    # retried delivery (ack lost in transit): the stage
                    # from the first attempt stands — idempotent success
                    return False
                raise TwoPhaseError(f"tx {txid} already prepared here")
            # rids this batch rewrites before its creates apply: their
            # unique keys are released (or re-checked at apply), so the
            # phase-1 probe must not count them as conflicting holders
            # (delete-then-recreate of a unique key is a valid batch)
            batch_writes = {
                RID.parse(op["rid"])
                for op in ops
                if op.get("kind") in ("update", "delete") and "rid" in op
            }
            claimed: set = set()  # unique keys staged creates claim
            with db._lock:
                for op in ops:
                    kind = op.get("kind")
                    if kind in ("create", "edge"):
                        # deterministic constraint checks belong in
                        # phase 1: a schema/unique violation that only
                        # surfaced at phase-2 commit would turn a clean
                        # abort into TxInDoubtError (ADVICE r5)
                        self._validate_staged_create(
                            op, batch_writes, claimed
                        )
                        continue
                    if kind != "update":
                        continue
                    rid = RID.parse(op["rid"])
                    cur = db._load_raw(rid)
                    if cur is None:
                        raise TxOpError(
                            404, f"record {op['rid']} not found"
                        )
                    base = op.get("base_version")
                    if base is not None and cur.version != base:
                        metrics.incr("tx2pc.conflict")
                        raise ConcurrentModificationError(
                            f"{op['rid']}: stored v{cur.version} != "
                            f"base v{base}"
                        )
                locks = db._tx2pc_locks
                now = time.time()
                for rid in lock_rids:
                    held = locks.get(rid)
                    if (
                        held is not None
                        and held[0] != txid
                        and held[1] > now
                    ):
                        metrics.incr("tx2pc.conflict")
                        raise ConcurrentModificationError(
                            f"{rid} is locked by distributed tx {held[0]}"
                        )
                for rid in lock_rids:
                    locks[rid] = (txid, deadline)
            self._staged[txid] = _Staged(txid, ops, lock_rids, deadline)
        metrics.incr("tx2pc.prepare")
        return True

    def commit(
        self, txid: str, rid_map: Optional[Dict[str, str]] = None
    ) -> Tuple[List[Dict], Dict[str, str]]:
        """Execute the staged batch as one local tx; release locks.
        Raises TwoPhaseError when the txid is unknown (never prepared,
        aborted, or expired — the coordinator maps that to in-doubt).
        A commit replay for an ALREADY-COMMITTED txid (the resolver
        re-driving phase 2 after a lost ack, or after a participant
        restart replayed the decision from its WAL) answers with an
        idempotent empty success instead."""
        from orientdb_tpu.chaos import fault
        from orientdb_tpu.obs.trace import span as _span

        with _span(
            "tx2pc.participant.commit", txid=txid
        ), fault.point("tx2pc.commit"):
            return self._commit_inner(txid, rid_map)

    def _commit_inner(
        self, txid: str, rid_map: Optional[Dict[str, str]] = None
    ) -> Tuple[List[Dict], Dict[str, str]]:
        with self._mu:
            expired = self._sweep_locked()
            st = self._staged.pop(txid, None)
            replayed = (
                st is None and self._decided.get(txid) == "commit"
            )
            in_flight = st is None and txid in self._committing
            if st is not None:
                self._committing.add(txid)
        for t in expired:
            # durable presumed-abort for stages expired by THIS sweep:
            # without the decision record a restart would re-stage an
            # already-aborted tx and re-take its locks for a fresh TTL
            self._log_decision(t, "abort")
        if replayed:
            # replayed decision: already applied here — the results
            # were delivered (or superseded) on the original call
            return [], {}
        if in_flight:
            # the ORIGINAL commit is still executing (it can block up
            # to its endpoint wait): retryable, NOT terminal — a
            # TwoPhaseError here would read as presumed abort
            raise TxOpError(
                503, f"tx {txid} phase-2 commit still in flight here"
            )
        if st is None:
            raise TwoPhaseError(
                f"tx {txid} not prepared here (expired or aborted)"
            )
        db = self.db
        ops = st.ops
        if rid_map:
            substitute_rids(ops, rid_map)
        tl = db._tx_local
        tl.tx2pc_commit = txid
        try:
            # the commit's WAL `tx` entry carries txid2pc (stamped in
            # exec/tx._commit_locked from the thread-local marker), so a
            # restart classifies this txid as decided-commit
            out = execute_tx_ops(db, ops, endpoint_wait=10.0)
            with self._mu:
                self._mark_decided(txid, "commit")
        finally:
            tl.tx2pc_commit = None
            self._release(st)
            with self._mu:
                self._committing.discard(txid)
        metrics.incr("tx2pc.commit")
        return out

    def abort(self, txid: str) -> None:
        from orientdb_tpu.chaos import fault

        with fault.point("tx2pc.abort"):
            with self._mu:
                st = self._staged.pop(txid, None)
                if st is not None:
                    self._mark_decided(txid, "abort")
            if st is not None:
                from orientdb_tpu.obs.trace import span as _span

                with _span("tx2pc.participant.abort", txid=txid):
                    self._release(st)
                self._log_decision(txid, "abort")
                metrics.incr("tx2pc.abort")

    def _validate_staged_create(
        self, op: Dict, batch_writes=(), claimed=None
    ) -> None:
        """Class validation + unique-index probe for a staged create/
        edge op (caller holds db._lock). Raises ValueError /
        DuplicateKeyError so a doomed batch aborts in phase 1 with
        nothing locked or applied anywhere. ``batch_writes``: rids the
        same batch updates/deletes — excluded from the unique probe.
        ``claimed``: unique keys earlier creates in this batch claimed —
        two creates fighting over one key are invisible to the holder
        probe (neither is indexed yet) but equally deterministic."""
        from orientdb_tpu.models.indexes import DuplicateKeyError
        from orientdb_tpu.models.record import Document, Edge, Vertex
        from orientdb_tpu.storage.durability import _dec

        db = self.db
        fields = {k: _dec(v) for k, v in op.get("fields", {}).items()}
        class_name = op.get("class", "")
        cls = db.schema.get_class(class_name)
        if cls is not None:
            cls.validate(fields)
        if db._indexes is not None:
            if op.get("kind") == "edge":
                probe: Document = Edge(class_name, fields)
            elif op.get("type") == "vertex":
                probe = Vertex(class_name, fields)
            else:
                probe = Document(class_name, fields)
            db._indexes.validate_save(probe, exclude_rids=batch_writes)
            for tag in db._indexes.unique_keys_of(probe):
                if claimed is not None and tag in claimed:
                    raise DuplicateKeyError(
                        f"index '{tag[0]}': key {tag[1]!r} claimed by "
                        "two creates in one batch"
                    )
                if claimed is not None:
                    claimed.add(tag)

    # -- bookkeeping ---------------------------------------------------------

    def _release(self, st: _Staged) -> None:
        db = self.db
        with db._lock:
            for rid in st.locks:
                held = db._tx2pc_locks.get(rid)
                if held is not None and held[0] == st.txid:
                    del db._tx2pc_locks[rid]

    def sweep(self) -> None:
        """Presumed abort: drop staged batches past their deadline and
        durably record the abort decision so a later restart never
        re-stages them (the cluster's periodic probe calls this on every
        member, so an IDLE member's expired locks release too instead of
        waiting for the next registry call)."""
        with self._mu:
            expired = self._sweep_locked()
        for txid in expired:
            self._log_decision(txid, "abort")

    def staged_count(self) -> int:
        """Prepared-undecided batches currently staged (the admission
        -control pressure signal; cheaper than staged_report)."""
        with self._mu:
            return len(self._staged)

    def staged_report(self) -> List[Dict]:
        """JSON-friendly snapshot of the staged (prepared, undecided)
        batches — the observability accessor (/cluster/health counts,
        the debug bundle lists) so readers never touch the registry's
        lock or internals."""
        with self._mu:
            return [
                {
                    "txid": st.txid,
                    "ops": len(st.ops),
                    "locked_rids": [str(r) for r in st.locks],
                    "expires_in_s": round(st.deadline - time.time(), 3),
                }
                for st in self._staged.values()
            ]

    def snapshot_for_checkpoint(self) -> Dict:
        """Prepared-undecided stages + the decided-txid memory, JSON
        form — embedded in checkpoint/delta payloads
        (``storage/durability``). Without it, a checkpoint that covers
        a ``tx2pc_prepare`` WAL record ARCHIVES the segment recovery
        would have re-staged the tx from; the snapshot carries that
        state across the checkpoint boundary instead."""
        with self._mu:
            return {
                "staged": [
                    {
                        "txid": st.txid,
                        "ops": st.ops,
                        "ttl": DEFAULT_TTL,
                    }
                    for st in self._staged.values()
                ],
                "decided": dict(self._decided),
            }

    def _sweep_locked(self) -> List[str]:
        now = time.time()
        expired: List[str] = []
        for txid in [
            t for t, s in self._staged.items() if s.deadline < now
        ]:
            st = self._staged.pop(txid)
            self._release(st)
            self._mark_decided(txid, "abort")
            expired.append(txid)
            metrics.incr("tx2pc.expired")
            log.warning(
                "2pc tx %s expired after %.0fs without a coordinator "
                "decision; locks released (presumed abort)",
                txid,
                DEFAULT_TTL,
            )
        return expired


def get_registry(db) -> TwoPhaseRegistry:
    reg = getattr(db, "_tx2pc_registry", None)
    if reg is None:
        with db._lock:
            reg = getattr(db, "_tx2pc_registry", None)
            if reg is None:
                reg = db._tx2pc_registry = TwoPhaseRegistry(db)
    return reg


# -- crash recovery (participant side) --------------------------------------


def recover_from_wal(db, entries: List[Dict]) -> int:
    """Re-stage prepared-undecided 2PC transactions after a restart.

    Called by ``storage/durability.open_database`` with the recovered
    WAL entries. Classification per txid:

    - ``tx2pc_prepare`` with no later decision → RE-STAGE (locks and
      all): the coordinator saw "prepared", so the participant must
      still honor a commit arriving after the restart.
    - ``tx2pc_decision`` (abort, incl. presumed-abort sweeps) or a
      ``tx`` entry stamped ``txid2pc`` (the phase-2 commit itself) →
      decided; remembered so a late/replayed coordinator RPC gets an
      idempotent answer instead of "never prepared".

    Returns the number of re-staged transactions. A prepare whose
    revalidation fails (it should not — its locks kept every written
    rid untouched) is logged and presumed aborted, never fatal to
    recovery."""
    prepared: Dict[str, Dict] = {}
    decided: Dict[str, str] = {}
    for e in entries:
        op = e.get("op")
        if op == "tx2pc_prepare":
            prepared[e["txid"]] = e
        elif op == "tx2pc_decision":
            decided[e["txid"]] = e["decision"]
        elif op == "tx" and e.get("txid2pc"):
            decided[e["txid2pc"]] = "commit"
    if not prepared and not decided:
        return 0
    reg = get_registry(db)
    restaged = 0
    for txid, e in prepared.items():
        if txid in decided:
            continue
        try:
            # fresh TTL (the entry has no wall-clock stamp): the
            # coordinator's resolver replays the commit well within it,
            # and a vanished coordinator hits presumed abort as usual
            reg._prepare_inner(
                txid, e["ops"], float(e.get("ttl", DEFAULT_TTL))
            )
            restaged += 1
            metrics.incr("tx2pc.restaged")
            log.warning(
                "2pc recovery: re-staged prepared tx %s (%d ops)",
                txid,
                len(e["ops"]),
            )
        except Exception:
            log.exception(
                "2pc recovery: could not re-stage %s; presumed abort",
                txid,
            )
    with reg._mu:
        for txid, d in decided.items():
            reg._mark_decided(txid, d)
    return restaged


# -- coordinator-side in-doubt resolution ------------------------------------


class IndoubtResolver:
    """Terminates every coordinator-side :class:`TxInDoubtError` with no
    human in the loop. ``run_coordinator`` registers the participants
    whose phase-2 commit failed AFTER the decision; :meth:`resolve_once`
    (driven from the cluster's periodic probe,
    ``parallel/cluster.Cluster.probe_once``) replays the recorded commit
    at each with capped exponential backoff until one of:

    - the replay succeeds (the participant applied late, or had already
      applied and answers idempotently — durable participants re-stage
      prepared txs on restart, so a crash-restarted member lands here);
    - the participant answers "unknown txid" (HTTP 410 /
      :class:`TwoPhaseError`): its stage expired — presumed abort, the
      terminal answer of the protocol.

    Outcomes are written into the original in-doubt ``report`` (the one
    carried by the raised error and logged to :data:`INDOUBT_LOG`), so
    the debug bundle shows resolution next to the failure."""

    #: backoff bounds between replay rounds per transaction
    BASE_BACKOFF = 0.25
    MAX_BACKOFF = 5.0
    #: replay rounds before giving up on a participant that never
    #: answers (~20 min at MAX_BACKOFF): the outcome is recorded as
    #: ``unreachable_gave_up`` — the stage's TTL has long presumed
    #: abort by then, so further replays could never change anything
    MAX_ATTEMPTS = 240

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._pending: Dict[str, Dict] = {}

    def register(
        self,
        txid: str,
        failed_parts: Dict[object, "Participant"],
        rid_map: Dict[str, str],
        report: Dict,
    ) -> None:
        with self._mu:
            self._pending[txid] = {
                "txid": txid,
                "parts": dict(failed_parts),
                "rid_map": dict(rid_map),
                "report": report,
                "attempts": 0,
                "next_try": 0.0,
                "backoff": self.BASE_BACKOFF,
            }
            metrics.gauge("tx2pc.indoubt_pending", len(self._pending))

    def pending(self) -> List[Dict]:
        """JSON-friendly snapshot for /cluster/health and the bundle."""
        with self._mu:
            return [
                {
                    "txid": r["txid"],
                    "attempts": r["attempts"],
                    "participants": [str(k) for k in r["parts"]],
                }
                for r in self._pending.values()
            ]

    def resolve_once(self) -> int:
        """One resolution round over due transactions; returns how many
        became fully resolved."""
        now = time.time()
        with self._mu:
            work = [
                r for r in self._pending.values() if r["next_try"] <= now
            ]
        resolved = 0
        for rec in work:
            txid = rec["txid"]
            outcomes = rec["report"].setdefault("resolution", {})
            done: List[object] = []
            for key, part in list(rec["parts"].items()):
                try:
                    part.commit(txid, dict(rec["rid_map"]))
                    outcomes[str(key)] = "commit_replayed"
                    done.append(key)
                except TwoPhaseError:
                    outcomes[str(key)] = "presumed_abort"
                    done.append(key)
                except Exception as e:
                    if getattr(e, "code", None) == 410:
                        # the wire form of TwoPhaseError (http 410)
                        outcomes[str(key)] = "presumed_abort"
                        done.append(key)
                    else:
                        log.warning(
                            "indoubt %s: %s still unresolved: %r",
                            txid,
                            key,
                            e,
                        )
            with self._mu:
                live = self._pending.get(txid)
                if live is None:
                    continue
                for k in done:
                    live["parts"].pop(k, None)
                if (
                    live["parts"]
                    and live["attempts"] + 1 >= self.MAX_ATTEMPTS
                ):
                    for k in list(live["parts"]):
                        outcomes[str(k)] = "unreachable_gave_up"
                    live["parts"].clear()
                    metrics.incr("tx2pc.indoubt_gave_up")
                if not live["parts"]:
                    del self._pending[txid]
                    resolved += 1
                    metrics.incr("tx2pc.indoubt_resolved")
                    log.warning(
                        "indoubt tx %s resolved: %s", txid, outcomes
                    )
                else:
                    live["attempts"] += 1
                    live["backoff"] = min(
                        live["backoff"] * 2, self.MAX_BACKOFF
                    )
                    live["next_try"] = time.time() + live["backoff"]
                metrics.gauge(
                    "tx2pc.indoubt_pending", len(self._pending)
                )
        return resolved


#: the process-wide resolver (every coordinator in this process
#: registers here; Cluster.probe_once drives it)
resolver = IndoubtResolver()


# -- coordinator ------------------------------------------------------------


class Participant:
    """One coordinated party: ``prepare``/``commit``/``abort`` keyed by
    the coordinator's txid. ``commit`` receives (and extends) the
    accumulated temp→real rid map.

    ``replayable`` marks commits the :class:`IndoubtResolver` may
    safely re-drive: registry-backed participants answer a replayed
    commit idempotently (the ``_decided`` guard). The coordinator's own
    buffered-tx flavor (``exec/tx._LocalTx``) is NOT — re-running its
    commit would re-apply already-applied ops — so it keeps the False
    default and is never registered for replay."""

    replayable = False

    def prepare(self, txid: str) -> None:  # pragma: no cover - protocol
        raise NotImplementedError

    def commit(self, txid: str, rid_map: Dict[str, str]) -> None:
        raise NotImplementedError  # pragma: no cover - protocol

    def abort(self, txid: str) -> None:  # pragma: no cover - protocol
        raise NotImplementedError


class RemoteParticipant(Participant):
    """A WriteOwner reached over the wire (``POST /tx2pc``)."""

    replayable = True

    def __init__(self, owner, ops: List[Dict], adopt) -> None:
        self.owner = owner
        self.ops = ops
        self.adopt = adopt  # (ops, results) -> None

    def prepare(self, txid: str) -> None:
        self.owner.tx2pc("prepare", txid, ops=self.ops)

    def commit(self, txid: str, rid_map: Dict[str, str]) -> None:
        resp = self.owner.tx2pc("commit", txid, rid_map=rid_map)
        self.adopt(self.ops, resp["results"])
        for op, res in zip(self.ops, resp["results"]):
            if "temp" in op and res:
                rid_map[op["temp"]] = res["@rid"]

    def abort(self, txid: str) -> None:
        self.owner.tx2pc("abort", txid)


class LocalRegistryParticipant(Participant):
    """The coordinator's own database as a participant, driven through
    the same registry/lock machinery a remote owner uses."""

    replayable = True

    def __init__(self, db, ops: List[Dict], adopt) -> None:
        self.db = db
        self.ops = ops
        self.adopt = adopt

    def prepare(self, txid: str) -> None:
        get_registry(self.db).prepare(txid, self.ops)

    def commit(self, txid: str, rid_map: Dict[str, str]) -> None:
        results, temp_map = get_registry(self.db).commit(
            txid, rid_map=rid_map
        )
        self.adopt(self.ops, results)
        rid_map.update(temp_map)

    def abort(self, txid: str) -> None:
        get_registry(self.db).abort(txid)


def _abort_best_effort(p: Participant, txid: str) -> None:
    """Best-effort phase-2/unwind abort: the coordinator's own outcome
    never depends on it, but a failed abort leaves the participant
    staged (locks held) until TTL expiry — count and log it so piled-up
    stages have a trail instead of a silent ``pass``."""
    try:
        p.abort(txid)
    except Exception:
        metrics.incr("tx2pc.abort_error")
        log.warning("best-effort abort of %s failed", txid,
                    exc_info=True)


def run_coordinator(
    txid: str,
    parts: Dict[object, Participant],
    rows: List[Tuple[object, set, set]],
    coord_db=None,
) -> Dict[str, str]:
    """Drive one 2PC round over ``parts`` (key → participant; ``rows``
    as for :func:`order_participants`). Phase 1 prepares everyone —
    any failure aborts every prepared participant and re-raises (clean
    abort, nothing applied). Phase 2 commits in temp-reference
    dependency order, threading the accumulated rid map; a failure
    BEFORE any commit is still a clean abort, a failure after one is
    in-doubt (TxInDoubtError) but the remaining decided commits still
    run — EXCEPT participants whose ops transitively depend on a failed
    participant's unresolved temp rids: their edge endpoints can never
    arrive, so instead of spinning ``_load_with_wait`` for the full
    endpoint-wait per dangling endpoint (ADVICE r5) they are skipped,
    aborted (locks released now, not at TTL expiry), and recorded as
    not-applied in the in-doubt report. Returns the final temp→real
    rid map.

    ``coord_db``, when given (both tx paths pass their database), gets
    a durable ``tx2pc_coord`` decision record before phase 2 and a
    ``tx2pc_coord_done`` after — so an interrupted round is visible in
    the coordinator's own log. Phase-2 failures AFTER the decision are
    handed to :data:`resolver`, which terminates them from the cluster
    probe (replayed commit or presumed abort) — no human in the loop.

    The whole round runs under a ``tx2pc.coordinate`` span with the
    txid as baggage, so every participant's prepare/commit span — local
    or across the wire — assembles into ONE trace keyed by the txid."""
    import time as _time

    from orientdb_tpu.chaos import fault
    from orientdb_tpu.obs.propagation import baggage
    from orientdb_tpu.obs.trace import span

    order = order_participants(rows)
    creates_of = {key: set(creates) for key, creates, _refs in rows}
    refs_of = {key: set(refs) for key, _creates, refs in rows}
    with span(
        "tx2pc.coordinate", txid=txid, participants=len(parts)
    ) as coord_sp, baggage(txid=txid):
        prepared: List[Participant] = []
        try:
            for p in parts.values():
                p.prepare(txid)
                prepared.append(p)
        except Exception:
            for p in prepared:
                _abort_best_effort(p, txid)
            raise
        # the decision point: every participant is prepared — a crash
        # here (fault "tx2pc.decide") is the canonical coordinator death
        # between phases, leaving participants staged until presumed
        # abort / the probe-driven sweep terminates them
        with fault.point("tx2pc.decide"):
            _log_coord(coord_db, txid, parts)
        rid_map: Dict[str, str] = {}
        committed: List[object] = []
        failures: List[str] = []
        failed_keys: List[object] = []
        skipped: List[object] = []
        unresolved: set = set()  # temps a failed/skipped owner never mapped
        pending = list(order)
        while pending:
            key = pending.pop(0)
            if unresolved & refs_of.get(key, set()):
                # this participant's edge ops reference temps whose
                # creator failed: they will never resolve — skip and
                # release its staged locks immediately
                unresolved |= creates_of.get(key, set())
                skipped.append(key)
                _abort_best_effort(parts[key], txid)
                continue
            try:
                parts[key].commit(txid, rid_map)
                committed.append(key)
            except Exception as e:
                if not committed:
                    # nothing applied anywhere yet: clean abort —
                    # including the participant whose commit call failed
                    # (abort of an already-resolved stage is a no-op;
                    # leaving it staged would hold its locks until TTL
                    # expiry)
                    for k2 in [key] + pending:
                        _abort_best_effort(parts[k2], txid)
                    raise
                failures.append(f"{key}: {type(e).__name__}: {e}")
                failed_keys.append(key)
                unresolved |= {
                    t
                    for t in creates_of.get(key, ())
                    if t not in rid_map
                }
        if failures:
            metrics.incr("tx2pc.indoubt")
            report = {
                "txid": txid,
                "ts": round(_time.time(), 3),
                "trace_id": coord_sp.trace_id,
                "committed": [str(k) for k in committed],
                "failed": failures,
                "skipped": [str(k) for k in skipped],
                "unresolved_temps": sorted(unresolved),
            }
            INDOUBT_LOG.append(report)
            _log_coord_done(coord_db, txid, "indoubt")
            # hand the failed (decided-commit, not applied) participants
            # to the resolver: it replays the commit until it lands or
            # the participant answers presumed-abort. Only REPLAYABLE
            # flavors register — re-driving the coordinator's own
            # buffered-tx commit (exec/tx._LocalTx, which can fail
            # AFTER applying, e.g. a QuorumError on the deferred push)
            # would double-apply; its failure stays in the report.
            replay = {
                k: parts[k]
                for k in failed_keys
                if getattr(parts[k], "replayable", False)
            }
            not_replayable = [
                str(k) for k in failed_keys if k not in replay
            ]
            if not_replayable:
                report.setdefault("resolution", {}).update(
                    {k: "not_replayable" for k in not_replayable}
                )
            if replay:
                resolver.register(txid, replay, rid_map, report)
            msg = "distributed tx partially applied: " + "; ".join(
                failures
            )
            if skipped:
                msg += "; skipped (dependent, not applied): " + ", ".join(
                    str(k) for k in skipped
                )
            raise TxInDoubtError(msg, report)
        _log_coord_done(coord_db, txid, "committed")
        metrics.incr("tx2pc.coordinated")
        return rid_map


def _log_coord(coord_db, txid: str, parts: Dict) -> None:
    """Durable coordinator decision record ('every participant
    prepared; committing'). Best effort — an in-memory coordinator
    (no WAL) simply has no record."""
    if coord_db is None:
        return
    try:
        coord_db._wal_log(
            {
                "op": "tx2pc_coord",
                "txid": txid,
                "participants": [str(k) for k in parts],
            }
        )
    except Exception:  # pragma: no cover - best effort
        log.exception("2pc coordinator record failed for %s", txid)


def _log_coord_done(coord_db, txid: str, outcome: str) -> None:
    if coord_db is None:
        return
    try:
        coord_db._wal_log(
            {"op": "tx2pc_coord_done", "txid": txid, "outcome": outcome}
        )
    except Exception:  # pragma: no cover - best effort
        log.exception("2pc coordinator done-record failed for %s", txid)


def order_participants(
    batches: List[Tuple[object, set, set]]
) -> List[object]:
    """Topologically order participants so that a participant creating
    a temp rid commits BEFORE any participant whose ops reference it.
    ``batches`` rows are ``(key, creates_temps, refs_temps)``. Raises
    TwoPhaseError on a reference cycle (split the transaction)."""
    owner_of = {}
    for key, creates, _refs in batches:
        for t in creates:
            owner_of[t] = key
    deps: Dict[object, set] = {key: set() for key, _c, _r in batches}
    for key, _creates, refs in batches:
        for t in refs:
            src = owner_of.get(t)
            if src is not None and src != key:
                deps[key].add(src)
    out: List[object] = []
    ready = [k for k, d in deps.items() if not d]
    while ready:
        k = ready.pop()
        out.append(k)
        for k2, d in deps.items():
            if k in d:
                d.discard(k)
                if not d and k2 not in out and k2 not in ready:
                    ready.append(k2)
    if len(out) != len(deps):
        raise TwoPhaseError(
            "cyclic cross-owner temp references in distributed tx; "
            "split the transaction"
        )
    return out


def batch_temp_sets(ops: List[Dict]) -> Tuple[set, set]:
    """(creates_temps, refs_temps) for a JSON op batch."""
    creates = {op["temp"] for op in ops if "temp" in op}
    refs = set()
    for op in ops:
        if op.get("kind") == "edge":
            for end in (op["from"], op["to"]):
                if _is_temp(end) and end not in creates:
                    refs.add(end)
    return creates, refs
