"""Distributed execution: device meshes, sharded CSR, collective frontier
expansion (SURVEY.md §2 "Parallelism strategies" and §5.7/5.8)."""
