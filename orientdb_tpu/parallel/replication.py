"""Asynchronous primary→replica replication by WAL shipping.

Analog of the reference's distributed module ([E] distributed/
``OHazelcastPlugin``/``ODistributedServerManager``/``ODistributedDatabaseImpl``
with task-based op shipping and delta-sync; SURVEY.md §2 "Distributed",
§5.3/§5.8). Redesign: the durable host store already emits a logical,
LSN-ordered WAL (storage/durability.py) whose entries are exactly the
reference's "tasks" — replication is therefore WAL *shipping*:

- a **source** database arms a WAL (a throwaway one if not already
  durable) so every committed op has an LSN;
- the HTTP server exposes ``/replication/<db>/<from_lsn>`` (admin-only)
  returning the entries after that LSN — the [E] delta-sync path; a
  fresh replica starting from LSN 0 gets the full stream (full-sync);
- a **ReplicaPuller** thread on the replica side pulls, applies entries
  through the recovery machinery (``_apply_entry``), and tracks lag.
  Pulls double as heartbeats: consecutive failures mark the source DOWN
  ([E] the Hazelcast membership view collapsing to a node-status
  machine) and fire ``on_source_down`` — the operator's cue to promote
  (``promote()`` stops pulling; the replica is then an ordinary writable
  database).

Scope note: the reference is multi-master with write quorums; this v1
is single-writer primary→N async replicas (read scaling — the DP row of
SURVEY.md §2's parallelism table). Quorum-acked multi-master is the
documented delta.
"""

from __future__ import annotations

import base64
import json
import tempfile
import threading
import urllib.request
from typing import Callable, Dict, List, Optional

from orientdb_tpu.chaos import fault
from orientdb_tpu.models.database import Database
from orientdb_tpu.parallel.resilience import breaker
from orientdb_tpu.storage.durability import WriteAheadLog, _apply_entry
from orientdb_tpu.utils.logging import get_logger
from orientdb_tpu.utils.metrics import metrics

log = get_logger("replication")


class ReplicationGap(Exception):
    """The source can no longer serve the replica's next LSN and the
    replica is not fresh — data would be silently missing; resync from a
    fresh database instead."""


class QuorumError(Exception):
    """A synchronous write could not reach a majority of the cluster.
    The entry IS in the primary's local WAL (in-doubt): if the primary
    survives, pullers eventually replicate it; if the primary dies first,
    the conflict-safe election may discard it — exactly the ambiguity the
    error reports to the writer ([E] the reference's distributed tx
    surfaces ODistributedOperationException the same way)."""


def _replica_is_fresh(db: Database, floor: int) -> bool:
    """True when the replica database has never applied anything — the
    only state a full-sync checkpoint restore is safe to land on."""
    return (
        db.mutation_epoch == 0
        and floor == 0
        and len(db.schema.classes()) == 2  # just the V/E roots
    )


def apply_pushed_entries(
    db: Database,
    entries: List[Dict],
    term: Optional[int] = None,
    checkpoint: Optional[Dict] = None,
) -> int:
    """Replica-side apply for quorum-pushed entries; returns the applied
    LSN floor AFTER the batch.

    Shares the db-level apply lock and LSN floor with `ReplicaPuller`, so
    pushes and background delta pulls coexist without double-applies.
    CONTIGUITY is enforced: an entry past floor+1 is refused (applying it
    would hide a gap under the dedup floor), leaving catch-up to the
    puller — so a positive ack always means "this replica holds the full
    prefix through this LSN", the property quorum counting relies on.
    ``term`` fences stale primaries: pushes carrying a term below the
    replica's current one are refused outright (a partitioned
    predecessor keeps "succeeding" locally but can never ack here).

    ``checkpoint`` is the push-side full-sync path ([E] the reference's
    full database sync shipped as a distributed task): when the primary's
    delta range below the pushed entry is gone (late-armed source), a
    FRESH replica restores the checkpoint — so a quorum push can bring a
    still-empty replica fully up to date synchronously instead of
    waiting a pull interval. A non-fresh replica refuses it (restoring
    over applied state would lose writes) and stays puller territory."""
    from orientdb_tpu.obs.trace import span

    with span(
        "replication.apply", source="push", entries=len(entries)
    ), db._repl_lock:
        if term is not None:
            cur = getattr(db, "_repl_term", 0)
            if term < cur:
                return -1  # fenced: never an ack
            db._repl_term = term
        floor = getattr(db, "_repl_applied_lsn", 0)
        if checkpoint is not None and _replica_is_fresh(db, floor):
            from orientdb_tpu.storage.durability import restore_payload

            restore_payload(db, checkpoint)
            floor = checkpoint.get("lsn", 0)
            db._repl_applied_lsn = floor
            # lineage marker: drives the puller's exact=1 pull param —
            # the source then serves deltas from this LSN instead of
            # re-offering the base checkpoint (restore_payload is
            # additive, so restoring twice is never safe)
            db._repl_restored_ckpt_lsn = floor
            metrics.incr("replication.full_sync")
        from orientdb_tpu.cdc.feed import apply_scope, notify_applied
        from orientdb_tpu.obs.propagation import continue_trace

        for e in entries:
            lsn = e["lsn"]
            if lsn <= floor:
                continue  # already here via an earlier push or a pull
            if lsn > floor + 1:
                break  # gap: refuse; the puller will close it
            # the entry carries the ORIGINATING write's trace context
            # (stamped at WAL append): force-adopt it so the apply span
            # joins that write's trace, not this batch's
            with continue_trace(
                "replication.apply_entry",
                e.get("trace"),
                force=True,
                lsn=lsn,
                source="push",
            ), apply_scope(db):
                _apply_entry(db, e)
            # changefeed tap: a replica's subscribers see the entry with
            # its SOURCE lsn (apply_scope muted the local-write taps the
            # apply may have fired, e.g. a delete's cascade)
            notify_applied(db, e)
            floor = lsn
            db._repl_applied_lsn = floor
    return floor


class QuorumPusher:
    """Primary-side synchronous WAL shipping: every appended entry is
    pushed to all replicas in parallel and the write blocks until a
    MAJORITY of the cluster (counting the primary) holds it — the [E]
    writeQuorum:"majority" ack discipline over this package's WAL
    transport instead of Hazelcast tasks. Single-writer: there is no
    cross-primary conflict resolution to do, so "2-phase" reduces to
    (1) durable append + parallel ship, (2) ack after majority — a tx's
    buffered ops ship as ONE atomic `tx` entry, making multi-op commits
    all-or-nothing on every member ([E] the distributed tx task batch).
    """

    def __init__(
        self,
        dbname: str,
        targets,
        cluster_size,
        user: str = "admin",
        password: str = "admin",
        timeout: float = 2.0,
        term: int = 1,
        source_db: Optional[Database] = None,
    ) -> None:
        self.dbname = dbname
        #: fencing term: bumped by every failover, checked by replicas
        self.term = term
        #: primary database, for gap backfill from its WAL
        self.source_db = source_db
        #: callable -> [(member_name, base_url)] of current replicas
        self.targets = targets
        #: callable -> total member count (majority denominator; DOWN
        #: members still count — a 2-of-3 cluster needs 2 acks, not 1)
        self.cluster_size = cluster_size
        self.user = user
        self.password = password
        self.timeout = timeout
        #: True after a replicate() failed to reach majority, False
        #: after one succeeds — the read-only-degradation latch (writes
        #: shed with 503 + Retry-After while quorum is lost, instead of
        #: each paying the full quorum timeout)
        self.quorum_lost = False
        self._lost_at = 0.0
        from concurrent.futures import ThreadPoolExecutor

        self._pool = ThreadPoolExecutor(max_workers=8)
        #: seconds the write path stays shed after a quorum failure
        #: before a probe write is admitted again (half-open — see
        #: writes_degraded)
        self.degraded_retry_s = max(timeout, 1.0)
        #: url -> monotonic time of the last REFUSED checkpoint ship:
        #: a non-fresh replica refuses restores, so don't serialize and
        #: ship a full database at it on every subsequent write
        self._ckpt_refused: Dict[str, float] = {}
        from orientdb_tpu.parallel.resilience import RetryPolicy

        #: per-entry push retry: a transient channel blip must not cost
        #: the writer its quorum ack. Budgeted inside the quorum
        #: timeout so replicate()'s deadline still bounds the write
        self._push_retry = RetryPolicy(
            attempts=3, base_s=0.05, cap_s=0.5, budget_s=timeout
        )

    def _post(self, url: str, entries: List[Dict], **extra) -> int:
        from orientdb_tpu.obs.propagation import inject_headers

        cred = base64.b64encode(
            f"{self.user}:{self.password}".encode()
        ).decode()
        body = json.dumps(
            {"entries": entries, "term": self.term, **extra}
        ).encode()
        # each entry carries the ORIGINATING write's trace context
        # (stamped at WAL append) — this pool thread has no span stack
        # of its own, so the request headers borrow the first entry's
        # stamp to keep the push visible in the writer's trace
        ctx = next(
            (e.get("trace") for e in entries if e.get("trace")), None
        )
        req = urllib.request.Request(
            f"{url}/replication/{self.dbname}/apply",
            data=body,
            headers=inject_headers(
                {
                    "Authorization": f"Basic {cred}",
                    "Content-Type": "application/json",
                },
                ctx=ctx,
            ),
        )

        def _send():
            # fault point inside the breaker: injected drops/errors are
            # channel failures and count toward tripping it
            with fault.point("repl.push"):
                with urllib.request.urlopen(
                    req, timeout=self.timeout
                ) as r:
                    return json.loads(r.read()).get("applied_lsn", 0)

        import urllib.error as _uerr

        # per-replica fuse: a dead member costs ONE timeout per reset
        # window instead of one per write; quorum counting treats the
        # fast-fail exactly like any other missing ack
        return breaker(f"repl:{url}").call(
            _send, success_on=(_uerr.HTTPError,)
        )

    def _push_one(self, url: str, entry: Dict) -> bool:
        import urllib.error as _uerr

        from orientdb_tpu.parallel.resilience import (
            CircuitOpenError,
            RetryBudgetExceeded,
        )

        lsn = entry["lsn"]
        try:
            # channel failures retry under the policy; an HTTP error is
            # the replica ANSWERING (no retry), and an open breaker
            # fast-fails by design
            floor = self._push_retry.call(
                self._post,
                url,
                [entry],
                retry_on=(OSError,),
                give_up_on=(
                    _uerr.HTTPError,
                    CircuitOpenError,
                ),
            )
        except RetryBudgetExceeded as e:
            raise (
                e.__cause__
                if isinstance(e.__cause__, Exception)
                else e
            )
        if floor >= lsn:
            return True
        if floor < 0 or self.source_db is None:
            return False  # fenced, or no backfill source
        # the replica is mid-catch-up (its puller hasn't closed the gap
        # below this entry yet): backfill the missing range from the
        # primary's WAL and retry once — steady-state pushes then ack
        # without waiting a pull interval. A checkpoint (full sync) is
        # offered only to a replica that could restore it — floor == 0
        # and not recently refusing — decided BEFORE entries_after so the
        # O(database) checkpoint serialization under the primary's
        # db._lock never runs just to be discarded.
        import time as _time

        t = self._ckpt_refused.get(url)
        want_ckpt = floor == 0 and (
            t is None or _time.monotonic() - t >= 2.0
        )
        payload = entries_after(self.source_db, floor, checkpoint_ok=want_ckpt)
        if payload.get("checkpoint_needed"):
            return False  # replica can't take a checkpoint: puller territory
        if "checkpoint" in payload:
            # delta range gone (late-armed source): ship it — a FRESH
            # replica restores synchronously and the push acks without
            # waiting a pull interval; a refusal starts the cool-down
            ok = (
                self._post(url, [], checkpoint=payload["checkpoint"])
                >= lsn
            )
            if ok:
                self._ckpt_refused.pop(url, None)
            else:
                self._ckpt_refused[url] = _time.monotonic()
            return ok
        fill = [e for e in payload.get("entries", ()) if e["lsn"] <= lsn]
        if not fill:
            return False  # range gone: puller territory
        return self._post(url, fill) >= lsn

    def replicate(self, entry: Dict) -> int:
        """Block until a majority holds `entry`; returns the ack count
        (including the primary) or raises QuorumError."""
        targets = list(self.targets())
        total = max(int(self.cluster_size()), 1 + len(targets))
        need = total // 2 + 1  # majority, counting the primary's copy
        acks = 1
        if acks >= need:
            return acks
        futs = [
            self._pool.submit(self._push_one, url, entry)
            for _name, url in targets
        ]
        from concurrent.futures import FIRST_COMPLETED, wait

        pending = set(futs)
        deadline = self.timeout + 0.5
        import time as _time

        t_end = _time.monotonic() + deadline
        while pending and acks < need:
            done, pending = wait(
                pending,
                timeout=max(0.0, t_end - _time.monotonic()),
                return_when=FIRST_COMPLETED,
            )
            if not done and _time.monotonic() >= t_end:
                break
            for f in done:
                try:
                    if f.result():
                        acks += 1
                except Exception:
                    # dead/slow replica: no ack, never a blocker —
                    # but the dropped ack must show up in a signal
                    metrics.incr("replication.ack_error")
        if acks < need:
            metrics.incr("replication.quorum_failed")
            self.quorum_lost = True
            self._lost_at = _time.monotonic()
            metrics.gauge("replication.quorum_lost", 1)
            raise QuorumError(
                f"write lsn={entry.get('lsn')} reached {acks}/{need} "
                f"(cluster of {total})"
            )
        if self.quorum_lost:
            self.quorum_lost = False
            metrics.gauge("replication.quorum_lost", 0)
        metrics.incr("replication.quorum_acked")
        return acks

    def writes_degraded(self) -> bool:
        """The admission-control check (server/admission): shed writes
        only WITHIN the retry window after a quorum failure. Once it
        elapses, the next write is admitted as a half-open probe — its
        replicate() either clears the latch (majority back) or renews
        the window. Shedding on the raw latch forever would leave an
        HTTP/binary-only cluster read-only after the replicas
        recovered: no admitted write, nothing to ever clear it."""
        import time as _time

        return self.quorum_lost and (
            _time.monotonic() - self._lost_at < self.degraded_retry_s
        )

    def close(self) -> None:
        self._pool.shutdown(wait=False)


def enable_replication_source(db: Database) -> None:
    """Arm a WAL so the database's committed ops are shippable. Durable
    databases already have one; in-memory sources get a throwaway log."""
    if db._wal is None:
        d = tempfile.mkdtemp(prefix=f"repl-{db.name}-")
        from orientdb_tpu.storage.durability import enable_durability

        enable_durability(db, d, fsync=False)


def entries_after(
    db: Database,
    from_lsn: int,
    limit: int = 10_000,
    exact_ok: bool = False,
    checkpoint_ok: bool = True,
) -> Dict:
    """The shipping payload: WAL entries with lsn > from_lsn.

    When the requested range is no longer available — the source was
    armed after data already existed, or checkpoints pruned the covering
    archives — the response carries a full CHECKPOINT payload instead
    (the [E] full-sync path): the replica restores it and resumes delta
    pulls from its LSN. Archived segments whose name-encoded max LSN is
    ≤ from_lsn are skipped without parsing.

    ``exact_ok=True`` is the replica's assertion that it holds the
    source's state as of ``from_lsn`` EXACTLY (it restored this source's
    checkpoint at that LSN) — so the non-empty-base marker must not
    force a second checkpoint; deltas continue from there."""
    if db._wal is None:
        return {"entries": [], "lsn": 0}
    import os

    from orientdb_tpu.storage.durability import _wal_segments

    directory = getattr(db, "_durability_dir", None)
    entries: List[Dict] = []
    if directory and os.path.isdir(directory):
        for seg in _wal_segments(directory):
            base = os.path.basename(seg)
            if base.startswith("wal-") and base.endswith(".log"):
                try:
                    if int(base[4:-4]) <= from_lsn:
                        continue  # fully below the requested range
                except ValueError:
                    pass
            entries.extend(WriteAheadLog(seg).read_entries())
        entries.sort(key=lambda e: e["lsn"])
    else:
        entries = db._wal.read_entries()
    # gap detection: (a) a late-armed source holds data its log never saw
    # (the base marker), (b) archives pruned past the requested range
    base_lsn = getattr(db, "_wal_base_lsn", 0)
    # `_wal_base_exact_ok` (set by cluster promotion) means "state as of
    # base_lsn" — a replica AT that LSN already holds it and can continue
    # by delta; the late-armed-source marker (exact_ok unset) means the
    # LSN-0 state is non-empty, so even a from_lsn==base replica needs
    # the checkpoint
    needs_base = getattr(db, "_wal_has_base", False) and (
        from_lsn < base_lsn
        or (
            from_lsn == base_lsn
            and not exact_ok
            and not getattr(db, "_wal_base_exact_ok", False)
        )
    )
    available_from = entries[0]["lsn"] if entries else db._wal.next_lsn
    if needs_base or from_lsn + 1 < available_from:
        if not checkpoint_ok:
            # the caller would discard a checkpoint (push backfill to a
            # replica that can't restore one): answer WITHOUT paying the
            # O(database) serialization under db._lock
            return {"entries": [], "lsn": from_lsn, "checkpoint_needed": True}
        from orientdb_tpu.storage.durability import _checkpoint_payload

        with db._lock:
            upto = db._wal.next_lsn - 1
            payload = _checkpoint_payload(db)
        payload["lsn"] = upto
        return {"checkpoint": payload, "entries": [], "lsn": upto}
    out = [e for e in entries if e["lsn"] > from_lsn][:limit]
    last = out[-1]["lsn"] if out else from_lsn
    # head_lsn is the SOURCE's true tail, past this limit window — the
    # replica's lag gauge needs it (lsn alone reads as "caught up" the
    # moment the replica applies a truncated window)
    head = entries[-1]["lsn"] if entries else last
    return {"entries": out, "lsn": last, "head_lsn": head}


class ReplicaPuller:
    """Replica-side puller: applies the source's WAL stream to a local
    database and watches source liveness."""

    def __init__(
        self,
        source_url: str,
        dbname: str,
        local_db: Database,
        user: str = "admin",
        password: str = "admin",
        interval: float = 0.25,
        down_after: int = 4,
        on_source_down: Optional[Callable[[], None]] = None,
        stream: Optional[str] = None,
    ) -> None:
        self.source_url = source_url.rstrip("/")
        self.dbname = dbname
        self.db = local_db
        self.user = user
        self.password = password
        self.interval = interval
        self.down_after = down_after
        self.on_source_down = on_source_down
        #: multi-owner mode ([E] per-cluster owner lists): a NAMED stream
        #: pulls a secondary owner's WAL — its floor lives in the db's
        #: per-stream dict (not the primary floor), and applies suppress
        #: local WAL logging (the entries belong to the OTHER owner's
        #: stream; re-logging would interleave and double-ship them)
        self.stream = stream
        self.applied_lsn = 0
        self.failures = 0
        self.status = "STARTING"  # STARTING | ONLINE | DOWN | PROMOTED
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "ReplicaPuller":
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        return self

    def request_stop(self) -> None:
        """Signal the pull loop to exit without joining it — for callers
        holding locks the loop itself may be blocked on (cluster failover
        runs on a puller thread while sibling pullers wait to report)."""
        self._stop.set()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        # failover runs on a puller thread (on_source_down → promote/
        # repoint), so stop() must not join the thread it's running on
        if t is not None and t is not threading.current_thread():
            t.join(timeout=5)

    def promote(self) -> Database:
        """Stop replicating; the local database becomes the writable
        primary ([E] the reassign-cluster-ownership step of failover)."""
        self.stop()
        self.status = "PROMOTED"
        return self.db

    # -- pulling ------------------------------------------------------------

    def pull_once(self) -> int:
        """One delta pull; returns the number of applied entries."""
        # sync the cursor with the db-level floor first: a quorum push
        # (possibly a push-side full sync) may have advanced the database
        # past this puller's last pull — requesting from the stale cursor
        # would refetch the range, or worse demand a second checkpoint a
        # no-longer-fresh replica must refuse (ReplicationGap). The sync
        # stays a LOCAL until the apply lock is held below: rebinding
        # applied_lsn here raced the request_stop apply barrier (a
        # signal-stopped puller could bump the cursor AFTER the election
        # sampled it).
        cursor = max(self.applied_lsn, self._db_floor())
        cred = base64.b64encode(
            f"{self.user}:{self.password}".encode()
        ).decode()
        # exact=1: we restored this source's checkpoint, so our cursor
        # LSN denotes exactly-held state — the source must serve deltas,
        # never a second base checkpoint
        exact = (
            "?exact=1"
            if getattr(self.db, "_repl_restored_ckpt_lsn", None) is not None
            else ""
        )
        req = urllib.request.Request(
            f"{self.source_url}/replication/{self.dbname}/"
            f"{cursor}{exact}",
            headers={"Authorization": f"Basic {cred}"},
        )
        # fault point only, no breaker: the pull loop IS the failure
        # detector (down_after consecutive failures mark the source
        # DOWN) — a breaker here would starve it of real probes
        with fault.point("repl.pull"):
            with urllib.request.urlopen(req, timeout=5) as r:
                payload = json.loads(r.read())
        applied = 0
        # the duplicate guard lives on the DATABASE, not the puller: during
        # failover a signal-stopped predecessor puller (not joinable — the
        # stopper may hold a lock its loop is blocked on) can race its last
        # in-flight pull against the replacement puller on the same db, and
        # per-puller applied_lsn alone would double-apply the overlap
        from orientdb_tpu.obs.trace import span

        with span(
            "replication.apply",
            source="pull",
            entries=len(payload.get("entries", ())),
        ), self._lock, self.db._repl_lock:
            if self._stop.is_set():
                # request_stop is an apply BARRIER: once the stopper has
                # acquired this db's apply lock after setting the flag, no
                # further entries can land from this puller — the cluster
                # election relies on that to sample a settled applied LSN
                return 0
            # adopt the pre-fetch cursor sync now that the apply lock
            # serializes it against the stop barrier
            self.applied_lsn = max(self.applied_lsn, cursor)
            if "checkpoint" in payload and self.stream is not None:
                # a NAMED stream consumer already holds the base state
                # (it arrived via the primary stream): restoring the
                # secondary owner's full checkpoint would wipe this
                # member — the secondary source must be armed with
                # _wal_base_exact_ok (assign_class_owner does)
                raise ReplicationGap(
                    f"stream '{self.stream}' source offered a checkpoint; "
                    "multi-owner streams are delta-only"
                )
            if "checkpoint" in payload:
                # full sync: the delta range is gone (late-armed source or
                # pruned archives) — restore the shipped checkpoint
                from orientdb_tpu.storage.durability import restore_payload

                floor = max(
                    self.applied_lsn,
                    getattr(self.db, "_repl_applied_lsn", 0),
                )
                ckpt_lsn = payload["checkpoint"].get("lsn", payload["lsn"])
                restored = getattr(self.db, "_repl_restored_ckpt_lsn", None)
                if 0 < ckpt_lsn <= floor or (ckpt_lsn == 0 and floor > 0):
                    # a quorum push (possibly a push-side full sync)
                    # overtook this pull between fetch and apply — the
                    # replica already holds the range. (floor == 0 with a
                    # lsn-0 checkpoint means the OPPOSITE: a late-armed
                    # source whose base content we don't hold.)
                    self.applied_lsn = floor
                    return 0
                if restored is not None and ckpt_lsn <= restored:
                    # raced base state we already restored (the exact=1
                    # request and this response crossed): in sync
                    return 0
                if not _replica_is_fresh(self.db, floor):
                    # restore_payload is additive (indexes crash on
                    # re-create, deletions would survive): restoring
                    # over applied state is never safe — gaps on a
                    # non-fresh replica need a fresh resync
                    raise ReplicationGap(
                        "source lost the delta range past applied_lsn="
                        f"{self.applied_lsn}; full resync needs a FRESH "
                        "replica database"
                    )
                restore_payload(self.db, payload["checkpoint"])
                self.applied_lsn = ckpt_lsn
                self.db._repl_applied_lsn = self.applied_lsn
                # lineage marker: drives the exact=1 pull param above
                self.db._repl_restored_ckpt_lsn = ckpt_lsn
                metrics.incr("replication.full_sync")
                return 1
            floor = max(self.applied_lsn, self._db_floor())
            # named-stream consumers always suppress; a member armed as
            # a secondary OWNER SOURCE (per-class owner streams) must
            # suppress on EVERY puller — re-logging the primary's
            # applied entries into its own WAL would double-ship them
            # to every consumer of its stream (create_class crashes,
            # interleaved rid spaces)
            suppress = self.stream is not None or getattr(
                self.db, "_wal_foreign_suppress", False
            )
            if suppress:
                self.db._tx_local.suppress_wal = True
            from orientdb_tpu.cdc.feed import apply_scope, notify_applied
            from orientdb_tpu.obs.propagation import continue_trace

            try:
                for e in payload["entries"]:
                    lsn = e["lsn"]
                    if lsn <= floor:
                        # already in the db (possibly via the
                        # predecessor); advance our cursor so the range
                        # isn't refetched
                        if lsn > self.applied_lsn:
                            self.applied_lsn = lsn
                        continue
                    # a failing entry must NOT be skipped: advancing past
                    # it would silently diverge the replica while
                    # reporting ONLINE — raise, count as a failure, retry
                    # (the apply span force-joins the ORIGINATING
                    # write's trace, carried on the entry)
                    with continue_trace(
                        "replication.apply_entry",
                        e.get("trace"),
                        force=True,
                        lsn=lsn,
                        source="pull",
                    ), apply_scope(self.db):
                        _apply_entry(self.db, e)
                    if self.stream is None:
                        # changefeed tap (source lsn; local taps were
                        # muted). NAMED streams carry a foreign owner's
                        # independent LSN space — feeding them into the
                        # same feed would collide cursors, so CDC covers
                        # the primary stream only (documented limit)
                        notify_applied(self.db, e)
                    self.applied_lsn = floor = lsn
                    self._set_db_floor(lsn)
                    applied += 1
            finally:
                if suppress:
                    self.db._tx_local.suppress_wal = False
        if applied:
            metrics.incr("replication.applied", applied)
        # lag vs the SOURCE's head LSN (entries past this pull's limit
        # window; 0 when fully caught up) — the /metrics replication
        # signal. Older sources omit head_lsn; fall back to the window.
        head = payload.get("head_lsn", payload.get("lsn", 0))
        metrics.gauge(
            "replication.lag_entries", max(0, head - self.applied_lsn)
        )
        metrics.gauge("replication.applied_lsn", self.applied_lsn)
        return applied

    def _db_floor(self) -> int:
        if self.stream is None:
            return getattr(self.db, "_repl_applied_lsn", 0)
        return getattr(self.db, "_repl_stream_floors", {}).get(
            self.stream, 0
        )

    def _set_db_floor(self, lsn: int) -> None:
        if self.stream is None:
            self.db._repl_applied_lsn = lsn
        else:
            floors = self.db.__dict__.setdefault("_repl_stream_floors", {})
            floors[self.stream] = lsn

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.pull_once()
                self.failures = 0
                self.status = "ONLINE"
            except Exception:
                self.failures += 1
                if self.failures >= self.down_after and self.status != "DOWN":
                    self.status = "DOWN"
                    metrics.incr("replication.source_down")
                    log.warning(
                        "replication source %s marked DOWN after %d failures",
                        self.source_url,
                        self.failures,
                    )
                    if self.on_source_down is not None:
                        try:
                            self.on_source_down()
                        except Exception:
                            log.exception("on_source_down callback failed")
            self._stop.wait(self.interval)

    def lag(self) -> Dict:
        return {
            "status": self.status,
            "applied_lsn": self.applied_lsn,
            "failures": self.failures,
        }
