"""Gremlin-style step-chain traversal DSL.

Analog of the reference's TinkerPop/Gremlin surface ([E] the
``orientdb-gremlin`` companion repo's ``OrientGraphTraversalSource``;
SURVEY.md §2 "Graph API (TinkerPop)" marks Gremlin as the missing
traversal language over the Blueprints layer). Idiomatic-Python
redesign of the core step set:

    g = traversal(db)
    g.V().hasLabel("Person").has("age", P.gt(30)) \
         .out("knows").values("name").toList()
    g.V().repeat(__.out("knows")).times(2).dedup().count().next()

Traversals are LAZY step chains over the embedded database (one Python
generator per step — the pull-based iterator shape of the reference's
step executor); terminal steps (`toList`, `next`, `iterate`, `count`…)
drain them. Traverser state carries the path (for `path()`/
`simplePath()`) and `as_`-labels (for `select`)."""

from __future__ import annotations

import itertools
from typing import Callable, Dict, Iterator, List, Optional, Tuple

from orientdb_tpu.models.record import Direction, Edge, Vertex
from orientdb_tpu.models.rid import RID

_DIRS = {"out": Direction.OUT, "in": Direction.IN, "both": Direction.BOTH}


class P:
    """Gremlin-style predicates for `has(key, P.xxx(...))`."""

    def __init__(self, fn: Callable[[object], bool], desc: str) -> None:
        self.fn = fn
        self.desc = desc

    def __call__(self, v) -> bool:
        try:
            return bool(self.fn(v))
        except TypeError:
            return False  # e.g. None < int

    def __repr__(self) -> str:
        return f"P.{self.desc}"

    @staticmethod
    def eq(x):
        return P(lambda v: v == x, f"eq({x!r})")

    @staticmethod
    def neq(x):
        return P(lambda v: v != x, f"neq({x!r})")

    @staticmethod
    def gt(x):
        return P(lambda v: v is not None and v > x, f"gt({x!r})")

    @staticmethod
    def gte(x):
        return P(lambda v: v is not None and v >= x, f"gte({x!r})")

    @staticmethod
    def lt(x):
        return P(lambda v: v is not None and v < x, f"lt({x!r})")

    @staticmethod
    def lte(x):
        return P(lambda v: v is not None and v <= x, f"lte({x!r})")

    @staticmethod
    def within(*xs):
        if len(xs) == 1 and isinstance(xs[0], (list, tuple, set)):
            xs = tuple(xs[0])
        return P(lambda v: v in xs, f"within{xs!r}")

    @staticmethod
    def without(*xs):
        if len(xs) == 1 and isinstance(xs[0], (list, tuple, set)):
            xs = tuple(xs[0])
        return P(lambda v: v not in xs, f"without{xs!r}")

    @staticmethod
    def between(lo, hi):
        return P(lambda v: v is not None and lo <= v < hi, f"between({lo!r},{hi!r})")

    @staticmethod
    def containing(sub):
        return P(lambda v: isinstance(v, str) and sub in v, f"containing({sub!r})")


class _Traverser:
    __slots__ = ("obj", "path", "labels")

    def __init__(self, obj, path: Tuple, labels: Dict[str, object]) -> None:
        self.obj = obj
        self.path = path
        self.labels = labels

    def step(self, obj) -> "_Traverser":
        return _Traverser(obj, self.path + (obj,), self.labels)

    def tag(self, name: str) -> "_Traverser":
        labels = dict(self.labels)
        labels[name] = self.obj
        t = _Traverser(self.obj, self.path, labels)
        return t


def _obj_key(obj):
    if isinstance(obj, (Vertex, Edge)):
        return ("r", str(obj.rid))
    if isinstance(obj, dict):
        return ("d", tuple(sorted((k, repr(v)) for k, v in obj.items())))
    try:
        hash(obj)
        return ("v", obj)
    except TypeError:
        return ("s", repr(obj))


class Traversal:
    """A lazy step chain; every step method returns a NEW traversal with
    one more stage. Anonymous sub-traversals (``__``) start without a
    source and are bound per-traverser by `where`/`repeat`/`coalesce`."""

    def __init__(self, db=None, source=None, stages=None) -> None:
        self.db = db
        self._source = source  # callable -> iterator of seed objects
        self._stages: List[Callable] = stages or []

    # -- plumbing -----------------------------------------------------------

    def _with(self, stage: Callable) -> "Traversal":
        return Traversal(self.db, self._source, self._stages + [stage])

    def _run(self, seeds: Iterator[_Traverser]) -> Iterator[_Traverser]:
        it = seeds
        for stage in self._stages:
            it = stage(it)
        return it

    def _traversers(self) -> Iterator[_Traverser]:
        if self._source is None:
            raise ValueError("anonymous traversal needs a bound source")
        seeds = (_Traverser(o, (o,), {}) for o in self._source())
        return self._run(seeds)

    def __iter__(self):
        return (t.obj for t in self._traversers())

    # -- navigation steps ---------------------------------------------------

    def _nav(self, dname: str, labels, to_edges: bool) -> "Traversal":
        d = _DIRS[dname]
        labs = list(labels) or [None]

        def stage(it):
            for t in it:
                v = t.obj
                if not isinstance(v, Vertex):
                    continue
                for lab in labs:
                    if to_edges:
                        for e in v.edges(d, lab):
                            yield t.step(e)
                    else:
                        for n in v.vertices(d, lab):
                            yield t.step(n)

        return self._with(stage)

    def out(self, *labels) -> "Traversal":
        return self._nav("out", labels, to_edges=False)

    def in_(self, *labels) -> "Traversal":
        return self._nav("in", labels, to_edges=False)

    def both(self, *labels) -> "Traversal":
        return self._nav("both", labels, to_edges=False)

    def outE(self, *labels) -> "Traversal":
        return self._nav("out", labels, to_edges=True)

    def inE(self, *labels) -> "Traversal":
        return self._nav("in", labels, to_edges=True)

    def bothE(self, *labels) -> "Traversal":
        return self._nav("both", labels, to_edges=True)

    def _edge_end(self, which: str) -> "Traversal":
        def stage(it):
            for t in it:
                e = t.obj
                if not isinstance(e, Edge):
                    continue
                if which == "out":
                    yield t.step(e.from_vertex())
                elif which == "in":
                    yield t.step(e.to_vertex())
                else:  # other: the endpoint we did NOT come from
                    prev = next(
                        (p for p in reversed(t.path[:-1]) if isinstance(p, Vertex)),
                        None,
                    )
                    o, i = e.from_vertex(), e.to_vertex()
                    if prev is not None and o.rid == prev.rid:
                        yield t.step(i)
                    else:
                        yield t.step(o)

        return self._with(stage)

    def outV(self) -> "Traversal":
        return self._edge_end("out")

    def inV(self) -> "Traversal":
        return self._edge_end("in")

    def otherV(self) -> "Traversal":
        return self._edge_end("other")

    # -- filter steps -------------------------------------------------------

    def hasLabel(self, *labels) -> "Traversal":
        labs = set(labels)

        def stage(it):
            db = self.db
            for t in it:
                cls = getattr(t.obj, "class_name", None)
                if cls is None:
                    continue
                if cls in labs:
                    yield t
                elif db is not None:
                    c = db.schema.get_class(cls)
                    if c is not None and any(c.is_subclass_of(x) for x in labs):
                        yield t

        return self._with(stage)

    def has(self, key: str, value=None) -> "Traversal":
        def stage(it):
            for t in it:
                getter = getattr(t.obj, "get", None)
                if getter is None:
                    continue
                v = getter(key)
                if value is None:
                    ok = v is not None
                elif isinstance(value, P):
                    ok = value(v)
                else:
                    ok = v == value
                if ok:
                    yield t

        return self._with(stage)

    def hasNot(self, key: str) -> "Traversal":
        def stage(it):
            for t in it:
                getter = getattr(t.obj, "get", None)
                if getter is not None and getter(key) is None:
                    yield t

        return self._with(stage)

    def hasId(self, *ids) -> "Traversal":
        want = {str(i) for i in ids}

        def stage(it):
            for t in it:
                rid = getattr(t.obj, "rid", None)
                if rid is not None and str(rid) in want:
                    yield t

        return self._with(stage)

    def where(self, sub: "Traversal") -> "Traversal":
        def stage(it):
            for t in it:
                seeded = sub._run(iter([_Traverser(t.obj, (t.obj,), t.labels)]))
                if next(seeded, None) is not None:
                    yield t

        return self._with(stage)

    def not_(self, sub: "Traversal") -> "Traversal":
        def stage(it):
            for t in it:
                seeded = sub._run(iter([_Traverser(t.obj, (t.obj,), t.labels)]))
                if next(seeded, None) is None:
                    yield t

        return self._with(stage)

    def dedup(self) -> "Traversal":
        def stage(it):
            seen = set()
            for t in it:
                k = _obj_key(t.obj)
                if k not in seen:
                    seen.add(k)
                    yield t

        return self._with(stage)

    def simplePath(self) -> "Traversal":
        def stage(it):
            for t in it:
                keys = [_obj_key(p) for p in t.path]
                if len(keys) == len(set(keys)):
                    yield t

        return self._with(stage)

    def limit(self, n: int) -> "Traversal":
        return self._with(lambda it: itertools.islice(it, n))

    def skip(self, n: int) -> "Traversal":
        return self._with(lambda it: itertools.islice(it, n, None))

    def range_(self, lo: int, hi: int) -> "Traversal":
        return self._with(lambda it: itertools.islice(it, lo, hi))

    # -- repeat -------------------------------------------------------------

    def repeat(self, sub: "Traversal") -> "_RepeatBuilder":
        return _RepeatBuilder(self, sub)

    # -- labels / projection ------------------------------------------------

    def as_(self, name: str) -> "Traversal":
        return self._with(lambda it: (t.tag(name) for t in it))

    def select(self, *names) -> "Traversal":
        def stage(it):
            for t in it:
                if len(names) == 1:
                    if names[0] in t.labels:
                        yield t.step(t.labels[names[0]])
                else:
                    if all(n in t.labels for n in names):
                        yield t.step({n: t.labels[n] for n in names})

        return self._with(stage)

    def values(self, *keys) -> "Traversal":
        def stage(it):
            for t in it:
                getter = getattr(t.obj, "get", None)
                if getter is None:
                    continue
                ks = keys or getattr(t.obj, "field_names", lambda: [])()
                for k in ks:
                    v = getter(k)
                    if v is not None:
                        yield t.step(v)

        return self._with(stage)

    def valueMap(self, *keys) -> "Traversal":
        def stage(it):
            for t in it:
                getter = getattr(t.obj, "get", None)
                if getter is None:
                    continue
                ks = keys or getattr(t.obj, "field_names", lambda: [])()
                yield t.step({k: getter(k) for k in ks})

        return self._with(stage)

    def id_(self) -> "Traversal":
        return self._with(
            lambda it: (t.step(str(t.obj.rid)) for t in it if hasattr(t.obj, "rid"))
        )

    def label(self) -> "Traversal":
        return self._with(
            lambda it: (
                t.step(t.obj.class_name)
                for t in it
                if hasattr(t.obj, "class_name")
            )
        )

    def path(self) -> "Traversal":
        return self._with(lambda it: (t.step(list(t.path)) for t in it))

    # -- ordering / aggregation ---------------------------------------------

    def order(self) -> "_OrderBuilder":
        return _OrderBuilder(self)

    def count(self) -> "Traversal":
        def stage(it):
            n = sum(1 for _ in it)
            yield _Traverser(n, (n,), {})

        return self._with(stage)

    def fold(self) -> "Traversal":
        def stage(it):
            objs = [t.obj for t in it]
            yield _Traverser(objs, (objs,), {})

        return self._with(stage)

    def unfold(self) -> "Traversal":
        def stage(it):
            for t in it:
                for o in t.obj if isinstance(t.obj, (list, tuple, set)) else [t.obj]:
                    yield t.step(o)

        return self._with(stage)

    def _agg(self, fn, name) -> "Traversal":
        def stage(it):
            vals = [t.obj for t in it if t.obj is not None]
            out = fn(vals) if vals else None
            yield _Traverser(out, (out,), {})

        return self._with(stage)

    def sum_(self) -> "Traversal":
        return self._agg(sum, "sum")

    def max_(self) -> "Traversal":
        return self._agg(max, "max")

    def min_(self) -> "Traversal":
        return self._agg(min, "min")

    def mean(self) -> "Traversal":
        return self._agg(lambda v: sum(v) / len(v), "mean")

    def groupCount(self) -> "_GroupCountBuilder":
        return _GroupCountBuilder(self)

    def coalesce(self, *subs: "Traversal") -> "Traversal":
        def stage(it):
            for t in it:
                for sub in subs:
                    seeded = list(
                        sub._run(iter([_Traverser(t.obj, (t.obj,), t.labels)]))
                    )
                    if seeded:
                        for s in seeded:
                            yield t.step(s.obj)
                        break

        return self._with(stage)

    def constant(self, v) -> "Traversal":
        return self._with(lambda it: (t.step(v) for t in it))

    # -- terminals ----------------------------------------------------------

    def toList(self) -> List:
        return list(self)

    def toSet(self) -> set:
        return set(self)

    def next(self):
        it = iter(self)
        try:
            return next(it)
        except StopIteration:
            raise StopIteration("traversal is empty") from None

    def hasNext(self) -> bool:
        return next(iter(self), _SENTINEL) is not _SENTINEL

    def iterate(self) -> None:
        for _ in self:
            pass


_SENTINEL = object()


class _RepeatBuilder:
    """`repeat(sub)` awaiting its modulator: `.times(n)`, `.until(sub)`,
    optionally `.emit()` (emit every intermediate traverser too)."""

    def __init__(self, base: Traversal, sub: Traversal) -> None:
        self._base = base
        self._sub = sub
        self._emit = False

    def emit(self) -> "_RepeatBuilder":
        self._emit = True
        return self

    def times(self, n: int) -> Traversal:
        sub, emit = self._sub, self._emit

        def stage(it):
            # `repeat(X).emit()` = emit-AFTER each iteration (TinkerPop:
            # emit-before only when emit() precedes repeat())
            cur = list(it)
            for depth in range(n):
                nxt = []
                for t in cur:
                    nxt.extend(
                        sub._run(iter([_Traverser(t.obj, t.path, t.labels)]))
                    )
                cur = nxt
                if not cur:
                    return
                if emit and depth < n - 1:
                    yield from cur
            yield from cur

        return self._base._with(stage)

    def until(self, cond: Traversal, max_depth: int = 64) -> Traversal:
        sub, emit = self._sub, self._emit

        def done(t):
            seeded = cond._run(iter([_Traverser(t.obj, (t.obj,), t.labels)]))
            return next(seeded, None) is not None

        def stage(it):
            cur = list(it)
            for _depth in range(max_depth):
                still = []
                for t in cur:
                    if done(t):
                        yield t
                    else:
                        if emit:
                            yield t
                        still.append(t)
                if not still:
                    return
                nxt = []
                for t in still:
                    nxt.extend(
                        sub._run(iter([_Traverser(t.obj, t.path, t.labels)]))
                    )
                cur = nxt

        return self._base._with(stage)


class _OrderBuilder:
    def __init__(self, base: Traversal) -> None:
        self._base = base

    def by(self, key=None, desc: bool = False) -> Traversal:
        def keyfn(t):
            if key is None:
                return t.obj
            getter = getattr(t.obj, "get", None)
            v = getter(key) if getter else None
            return (v is None, v)  # nulls last, deterministic

        def stage(it):
            yield from sorted(it, key=keyfn, reverse=desc)

        return self._base._with(stage)


class _GroupCountBuilder:
    def __init__(self, base: Traversal) -> None:
        self._base = base

    def by(self, key=None) -> Traversal:
        def stage(it):
            counts: Dict = {}
            for t in it:
                if key is None:
                    k = t.obj
                else:
                    getter = getattr(t.obj, "get", None)
                    k = getter(key) if getter else None
                k = k if isinstance(k, (str, int, float, bool, type(None))) else str(k)
                counts[k] = counts.get(k, 0) + 1
            yield _Traverser(counts, (counts,), {})

        return self._base._with(stage)

    def __iter__(self):  # bare groupCount() groups by the object itself
        return iter(self.by())

    def toList(self):
        return self.by().toList()

    def next(self):
        return self.by().next()


class GraphTraversalSource:
    """`g = traversal(db)`: the V()/E() entry points."""

    def __init__(self, db) -> None:
        self.db = db

    def V(self, *ids) -> Traversal:
        db = self.db

        def source():
            if ids:
                for i in ids:
                    d = db.load(RID.parse(str(i)) if not isinstance(i, RID) else i)
                    if isinstance(d, Vertex):
                        yield d
            else:
                yield from db.browse_class("V", polymorphic=True)

        return Traversal(db, source)

    def E(self, *ids) -> Traversal:
        db = self.db

        def source():
            if ids:
                for i in ids:
                    d = db.load(RID.parse(str(i)) if not isinstance(i, RID) else i)
                    if isinstance(d, Edge):
                        yield d
            else:
                yield from db.browse_class("E", polymorphic=True)

        return Traversal(db, source)


def traversal(db_or_graph) -> GraphTraversalSource:
    db = getattr(db_or_graph, "db", db_or_graph)
    return GraphTraversalSource(db)


class _Anonymous:
    """`__.out("knows")`-style anonymous traversal factory."""

    def __getattr__(self, name: str):
        def start(*args, **kw):
            t = Traversal(None, None)
            return getattr(t, name)(*args, **kw)

        return start


__ = _Anonymous()
