"""Blueprints-style property-graph API.

Analog of the reference's TinkerPop compatibility layer ([E] graphdb/
``OrientGraph``/``OrientVertex``/``OrientEdge``; SURVEY.md §2 "Graph API
(TinkerPop)"): a thin, idiomatic wrapper over the embedded Database for
programs that want graph verbs (add_vertex/add_edge/vertices/edges,
degree, neighbor iteration) rather than SQL. The native graph model
lives in ``models/`` — this is the compatibility surface, not a second
engine."""

from __future__ import annotations

from typing import Iterator, List, Optional

from orientdb_tpu.models.database import Database
from orientdb_tpu.models.record import Direction, Edge, Vertex
from orientdb_tpu.models.rid import RID


class GraphVertex:
    """[E] OrientVertex: property access + incident-edge navigation."""

    __slots__ = ("_g", "_doc")

    def __init__(self, g: "Graph", doc: Vertex) -> None:
        self._g = g
        self._doc = doc

    @property
    def id(self) -> str:
        return str(self._doc.rid)

    @property
    def label(self) -> str:
        return self._doc.class_name

    def value(self, key: str, default=None):
        return self._doc.get(key, default)

    def keys(self) -> List[str]:
        return self._doc.field_names()

    def property(self, key: str, value) -> "GraphVertex":
        self._doc.set(key, value)
        self._g.db.save(self._doc)
        return self

    def remove(self) -> None:
        self._g.db.delete(self._doc)

    def edges(self, direction: str = "both", label: Optional[str] = None) -> Iterator["GraphEdge"]:
        d = {"out": Direction.OUT, "in": Direction.IN, "both": Direction.BOTH}[direction]
        for e in self._doc.edges(d, label):
            yield GraphEdge(self._g, e)

    def vertices(self, direction: str = "both", label: Optional[str] = None) -> Iterator["GraphVertex"]:
        d = {"out": Direction.OUT, "in": Direction.IN, "both": Direction.BOTH}[direction]
        for v in self._doc.vertices(d, label):
            yield GraphVertex(self._g, v)

    def degree(self, direction: str = "both", label: Optional[str] = None) -> int:
        return sum(1 for _ in self.edges(direction, label))

    def __repr__(self) -> str:
        return f"v[{self.id}]"


class GraphEdge:
    """[E] OrientEdge."""

    __slots__ = ("_g", "_doc")

    def __init__(self, g: "Graph", doc: Edge) -> None:
        self._g = g
        self._doc = doc

    @property
    def id(self) -> str:
        return str(self._doc.rid)

    @property
    def label(self) -> str:
        return self._doc.class_name

    def value(self, key: str, default=None):
        return self._doc.get(key, default)

    def property(self, key: str, value) -> "GraphEdge":
        self._doc.set(key, value)
        self._g.db.save(self._doc)
        return self

    def out_vertex(self) -> GraphVertex:
        return GraphVertex(self._g, self._doc.from_vertex())

    def in_vertex(self) -> GraphVertex:
        return GraphVertex(self._g, self._doc.to_vertex())

    def remove(self) -> None:
        self._g.db.delete(self._doc)

    def __repr__(self) -> str:
        return f"e[{self.id}][{self.label}]"


class Graph:
    """[E] OrientGraph: the Blueprints-style entry point.

    >>> g = Graph()
    >>> a = g.add_vertex("Person", name="ada")
    >>> b = g.add_vertex("Person", name="bob")
    >>> g.add_edge(a, b, "Knows", since=1970)
    """

    def __init__(self, db: Optional[Database] = None, name: str = "graph") -> None:
        self.db = db if db is not None else Database(name)

    # -- mutation -----------------------------------------------------------

    def add_vertex(self, label: str = "V", **props) -> GraphVertex:
        return GraphVertex(self, self.db.new_vertex(label, **props))

    def add_edge(
        self, src: GraphVertex, dst: GraphVertex, label: str = "E", **props
    ) -> GraphEdge:
        return GraphEdge(self, self.db.new_edge(label, src._doc, dst._doc, **props))

    # -- lookup -------------------------------------------------------------

    def vertex(self, vid) -> Optional[GraphVertex]:
        doc = self.db.load(RID.parse(vid) if isinstance(vid, str) else vid)
        return GraphVertex(self, doc) if isinstance(doc, Vertex) else None

    def edge(self, eid) -> Optional[GraphEdge]:
        doc = self.db.load(RID.parse(eid) if isinstance(eid, str) else eid)
        return GraphEdge(self, doc) if isinstance(doc, Edge) else None

    def vertices(self, label: str = "V", **filters) -> Iterator[GraphVertex]:
        for doc in self.db.browse_class(label):
            if isinstance(doc, Vertex) and all(
                doc.get(k) == v for k, v in filters.items()
            ):
                yield GraphVertex(self, doc)

    def edges(self, label: str = "E", **filters) -> Iterator[GraphEdge]:
        for doc in self.db.browse_class(label):
            if isinstance(doc, Edge) and all(
                doc.get(k) == v for k, v in filters.items()
            ):
                yield GraphEdge(self, doc)

    # -- SQL passthrough (the TinkerPop layer exposes this too) -------------

    def query(self, sql: str, **kw):
        return self.db.query(sql, **kw)

    def command(self, sql: str, **kw):
        return self.db.command(sql, **kw)
