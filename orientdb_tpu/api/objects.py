"""Object mapping over documents.

Analog of the reference's object database ([E] object/
``OObjectDatabaseTx``/``OObjectEntitySerializer``; SURVEY.md §2 "Object
API"): maps plain Python classes (dataclasses or attribute classes) onto
schema classes — the reference's javassist-proxied POJOs become plain
instances with an attached ``@rid``/``@version``. Link fields (values
that are themselves mapped instances) persist as RID links and resolve
back to instances on load."""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterator, List, Optional, Type, TypeVar

from orientdb_tpu.models.database import Database
from orientdb_tpu.models.record import Document
from orientdb_tpu.models.rid import RID
from orientdb_tpu.models.schema import PropertyType

T = TypeVar("T")

_RID_ATTR = "_odb_rid"
_VER_ATTR = "_odb_version"


class ObjectDatabase:
    """[E] OObjectDatabaseTx: register classes, save/load/query instances."""

    def __init__(self, db: Optional[Database] = None, name: str = "objects") -> None:
        self.db = db if db is not None else Database(name)
        self._registered: Dict[str, Type] = {}

    # -- registration -------------------------------------------------------

    def register(self, cls: Type[T], vertex: bool = False) -> Type[T]:
        """Register an entity class; its name becomes the schema class
        ([E] ODatabaseObject.getEntityManager().registerEntityClass).
        Dataclass fields (or __init__-set attributes) become properties."""
        name = cls.__name__
        if not self.db.schema.exists_class(name):
            sc = (
                self.db.schema.create_vertex_class(name)
                if vertex
                else self.db.schema.create_class(name)
            )
            if dataclasses.is_dataclass(cls):
                for f in dataclasses.fields(cls):
                    pt = _ptype_for(f.type)
                    if pt is not None:
                        sc.create_property(f.name, pt)
        self._registered[name] = cls
        return cls

    # -- persistence --------------------------------------------------------

    def save(self, obj, _saving: Optional[set] = None) -> object:
        """Persist an instance ([E] OObjectDatabaseTx.save): cycles are
        handled by creating the record shell BEFORE resolving link fields
        (so mutually-referential instances see each other's RIDs), and a
        stale instance version raises ConcurrentModificationError instead
        of silently clobbering a newer store state."""
        from orientdb_tpu.models.database import ConcurrentModificationError

        _saving = _saving if _saving is not None else set()
        if id(obj) in _saving:
            return obj  # already on the save stack; its shell rid exists
        _saving.add(id(obj))
        cls_name = type(obj).__name__
        if cls_name not in self._registered:
            raise TypeError(f"class {cls_name!r} is not registered")
        rid: Optional[RID] = getattr(obj, _RID_ATTR, None)
        if rid is None:
            # phase 1: shell record, so link cycles can point at it
            doc = self.db.new_element(cls_name)
            object.__setattr__(obj, _RID_ATTR, doc.rid)
        else:
            doc = self.db.load(rid)
            if doc is None:
                raise LookupError(f"{rid} vanished")
            stale = getattr(obj, _VER_ATTR, doc.version)
            if doc.version != stale:
                raise ConcurrentModificationError(
                    f"{rid}: stored v{doc.version} != instance v{stale}"
                )
        # phase 2: resolve fields. Linked instances cascade
        # unconditionally (the _saving guard breaks cycles): a modified,
        # already-persisted linked object must not be silently skipped.
        fields = {}
        for k, v in _instance_fields(obj).items():
            if type(v).__name__ in self._registered:
                self.save(v, _saving)
                fields[k] = getattr(v, _RID_ATTR)
            else:
                fields[k] = v
        # no-op saves skip the store write (cascades would otherwise bump
        # versions on every reachable object)
        if rid is not None and fields == {
            k: doc.get(k) for k in doc.field_names()
        }:
            object.__setattr__(obj, _VER_ATTR, doc.version)
            return obj
        for k, v in fields.items():
            doc.set(k, v)
        self.db.save(doc)
        object.__setattr__(obj, _RID_ATTR, doc.rid)
        object.__setattr__(obj, _VER_ATTR, doc.version)
        return obj

    def load(self, rid, cls: Optional[Type[T]] = None) -> Optional[T]:
        if isinstance(rid, str):
            rid = RID.parse(rid)
        doc = self.db.load(rid)
        if doc is None:
            return None
        return self._materialize(doc, cls)

    def delete(self, obj) -> None:
        rid = getattr(obj, _RID_ATTR, None)
        if rid is None:
            return
        doc = self.db.load(rid)
        if doc is not None:
            self.db.delete(doc)
        object.__setattr__(obj, _RID_ATTR, None)

    def browse(self, cls: Type[T]) -> Iterator[T]:
        for doc in self.db.browse_class(cls.__name__):
            yield self._materialize(doc, cls)

    def query(self, sql: str, params=None, cls: Optional[Type[T]] = None) -> List[T]:
        """SQL over entities; element rows materialize as instances."""
        out = []
        for r in self.db.query(sql, params=params):
            if r.is_element:
                out.append(self._materialize(r.element, cls))
            else:
                out.append(r.to_dict())
        return out

    # -- materialization ----------------------------------------------------

    def _materialize(
        self, doc: Document, cls: Optional[Type] = None, _memo: Optional[Dict] = None
    ):
        """Instance for a document; ``_memo`` (rid → instance) makes link
        cycles materialize as object cycles instead of recursing forever."""
        _memo = _memo if _memo is not None else {}
        hit = _memo.get(doc.rid)
        if hit is not None:
            return hit
        cls = cls or self._registered.get(doc.class_name)
        if cls is None:
            raise TypeError(f"no registered class for {doc.class_name!r}")
        # shell first, memoize, THEN resolve links (cycles point at the shell)
        obj = cls.__new__(cls)
        _memo[doc.rid] = obj
        for k, v in doc.fields().items():
            if isinstance(v, RID):
                linked = self.db.load(v)
                v = (
                    self._materialize(linked, _memo=_memo)
                    if linked is not None
                    else None
                )
            object.__setattr__(obj, k, v)
        if dataclasses.is_dataclass(cls):
            # fill declared fields absent from the document with defaults
            for f in dataclasses.fields(cls):
                if not hasattr(obj, f.name):
                    if f.default is not dataclasses.MISSING:
                        object.__setattr__(obj, f.name, f.default)
                    elif f.default_factory is not dataclasses.MISSING:  # type: ignore[misc]
                        object.__setattr__(obj, f.name, f.default_factory())  # type: ignore[misc]
        object.__setattr__(obj, _RID_ATTR, doc.rid)
        object.__setattr__(obj, _VER_ATTR, doc.version)
        return obj


def rid_of(obj) -> Optional[RID]:
    """The persistent identity of a saved instance (None = transient)."""
    return getattr(obj, _RID_ATTR, None)


def _instance_fields(obj) -> Dict[str, object]:
    if dataclasses.is_dataclass(obj):
        return {f.name: getattr(obj, f.name) for f in dataclasses.fields(obj)}
    return {
        k: v for k, v in vars(obj).items() if not k.startswith("_")
    }


def _ptype_for(annotation) -> Optional[PropertyType]:
    mapping = {
        int: PropertyType.LONG,
        "int": PropertyType.LONG,
        float: PropertyType.DOUBLE,
        "float": PropertyType.DOUBLE,
        str: PropertyType.STRING,
        "str": PropertyType.STRING,
        bool: PropertyType.BOOLEAN,
        "bool": PropertyType.BOOLEAN,
    }
    return mapping.get(annotation)
