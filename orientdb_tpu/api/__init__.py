from orientdb_tpu.api.graph import Graph  # noqa: F401
from orientdb_tpu.api.objects import ObjectDatabase  # noqa: F401
