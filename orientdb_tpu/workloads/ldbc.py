"""LDBC SNB Interactive *short reads* IS1–IS7 as MATCH statements.

The seven short reads are the north-star read workload (BASELINE.json
configs[2] and [4]; SURVEY.md §6 rows 3/5). Each is translated from its
SNB specification to this engine's MATCH dialect:

- IS1  person profile + city          — 1-hop ``isLocatedIn``
- IS2  person's last 10 messages      — ``<-hasCreator-`` then a
       variable-depth ``replyOf`` walk to the root Post (the walk's
       target carries ``class:Post``: traversal passes through Comments
       and emits only the root), then the root's author
- IS3  person's friends               — undirected 1-hop ``knows`` with
       the friendship edge bound (``{as:k}``) for its creationDate
- IS4  message content/date           — single-node MATCH on Message
- IS5  message author                 — 1-hop ``hasCreator``
- IS6  forum + moderator of a message — ``replyOf``-walk to the root
       Post, then ``<-containerOf-`` and ``-hasModerator->``
- IS7  replies to a message + their authors + whether each reply author
       knows the original author — the knows flag is an OPTIONAL cyclic
       arm between two already-bound aliases (a semi-join probe).

Every query is a single MATCH so the whole workload runs on the compiled
TPU path; parameters use the ``:name`` form so plans cache across
parameter values. Parity oracle/TPU is asserted in
``tests/test_ldbc_is.py``; throughput is measured in ``bench.py``.
"""

from __future__ import annotations

from typing import Dict

# IS1: profile of a person, plus the city they live in.
IS1 = (
    "MATCH {class:Person, as:p, where:(id = :personId)}"
    "-isLocatedIn->{as:c} "
    "RETURN p.firstName AS firstName, p.lastName AS lastName, "
    "p.birthday AS birthday, p.locationIP AS locationIP, "
    "p.browserUsed AS browserUsed, c.name AS cityName, "
    "p.creationDate AS creationDate"
)

# IS2: the person's 10 most recent messages; for each, the root post of
# its thread and that post's author. A Post is its own root (the
# var-depth arm emits the origin at depth 0 when it passes the class
# mask), so one row per message.
IS2 = (
    "MATCH {class:Person, as:p, where:(id = :personId)}"
    "<-hasCreator-{as:m}"
    "-replyOf->{as:post, class:Post, while:(true)}, "
    "{as:post}-hasCreator->{as:op} "
    "RETURN m.id AS messageId, m.content AS messageContent, "
    "m.creationDate AS messageCreationDate, post.id AS originalPostId, "
    "op.id AS originalPostAuthorId, "
    "op.firstName AS originalPostAuthorFirstName, "
    "op.lastName AS originalPostAuthorLastName "
    "ORDER BY messageCreationDate DESC, messageId DESC LIMIT 10"
)

# IS3: all friends, most recent friendship first. `knows` is stored as
# one directed edge per pair and queried undirected, per SNB convention.
IS3 = (
    "MATCH {class:Person, as:p, where:(id = :personId)}"
    "-knows{as:k}-{as:f} "
    "RETURN f.id AS personId, f.firstName AS firstName, "
    "f.lastName AS lastName, k.creationDate AS friendshipCreationDate "
    "ORDER BY friendshipCreationDate DESC, personId ASC"
)

# IS4: content + creation date of a message (Post or Comment — Message
# is the abstract superclass, matched polymorphically).
IS4 = (
    "MATCH {class:Message, as:m, where:(id = :messageId)} "
    "RETURN m.creationDate AS messageCreationDate, m.content AS content"
)

# IS5: the author of a message.
IS5 = (
    "MATCH {class:Message, as:m, where:(id = :messageId)}"
    "-hasCreator->{as:p} "
    "RETURN p.id AS personId, p.firstName AS firstName, "
    "p.lastName AS lastName"
)

# IS6: the forum containing a message's thread, and its moderator.
IS6 = (
    "MATCH {class:Message, as:m, where:(id = :messageId)}"
    "-replyOf->{as:post, class:Post, while:(true)}, "
    "{as:post}<-containerOf-{as:f}-hasModerator->{as:mod} "
    "RETURN f.id AS forumId, f.title AS forumTitle, "
    "mod.id AS moderatorId, mod.firstName AS moderatorFirstName, "
    "mod.lastName AS moderatorLastName"
)

# IS7: direct replies to a message, each reply's author, and whether the
# reply author knows the original message's author. The knows probe is an
# OPTIONAL undirected arm between the two bound person aliases: when the
# edge exists the arm binds it ({as:kn}), otherwise the row survives with
# kn = null — so `kn IS NOT NULL` is the boolean the SNB spec asks for.
IS7 = (
    "MATCH {class:Message, as:m, where:(id = :messageId)}"
    "<-replyOf-{as:c}-hasCreator->{as:ra}, "
    "{as:m}-hasCreator->{as:ma}, "
    "{as:ma}-knows{as:kn, optional:true}-{as:ra} "
    "RETURN c.id AS commentId, c.content AS commentContent, "
    "c.creationDate AS commentCreationDate, ra.id AS replyAuthorId, "
    "ra.firstName AS replyAuthorFirstName, "
    "ra.lastName AS replyAuthorLastName, "
    "kn IS NOT NULL AS replyAuthorKnowsOriginalMessageAuthor "
    "ORDER BY commentCreationDate DESC, replyAuthorId ASC"
)

IS_QUERIES: Dict[str, str] = {
    "IS1": IS1,
    "IS2": IS2,
    "IS3": IS3,
    "IS4": IS4,
    "IS5": IS5,
    "IS6": IS6,
    "IS7": IS7,
}


def is_query(name: str) -> str:
    return IS_QUERIES[name.upper()]


# ---------------------------------------------------------------------------
# Interactive COMPLEX reads (IC) — the multi-hop half of the SNB
# interactive workload (BASELINE configs[4]'s "multi-pattern MATCH"
# shape). Translated to this dialect for the entities the offline
# generator covers; each stays a single MATCH so the whole workload
# rides the compiled path.
# ---------------------------------------------------------------------------

# IC1 (transitive friends by name): friends within 3 knows-hops whose
# first name matches, nearest first. The var-depth arm emits each
# reachable person once at its minimum depth.
IC1 = (
    "MATCH {class:Person, as:p, where:(id = :personId)}"
    "-knows-{as:f, while:($depth < 3), "
    "where:(firstName = :firstName AND id <> :personId), "
    "depthAlias: dist} "
    "RETURN f.id AS friendId, f.lastName AS friendLastName, "
    "dist AS distanceFromPerson "
    "ORDER BY distanceFromPerson ASC, friendLastName ASC, friendId ASC "
    "LIMIT 20"
)

# IC2 (recent messages of friends): a friend's messages before a date,
# newest first.
IC2 = (
    "MATCH {class:Person, as:p, where:(id = :personId)}"
    "-knows-{as:f}"
    "<-hasCreator-{as:m, where:(creationDate < :maxDate)} "
    "RETURN f.id AS personId, f.firstName AS personFirstName, "
    "f.lastName AS personLastName, m.id AS messageId, "
    "m.content AS messageContent, m.creationDate AS messageCreationDate "
    "ORDER BY messageCreationDate DESC, messageId ASC LIMIT 20"
)

# IC-shaped aggregate: message volume over the friend-of-friend hull —
# the 3-hop join whose binding table the reference's per-record DFS
# walks row by row, collapsed here into COUNT pushdown weight passes.
ICA = (
    "MATCH {class:Person, as:p, where:(id = :personId)}"
    "-knows-{as:f}"
    "-knows-{as:ff, where:(id <> :personId)}"
    "<-hasCreator-{as:m} "
    "RETURN count(*) AS messageCount"
)

IC_QUERIES: Dict[str, str] = {
    "IC1": IC1,
    "IC2": IC2,
    "ICA": ICA,
}


def ic_query(name: str) -> str:
    return IC_QUERIES[name.upper()]
