"""Closed-loop production traffic simulator with an SLO verdict.

Every scenario before this PR measures ONE query shape at a time; the
north star ("millions of users") is mixed traffic with failures. This
module drives the whole serving surface at once, deterministically:

- a **seeded schedule** (:func:`build_schedule`) of LDBC SNB
  interactive operations — the IS1–7 short reads and IC1/IC2/ICA
  complex reads from ``workloads/ldbc.py``, mixed with inserts/updates
  at the SNB update ratio (``workload_update_ratio``) and cross-owner
  2PC transactions — same seed, same schedule, byte for byte
  (``schedule_digest`` proves it);
- **many concurrent closed-loop client sessions** over BOTH transports
  (binary protocol sessions via ``client/remote``, HTTP sessions via
  the REST routes — every simulated HTTP request crosses the
  ``workload.http`` fault point) against a **real multi-member
  cluster** (primary + replicas, one class write-owned by a replica so
  transactions 2-phase commit across members), with live CDC consumers
  attached on both transports;
- a deterministic **chaos phase**: a seeded :class:`chaos.FaultPlan`
  armed for the traffic window plus a scheduled replica kill/restart
  (and optionally a mid-run primary failover), then a **settle phase**
  that keeps issuing light traffic so replicas catch up, tripped
  breakers half-open and close, and alerts resolve — the run must end
  *recovered*, not mid-incident;
- one **SLO verdict** (obs/slo): per-class p50/p99 and availability
  read from the query-stats histograms over this run's window, no
  alert left firing, error-budget burn in target. The report is
  machine-readable and reproducible: same seed, same verdict.

``TrafficSim(seed=7).run()`` returns the full run report (schedule
digest, per-kind op/error counts, CDC delivery counts, chaos fires,
and the SLO report under ``"slo"``).
"""

from __future__ import annotations

import json
import hashlib
import random
import threading
import time
import urllib.parse
import urllib.request
from typing import Dict, List, NamedTuple, Optional

from orientdb_tpu.chaos.faults import FaultPlan, fault
from orientdb_tpu.obs.slo import SloClass, SloSpec, engine as slo_engine
from orientdb_tpu.obs.stats import stats
from orientdb_tpu.obs.trace import span
from orientdb_tpu.utils.config import config
from orientdb_tpu.utils.logging import get_logger
from orientdb_tpu.utils.metrics import metrics
from orientdb_tpu.workloads.ldbc import IC_QUERIES, IS_QUERIES

log = get_logger("workload")

#: read-op kinds and their mix weights (short reads dominate, the SNB
#: interactive shape); writes are drawn separately at the update ratio
READ_KINDS = tuple(sorted(IS_QUERIES)) + tuple(sorted(IC_QUERIES))
READ_WEIGHTS = (4, 4, 4, 4, 4, 4, 4, 1, 1, 1)

#: write-op kinds drawn at the update ratio. Session 0 leans on the
#: cross-owner transaction (it alone runs the embedded 2PC path — one
#: database handle must not see concurrent begin()s); the other
#: sessions split inserts/updates
WRITE_KINDS = ("insert", "update", "tx2pc")
WRITE_WEIGHTS = (5, 3, 2)
WRITE_WEIGHTS_TX = (2, 1, 7)

#: the synthetic statement the cross-owner transaction's latency and
#: errors are recorded under (stats.record_external) — the SLO spec's
#: tx2pc class joins the stats table on its fingerprint
TX2PC_SQL = "COMMIT CROSS OWNER SIM"

#: id space for simulator-inserted messages, far above any generated id
_SIM_ID_BASE = 10_000_000


class Op(NamedTuple):
    kind: str  #: IS1..IS7 | IC1 | IC2 | ICA | insert | update | tx2pc
    sql: str  #: parameterized read, or a literal write statement
    params: Optional[Dict]


def _inline(sql: str, params: Optional[Dict]) -> str:
    """Render ``:name`` parameters as literals (the HTTP sessions'
    form — the REST routes take raw SQL). Longest names substitute
    first so a shared prefix can never corrupt a sibling."""
    if not params:
        return sql
    for k in sorted(params, key=len, reverse=True):
        v = params[k]
        lit = (
            "'" + str(v).replace("'", "\\'") + "'"
            if isinstance(v, str)
            else str(v)
        )
        sql = sql.replace(":" + k, lit)
    return sql


def build_schedule(
    seed: int,
    sessions: int,
    ops_per_session: int,
    update_ratio: float,
    n_persons: int,
    n_messages: int,
    first_name: str = "A",
) -> List[List[Op]]:
    """The deterministic event schedule: one op list per session, every
    draw from one ``random.Random(seed)`` in fixed order — same inputs,
    same schedule, regardless of how threads later interleave."""
    rng = random.Random(seed)
    next_id = _SIM_ID_BASE
    schedule: List[List[Op]] = []
    for s in range(sessions):
        ops: List[Op] = []
        for _i in range(ops_per_session):
            if rng.random() < update_ratio:
                kind = rng.choices(
                    WRITE_KINDS,
                    WRITE_WEIGHTS_TX if s == 0 else WRITE_WEIGHTS,
                )[0]
                if kind == "tx2pc" and s != 0:
                    kind = "insert"  # one embedded tx path (session 0)
                if kind == "insert":
                    next_id += 1
                    ops.append(
                        Op(
                            "insert",
                            f"INSERT INTO Post SET id = {next_id}, "
                            f"content = 'sim', creationDate = "
                            f"{1_000_000 + next_id}",
                            None,
                        )
                    )
                elif kind == "update":
                    pid = rng.randrange(max(n_persons, 1))
                    ops.append(
                        Op(
                            "update",
                            "UPDATE Person SET browserUsed = "
                            f"'sim{_i}' WHERE id = {pid}",
                            None,
                        )
                    )
                else:
                    next_id += 1
                    ops.append(Op("tx2pc", TX2PC_SQL, {"uid": next_id}))
                continue
            kind = rng.choices(READ_KINDS, READ_WEIGHTS)[0]
            sql = (
                IS_QUERIES[kind] if kind in IS_QUERIES else IC_QUERIES[kind]
            )
            if ":personId" in sql:
                p: Dict = {"personId": rng.randrange(max(n_persons, 1))}
            else:
                p = {"messageId": rng.randrange(max(n_messages, 1))}
            if kind == "IC1":
                p["firstName"] = first_name
            elif kind == "IC2":
                p["maxDate"] = 2**30 + rng.randrange(100_000)
            ops.append(Op(kind, sql, p))
        schedule.append(ops)
    return schedule


def schedule_digest(schedule: List[List[Op]]) -> str:
    """Stable digest of one schedule (the determinism receipt carried
    in the run report: same seed, same digest)."""
    doc = [[(o.kind, o.sql, o.params) for o in ops] for ops in schedule]
    return hashlib.blake2b(
        json.dumps(doc, sort_keys=True).encode(), digest_size=8
    ).hexdigest()


def default_slo_spec(
    first_name: str = "A",
    p50_ms: Optional[float] = None,
    p99_ms: Optional[float] = None,
    availability: Optional[float] = None,
    kinds: Optional[set] = None,
) -> SloSpec:
    """The spec a simulator run is judged against: one class per read
    kind (both the parameterized and literal-inlined spellings — the
    two transports fingerprint differently), plus the write and 2PC
    classes. Targets default to the ``slo_*`` config keys; the chaos-
    facing write/2PC classes check latency only by default (the chaos
    plan EXISTS to fail some of them — run-wide damage is bounded by
    the error-budget burn policy instead). ``kinds`` limits the spec
    to the op kinds one schedule actually drew (a short run must not
    fail ``no_traffic`` on a class it never scheduled)."""
    classes = []
    example = {
        "personId": 1,
        "messageId": 1,
        "firstName": first_name,
        "maxDate": 2**30,
    }
    for kind in READ_KINDS:
        if kinds is not None and kind not in kinds:
            continue
        sql = IS_QUERIES[kind] if kind in IS_QUERIES else IC_QUERIES[kind]
        used = {
            k: v
            for k, v in example.items()
            if ":" + k in sql
        }
        classes.append(
            SloClass(
                kind,
                [sql, _inline(sql, used)],
                p50_ms=p50_ms,
                p99_ms=p99_ms,
                availability=availability,
            )
        )
    writes = (
        ("insert", "INSERT INTO Post SET id = 1, content = 'sim', "
         "creationDate = 1"),
        ("update", "UPDATE Person SET browserUsed = 'sim1' WHERE id = 1"),
        ("tx2pc", TX2PC_SQL),
    )
    for kind, sql in writes:
        if kinds is not None and kind not in kinds:
            continue
        classes.append(
            SloClass(
                kind, [sql],
                p50_ms=p50_ms, p99_ms=p99_ms, availability=0.0,
            )
        )
    return SloSpec(classes)


class _HttpSession:
    """One closed-loop HTTP client: reads via ``GET /query``, writes
    via ``POST /command`` (raw SQL, parameters inlined). Knows both
    members' ports so it retries once against the sibling on a
    transport failure — the poor operator's failover client."""

    def __init__(self, ports: List[int], dbname: str, password: str) -> None:
        import base64

        self.urls = [f"http://127.0.0.1:{p}" for p in ports]
        self.dbname = dbname
        cred = base64.b64encode(f"admin:{password}".encode()).decode()
        self.headers = {"Authorization": f"Basic {cred}"}

    def _http_call(self, base: str, op: Op) -> None:
        sql = _inline(op.sql, op.params)
        if op.kind in ("insert", "update"):
            req = urllib.request.Request(
                f"{base}/command/{self.dbname}/sql",
                data=json.dumps({"command": sql}).encode(),
                headers=self.headers,
                method="POST",
            )
        else:
            q = urllib.parse.quote(sql, safe="")
            req = urllib.request.Request(
                f"{base}/query/{self.dbname}/sql/{q}",
                headers=self.headers,
            )
        with fault.point("workload.http"):
            with urllib.request.urlopen(req, timeout=15) as r:
                r.read()

    def run_op(self, op: Op) -> None:
        try:
            self._http_call(self.urls[0], op)
        except urllib.error.HTTPError:
            # a non-2xx is a DEFINITIVE server answer (the server-side
            # stats table already recorded any execution error) — never
            # replayed against the sibling: a non-idempotent write must
            # not run twice, and the error must not count twice
            raise
        except (urllib.error.URLError, OSError):
            # connection-level failure: one retry against the sibling
            # member, READS ONLY — a timed-out write may already have
            # executed on the first member (the response, not the
            # request, can be what was lost), and replaying it would
            # apply it twice
            if len(self.urls) < 2 or op.kind in ("insert", "update"):
                raise
            self._http_call(self.urls[1], op)

    def close(self) -> None:
        pass


class _BinarySession:
    """One closed-loop binary-protocol client (a FailoverDatabase when
    both members' ports are known, so a mid-run failover re-routes)."""

    def __init__(self, ports: List[int], dbname: str, password: str) -> None:
        from orientdb_tpu.client.remote import connect

        hosts = ";".join(f"127.0.0.1:{p}" for p in ports)
        self.db = connect(f"remote:{hosts}/{dbname}", "admin", password)

    def run_op(self, op: Op) -> None:
        if op.kind in ("insert", "update"):
            self.db.command(_inline(op.sql, op.params))
        else:
            self.db.query(op.sql, op.params).to_dicts()

    def close(self) -> None:
        self.db.close()


class TrafficSim:
    """One reproducible closed-loop run. Construction is cheap; the
    cluster builds and the sessions run inside :meth:`run`."""

    def __init__(
        self,
        seed: int = 0,
        persons: int = 120,
        sessions: Optional[int] = None,
        ops_per_session: Optional[int] = None,
        update_ratio: Optional[float] = None,
        replicas: int = 1,
        chaos: Optional[FaultPlan] = None,
        replica_outage: Optional[tuple] = (0.3, 0.6),
        promote_at: Optional[float] = None,
        cdc_consumers: int = 2,
        spec: Optional[SloSpec] = None,
        settle_s: Optional[float] = None,
        tick_s: float = 0.2,
        reset_alerts: bool = True,
        dbname: str = "simdb",
        password: str = "pw",
    ) -> None:
        self.seed = seed
        self.persons = persons
        self.sessions = (
            config.workload_sessions if sessions is None else sessions
        )
        self.ops_per_session = (
            config.workload_ops if ops_per_session is None else ops_per_session
        )
        self.update_ratio = (
            config.workload_update_ratio
            if update_ratio is None
            else update_ratio
        )
        self.replicas = max(replicas, 1)
        self.chaos = chaos
        self.replica_outage = replica_outage
        self.promote_at = promote_at
        self.cdc_consumers = cdc_consumers
        self.spec = spec
        self.settle_s = (
            config.workload_settle_s if settle_s is None else settle_s
        )
        self.tick_s = tick_s
        self.reset_alerts = reset_alerts
        self.dbname = dbname
        self.password = password
        # shared mutable run state: containers only (threads mutate
        # them under _mu; no attribute is rebound after __init__)
        self._mu = threading.Lock()
        self._counts: Dict[str, int] = {}
        self._client_errors: Dict[str, int] = {}
        self._state = {"completed": 0, "cdc_events": 0, "stop": False}
        self._schedule: List[List[Op]] = []
        self._harness: Dict[str, object] = {}

    # -- counters (tiny lock sections; never I/O under _mu) -----------------

    def _bump(self, table: Dict[str, int], kind: str) -> None:
        with self._mu:
            table[kind] = table.get(kind, 0) + 1

    def _completed(self) -> int:
        with self._mu:
            self._state["completed"] += 1
            return self._state["completed"]

    # -- cluster harness ----------------------------------------------------

    def _build(self) -> None:
        """Generate the SNB graph on the primary, replicate it to every
        member, and hand one class's write ownership to a replica so
        the tx2pc ops actually cross members."""
        from orientdb_tpu.parallel.cluster import Cluster
        from orientdb_tpu.server.server import Server
        from orientdb_tpu.storage.ingest import generate_ldbc_snb

        servers = [
            Server(name=f"sim{i}", admin_password=self.password)
            for i in range(1 + self.replicas)
        ]
        for s in servers:
            s.startup()
        pdb = servers[0].create_database(self.dbname)
        cl = Cluster(
            self.dbname, user="admin", password=self.password,
            interval=0.1, down_after=10_000,
        )
        cl.set_primary("n0", servers[0], pdb)
        for i in range(1, 1 + self.replicas):
            cl.add_replica(f"n{i}", servers[i])
        cl.start()
        generate_ldbc_snb(db=pdb, n_persons=self.persons, seed=self.seed)
        pdb.schema.create_vertex_class("SimEvent")
        pdb.schema.create_vertex_class("SimAudit")
        n_messages = pdb.count_class("Post") + pdb.count_class("Comment")
        first = next(pdb.browse_class("Person")).get("firstName") or "A"
        # every replica must hold the dataset before traffic starts
        # (reads serve anywhere, and the 2PC owner validates schema)
        want = pdb.count_class("Person")
        deadline = time.monotonic() + 60
        for i in range(1, 1 + self.replicas):
            m = cl.members[f"n{i}"]
            while time.monotonic() < deadline:
                m.puller.pull_once()
                try:
                    if m.db.count_class("Person") >= want:
                        break
                except ValueError:
                    pass
                time.sleep(0.05)
        cl.assign_class_owner("SimAudit", "n1")
        self._harness.update(
            servers=servers, cluster=cl, pdb=pdb,
            n_messages=n_messages, first_name=first,
        )

    def _teardown(self) -> None:
        cl = self._harness.get("cluster")
        if cl is not None:
            try:
                cl.stop()
            except Exception:
                log.exception("cluster stop failed")
        for s in self._harness.get("servers", ()):
            try:
                s.shutdown()
            except Exception:
                log.exception("server shutdown failed")

    # -- op execution --------------------------------------------------------

    def _run_tx2pc(self, op: Op) -> None:
        """One cross-owner transaction from the embedded primary
        handle: a SimEvent (primary-owned) plus a SimAudit (replica-
        owned) commit all-or-nothing through parallel/twophase. Its
        latency and outcome fold into the stats table under
        :data:`TX2PC_SQL` so the SLO plane judges it like any query
        class."""
        pdb = self._harness["pdb"]
        uid = op.params["uid"]
        t0 = time.perf_counter()
        err: Optional[BaseException] = None
        try:
            pdb.begin()
            pdb.new_vertex("SimEvent", uid=uid)
            pdb.new_vertex("SimAudit", uid=uid)
            pdb.commit()
        except Exception as e:
            err = e
            tx = getattr(pdb, "tx", None)
            if tx is not None:
                try:
                    tx.rollback()
                except Exception:
                    log.exception("tx2pc rollback failed")
        stats.record_external(
            TX2PC_SQL, time.perf_counter() - t0, engine="tx2pc", error=err
        )
        if err is not None:
            raise err

    def _session_run(self, idx: int, client) -> None:
        """One closed-loop session: issue the next op when the previous
        completes; client-side transport failures count against the
        run (and fold into the stats table — the server never saw
        them, but the USER did)."""
        from orientdb_tpu.client.remote import (
            RemoteConnectionError,
            ServerOverloadedError,
        )

        with span("workload.session", session=idx):
            for op in self._schedule[idx]:
                self._bump(self._counts, op.kind)
                metrics.incr("workload.ops")
                try:
                    if op.kind == "tx2pc":
                        self._run_tx2pc(op)
                    else:
                        client.run_op(op)
                except urllib.error.HTTPError as e:
                    # a definitive HTTP status: an execution error was
                    # already recorded server-side — except a 503 shed,
                    # which the admission layer refuses BEFORE the
                    # engine front door, so the failed call is recorded
                    # here (availability must see shed traffic)
                    self._bump(self._client_errors, op.kind)
                    metrics.incr("workload.client_errors")
                    if e.code == 503 and op.kind != "tx2pc":
                        stats.record_external(
                            op.sql, 0.0, engine="client", error=e
                        )
                except (
                    ServerOverloadedError,
                    RemoteConnectionError,
                    urllib.error.URLError,
                    OSError,
                ) as e:
                    # transport-level failure (or a binary-channel
                    # shed): the server-side stats table never saw
                    # this op, so record the failed call here —
                    # availability must reflect what the client
                    # observed
                    self._bump(self._client_errors, op.kind)
                    metrics.incr("workload.client_errors")
                    if op.kind != "tx2pc":
                        stats.record_external(
                            op.sql, 0.0, engine="client", error=e
                        )
                except Exception:
                    # the server recorded this one (stats error path)
                    self._bump(self._client_errors, op.kind)
                    metrics.incr("workload.client_errors")
                self._completed()

    # -- chaos / control -----------------------------------------------------

    def _controller(self, watchdog, total_ops: int) -> None:
        """Ticks the watchdog through the run and executes the
        scheduled infrastructure events (replica kill/restart, the
        optional failover) at their op-count thresholds."""
        cl = self._harness["cluster"]
        kill_at = restart_at = promote_op = None
        if self.replica_outage is not None:
            kill_at = int(self.replica_outage[0] * total_ops)
            restart_at = int(self.replica_outage[1] * total_ops)
        if self.promote_at is not None:
            promote_op = int(self.promote_at * total_ops)
        killed = restarted = promoted = False
        while True:
            with self._mu:
                done = self._state["completed"]
                stop = self._state["stop"]
            if stop:
                return
            if kill_at is not None and not killed and done >= kill_at:
                killed = True
                log.warning("chaos: killing replica n1 (op %d)", done)
                cl.stop_replica("n1")
            if (
                restart_at is not None
                and killed
                and not restarted
                and done >= restart_at
            ):
                restarted = True
                log.warning("chaos: restarting replica n1 (op %d)", done)
                cl.restart_replica("n1")
            if promote_op is not None and not promoted and done >= promote_op:
                promoted = True
                log.warning("chaos: promoting n1 (op %d)", done)
                cl.promote("n1")
            try:
                watchdog.tick()
            except Exception:
                log.exception("watchdog tick failed mid-run")
            time.sleep(self.tick_s)

    def _settle(self, watchdog) -> Dict[str, object]:
        """Post-chaos recovery: light clean traffic (each round probes
        any tripped breaker and advances replication), replica
        catch-up, and watchdog ticks, until no alert is firing and no
        breaker is open — or the settle budget runs out. The verdict
        judges the END state, so an unrecovered run fails loudly."""
        from orientdb_tpu.obs.alerts import engine as alert_engine
        from orientdb_tpu.parallel.resilience import breaker_snapshot

        cl = self._harness["cluster"]
        deadline = time.monotonic() + self.settle_s
        rounds = 0
        uid = _SIM_ID_BASE + 900_000
        while True:
            rounds += 1
            for m in cl.members.values():
                if m.role == "REPLICA" and m.puller is not None:
                    try:
                        m.puller.pull_once()
                    except Exception:
                        log.exception("settle pull failed")
            open_breakers = [
                n
                for n, b in breaker_snapshot().items()
                if b["state"] == "open"
            ]
            if open_breakers:
                # one clean cross-owner tx probes the forward channel
                # (half-open after reset_s) so the breaker can close
                uid += 1
                try:
                    self._run_tx2pc(Op("tx2pc", TX2PC_SQL, {"uid": uid}))
                except Exception:
                    log.warning("settle probe tx failed (breaker warm-up)")
            try:
                watchdog.tick()
            except Exception:
                # a mid-recovery tick may race a half-restarted member;
                # the verdict must still be produced from the end state
                log.exception("watchdog tick failed during settle")
            firing = [
                a
                for a in alert_engine.active()
                if a["state"] == "firing"
            ]
            if not firing and not open_breakers:
                return {"rounds": rounds, "settled": True}
            if time.monotonic() > deadline:
                return {
                    "rounds": rounds,
                    "settled": False,
                    "firing": [a["rule"] for a in firing],
                    "open_breakers": open_breakers,
                }
            time.sleep(self.tick_s)

    # -- the run -------------------------------------------------------------

    def run(self) -> Dict[str, object]:
        from orientdb_tpu.obs.alerts import engine as alert_engine
        from orientdb_tpu.obs.watchdog import HealthWatchdog

        t_start = time.perf_counter()
        if self.reset_alerts:
            # the verdict judges THIS run: ambient alert lifecycle
            # state from earlier traffic must not leak into it
            alert_engine.reset()
        self._build()
        cdc_clients = []
        try:
            servers = self._harness["servers"]
            pdb = self._harness["pdb"]
            self._schedule = build_schedule(
                self.seed,
                self.sessions,
                self.ops_per_session,
                self.update_ratio,
                self.persons,
                self._harness["n_messages"],
                self._harness["first_name"],
            )
            digest = schedule_digest(self._schedule)
            kinds = {
                op.kind for ops in self._schedule for op in ops
            }
            spec = self.spec or default_slo_spec(
                self._harness["first_name"], kinds=kinds
            )
            slo_run = slo_engine.begin(spec)
            cdc_clients = self._attach_cdc(servers)
            watchdog = HealthWatchdog(servers[0])  # manual ticks
            http_ports = [s.http_port for s in servers[:2]]
            bin_ports = [s.binary_port for s in servers[:2]]
            clients = []
            for i in range(self.sessions):
                if i % 2 == 0:
                    clients.append(
                        _BinarySession(bin_ports, self.dbname, self.password)
                    )
                else:
                    clients.append(
                        _HttpSession(http_ports, self.dbname, self.password)
                    )
            total_ops = sum(len(ops) for ops in self._schedule)
            threads = [
                threading.Thread(
                    target=self._session_run,
                    args=(i, clients[i]),
                    name=f"sim-session-{i}",
                    daemon=True,  # a wedged session must not pin exit
                )
                for i in range(self.sessions)
            ]
            controller = threading.Thread(
                target=self._controller,
                args=(watchdog, total_ops),
                name="sim-controller",
                # daemon: even if the stop/join below is skipped by an
                # unexpected unwind, a ticking controller must never
                # pin the interpreter open at exit (the bench-headline
                # rc-124 failure mode)
                daemon=True,
            )
            try:
                with span(
                    "workload.run", seed=self.seed, sessions=self.sessions
                ):
                    controller.start()
                    if self.chaos is not None:
                        with fault.armed(self.chaos):
                            for t in threads:
                                t.start()
                            for t in threads:
                                t.join()
                    else:
                        for t in threads:
                            t.start()
                        for t in threads:
                            t.join()
                    settle = self._settle(watchdog)
            finally:
                # ANY unwind (a session crash, a harness interrupt)
                # must stop the controller before the cluster tears
                # down under it, and must close every client session
                with self._mu:
                    self._state["stop"] = True
                controller.join(timeout=10)
                for c in clients:
                    try:
                        c.close()
                    except Exception:
                        log.exception("session close failed")
            report = slo_engine.finish(
                slo_run,
                extra={
                    "seed": self.seed,
                    "schedule_digest": digest,
                },
            )
            with self._mu:
                counts = dict(self._counts)
                errors = dict(self._client_errors)
                cdc_events = self._state["cdc_events"]
            chaos_doc = None
            if self.chaos is not None:
                chaos_doc = {
                    "seed": self.chaos.seed,
                    "points": sorted(self.chaos.rules),
                    "fired": self.chaos.fired(),
                }
            return {
                "seed": self.seed,
                "sessions": self.sessions,
                "ops_per_session": self.ops_per_session,
                "update_ratio": self.update_ratio,
                "persons": self.persons,
                "schedule_digest": digest,
                "ops": counts,
                "client_errors": errors,
                "cdc": {
                    "consumers": len(cdc_clients),
                    "events": cdc_events,
                },
                "chaos": chaos_doc,
                "replica_outage": (
                    list(self.replica_outage)
                    if self.replica_outage
                    else None
                ),
                "settle": settle,
                "wall_s": round(time.perf_counter() - t_start, 3),
                "slo": report,
            }
        finally:
            self._detach_cdc(cdc_clients)
            self._teardown()

    # -- CDC consumers -------------------------------------------------------

    def _attach_cdc(self, servers) -> List:
        """Live changefeed consumers on both transports: binary push
        subscriptions counting deliveries, plus one HTTP long-poll
        consumer thread when two or more are requested."""
        from orientdb_tpu.client.remote import connect

        out = []

        def _on_event(_ev) -> None:
            with self._mu:
                self._state["cdc_events"] += 1

        n_binary = max(self.cdc_consumers - 1, 0)
        for _ in range(n_binary or (1 if self.cdc_consumers else 0)):
            c = connect(
                f"remote:127.0.0.1:{servers[0].binary_port}/{self.dbname}",
                "admin",
                self.password,
            )
            c.cdc_subscribe(_on_event)
            out.append(c)
        if self.cdc_consumers >= 2:
            stop = threading.Event()
            t = threading.Thread(
                target=self._http_cdc_poll,
                args=(servers[0].http_port, stop),
                name="sim-cdc-http",
            )
            t.start()
            out.append((stop, t))
        return out

    def _http_cdc_poll(self, port: int, stop: threading.Event) -> None:
        import base64

        cred = base64.b64encode(
            f"admin:{self.password}".encode()
        ).decode()
        since = None  # a fresh named cursor starts at the head
        while not stop.is_set():
            url = (
                f"http://127.0.0.1:{port}/changes/{self.dbname}"
                f"?cursor=sim-http&timeout=0.3&limit=200"
                + (f"&since={since}" if since is not None else "")
            )
            req = urllib.request.Request(
                url, headers={"Authorization": f"Basic {cred}"}
            )
            try:
                with fault.point("workload.http"):
                    with urllib.request.urlopen(req, timeout=10) as r:
                        doc = json.loads(r.read())
                since = max(
                    since or 0, int(doc.get("cursor", since or 0))
                )
                n = len(doc.get("events", ()))
                if n:
                    with self._mu:
                        self._state["cdc_events"] += n
            except Exception:
                # chaos may sever a poll; the loop resumes from its
                # cursor — exactly the consumer behavior CDC promises
                time.sleep(0.05)

    def _detach_cdc(self, cdc_clients) -> None:
        for c in cdc_clients:
            try:
                if isinstance(c, tuple):
                    stop, t = c
                    stop.set()
                    t.join(timeout=5)
                else:
                    c.close()
            except Exception:
                log.exception("cdc consumer teardown failed")


def default_chaos_plan(seed: int) -> FaultPlan:
    """The bench scenario's seeded fault schedule: enough consecutive
    forward-channel drops to trip the ``fwd:`` breaker mid-run (2PC
    prepares retry through them, then fail fast while it is open),
    dropped replica pulls (lag builds, then heals), and jittered
    binary-frame delays — all replayable by seed."""
    return (
        FaultPlan(seed)
        .at("fwd.req", "drop", times=8, after=1)
        .at("repl.pull", "drop", times=3)
        .at("bin.send", "delay", times=12, delay_s=0.002)
    )
