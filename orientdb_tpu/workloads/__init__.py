from orientdb_tpu.workloads.ldbc import IS_QUERIES, is_query  # noqa: F401

#: the closed-loop traffic simulator (workloads/driver) is imported
#: lazily by its users — importing it here would pull the whole
#: cluster/server stack into every `import orientdb_tpu.workloads`
