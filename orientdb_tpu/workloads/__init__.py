from orientdb_tpu.workloads.ldbc import IS_QUERIES, is_query  # noqa: F401
