"""Per-database ChangeFeed: resumable, filtered, backpressured delivery.

The feed is the durable-changefeed analog of the reference's
``OLiveQueryMonitor`` registry, rebuilt on the WAL (see the package
docstring). Key properties:

- **a cursor is just an LSN.** Consumers ack the LSN they have durably
  processed; restart resumes from the acked cursor with at-least-once
  delivery in LSN order. Named cursors persist in
  ``<durability_dir>/cdc-cursors.json`` (``atomic_write``), so they
  survive process restarts with the database.
- **the WAL is the source of truth, the queue an optimization.** Live
  events arrive via taps on every WAL-append site (local writes, tx
  commits, bulk flushes) and on the replication apply paths (a replica's
  feed sees the primary's entries with their SOURCE LSNs). Catch-up
  reads ``storage.durability.wal_entries_above`` — archives whose
  name-encoded max LSN is covered are skipped unread — overlaid with a
  bounded in-memory ring for entries the local WAL never logged
  (replication applies on a WAL-less or suppressed replica).
- **backpressure is explicit.** Per-consumer queues are bounded at
  ``config.cdc_queue_max``; a slow consumer either BLOCKS the producer
  (bounded by ``cdc_poll_timeout_s``, then sheds anyway) or is SHED:
  its queue drops and the next poll transparently catches up from the
  log — nothing is lost, only re-read. Shed counts and lag ride
  ``/metrics`` and ``/cluster/health``.
- **gaps are loud.** A cursor below the oldest retained LSN (checkpoint
  retired the covering archives, or a non-durable feed's ring rolled
  over) raises :class:`CdcGapError` — consumers must resync, never
  silently skip.

``LIVE SELECT`` monitors are callback-mode consumers of the same feed;
databases with no WAL get a hook-tap fallback (synthetic LSNs, not
resumable) so the embedded live-query surface keeps working unchanged.
"""

from __future__ import annotations

import json
import os
import threading
import time
import weakref
from collections import deque
from contextlib import contextmanager
from typing import Callable, Dict, List, Optional, Tuple

from orientdb_tpu.cdc.decode import EntryDecoder
from orientdb_tpu.utils.config import config
from orientdb_tpu.utils.logging import get_logger
from orientdb_tpu.utils.metrics import metrics

log = get_logger("cdc")

CURSOR_FILE = "cdc-cursors.json"

_HOOK_OPS = {
    "after_create": "create",
    "after_update": "update",
    "after_delete": "delete",
}

#: live feeds, for the process-wide cdc gauges (weak: a dropped database
#: must not be pinned by its feed's metrics)
_FEEDS: "weakref.WeakSet[ChangeFeed]" = weakref.WeakSet()


class CdcGapError(Exception):
    """The requested LSN range is no longer retained (archives retired
    by a checkpoint, or a non-durable ring rolled over): the consumer
    must resync from current state instead of silently skipping."""


#: gauge refresh throttle: the walk takes every consumer's lock, so the
#: write path must not pay it per commit (registration changes force it)
_PUB_INTERVAL_S = 0.5
_next_pub = 0.0


def _publish_gauges(force: bool = False) -> None:
    global _next_pub
    now = time.monotonic()
    if not force and now < _next_pub:
        return
    _next_pub = now + _PUB_INTERVAL_S
    consumers = 0
    depth = 0
    lag = 0
    for f in list(_FEEDS):
        s = f.quick_stats()
        consumers += s["consumers"]
        depth += s["queue_depth"]
        lag = max(lag, s["max_lag"])
    metrics.gauge("cdc.consumers", consumers)
    metrics.gauge("cdc.queue_depth", depth)
    metrics.gauge("cdc.lag_entries", lag)


# ---------------------------------------------------------------------------
# durable named cursors
# ---------------------------------------------------------------------------


class CursorStore:
    """Named consumer cursors. Durable (atomic_write to the database's
    durability directory) when the database is durable; in-memory
    otherwise. Acks only advance — a replayed stale ack cannot move a
    cursor backwards. Cursors idle past ``cdc_cursor_retention_s``
    EXPIRE at the next ack: they keep a tombstone, and a consumer
    reconnecting on one gets a loud :class:`CdcGapError` (resync or
    re-ack explicitly) — never a silent restart at head."""

    def __init__(self, db) -> None:
        self._db = db
        self._lock = threading.Lock()
        self._mem: Dict[str, Dict] = {}
        self._loaded = False

    def _path(self) -> Optional[str]:
        d = getattr(self._db, "_durability_dir", None)
        return os.path.join(d, CURSOR_FILE) if d else None

    def _load_locked(self) -> None:
        if self._loaded:
            return
        self._loaded = True
        p = self._path()
        if p and os.path.exists(p):
            try:
                with open(p, "rb") as f:
                    self._mem = json.loads(f.read())
            except Exception:
                log.warning("cdc cursor file %s unreadable; starting "
                            "empty", p, exc_info=True)
                self._mem = {}

    def get(self, name: str) -> Optional[int]:
        """The stored LSN, None for an unknown name — or a LOUD
        :class:`CdcGapError` for an expired one (the offline window may
        be gone; restarting at head silently would hide that)."""
        with self._lock:
            self._load_locked()
            cur = self._mem.get(name)
            if cur is None:
                return None
            if cur.get("expired"):
                raise CdcGapError(
                    f"cursor {name!r} expired after "
                    f"{config.cdc_cursor_retention_s:g}s idle at lsn "
                    f"{cur['lsn']}; resync (or re-ack a position "
                    "explicitly) to revive it"
                )
            return int(cur["lsn"])

    def ack(self, name: str, lsn: int) -> int:
        """Advance (never regress) a named cursor; returns the stored
        LSN. Persists durably when the database is durable. Acking an
        expired cursor revives it (an explicit new position)."""
        with self._lock:
            self._load_locked()
            prev = int(self._mem.get(name, {}).get("lsn", 0))
            now = time.time()
            self._mem[name] = {"lsn": max(prev, int(lsn)), "ts": now}
            retention = config.cdc_cursor_retention_s
            if retention > 0:
                for stale, cur in self._mem.items():
                    if (
                        stale != name
                        and not cur.get("expired")
                        and now - cur.get("ts", now) > retention
                    ):
                        cur["expired"] = True
            data = json.dumps(self._mem, separators=(",", ":")).encode()
            path = self._path()
            stored = int(self._mem[name]["lsn"])
            if path is not None:
                # persist INSIDE the lock: two concurrent acks racing
                # their atomic_writes outside it could land the staler
                # snapshot last and durably regress the other cursor
                from orientdb_tpu.storage.durability import atomic_write

                atomic_write(path, data)
        return stored

    def all(self) -> Dict[str, Dict]:
        with self._lock:
            self._load_locked()
            return {k: dict(v) for k, v in self._mem.items()}


# ---------------------------------------------------------------------------
# filtering (shared by consumers and the stateless HTTP transport)
# ---------------------------------------------------------------------------


def parse_where(where_sql: str, class_name: Optional[str] = None):
    """A WHERE snippet → predicate AST (evaluated by exec/eval like any
    LIVE SELECT filter)."""
    from orientdb_tpu.exec.engine import parse_cached

    stmt = parse_cached(
        f"SELECT FROM {class_name or 'V'} WHERE {where_sql}"
    )
    return stmt.where


def event_matches(db, ev: Dict, classes=None, where=None, doc=None) -> bool:
    """Per-class (subclass-aware) + WHERE filtering. Delete events skip
    the WHERE (the stored record no longer matches anything — same
    contract as LIVE SELECT); a WHERE that errors filters the event out
    rather than failing the feed."""
    if classes:
        cname = ev.get("class")
        if cname is None:
            return False
        cls = db.schema.get_class(cname) if db is not None else None
        if cls is None:
            if not any(cname.lower() == c.lower() for c in classes):
                return False
        elif not any(cls.is_subclass_of(c) for c in classes):
            return False
    if where is not None and ev.get("op") != "delete":
        from orientdb_tpu.exec.eval import EvalContext, evaluate, truthy

        if doc is None and db is not None:
            # prefer the LIVE record: synchronous tap deliveries run
            # before any later write, so it matches the event state and
            # supports @rid/@version/graph predicates exactly like the
            # old hook path did (catch-up reads may see newer state —
            # the documented predicate approximation)
            from orientdb_tpu.models.rid import RID

            try:
                doc = db._load_raw(RID.parse(ev["rid"]))
            except (ValueError, KeyError):
                doc = None
        if doc is None:
            from orientdb_tpu.models.record import Document
            from orientdb_tpu.models.rid import RID
            from orientdb_tpu.storage.durability import _dec

            rec = ev.get("record") or {}
            fields = {
                k: _dec(v) for k, v in rec.items() if not k.startswith("@")
            }
            doc = Document(ev.get("class") or "O", fields)
            doc._db = db
            try:
                doc.rid = RID.parse(ev["rid"])
            except (ValueError, KeyError):
                pass
            if rec.get("@version") is not None:
                doc.version = rec["@version"]
        try:
            if not truthy(evaluate(EvalContext(db, current=doc), where)):
                return False
        except Exception:
            return False
    return True


# ---------------------------------------------------------------------------
# consumers
# ---------------------------------------------------------------------------


class Consumer:
    """One subscription. Two delivery modes:

    - **queue mode** (default): events buffer in a bounded deque;
      ``poll(max_events, timeout)`` drains in LSN order, transparently
      switching to WAL catch-up after a resume or a shed;
    - **callback mode** (``callback=...``): events deliver inline from
      the write path — LIVE SELECT semantics (post-commit, in-process,
      not resumable)."""

    def __init__(
        self,
        feed: "ChangeFeed",
        token: int,
        name: Optional[str] = None,
        classes=None,
        where=None,
        callback: Optional[Callable] = None,
        policy: str = "shed",
        queue_max: Optional[int] = None,
        resume_lsn: int = 0,
        catchup: bool = False,
    ) -> None:
        if policy not in ("shed", "block"):
            raise ValueError(f"unknown backpressure policy {policy!r}")
        self.feed = feed
        self.token = token
        self.name = name
        self.classes = list(classes) if classes else None
        self.where = where
        self.callback = callback
        self.policy = policy
        self.queue_max = queue_max or config.cdc_queue_max
        #: where delivery resumes from (the registration-time cursor)
        self.resume_lsn = resume_lsn
        self.acked_lsn = resume_lsn
        self.delivered = 0
        self.shed_events = 0
        self.closed = False
        self._cv = threading.Condition()
        self._q: deque = deque()
        #: events at/below the floor were already handed to the consumer
        self._floor = resume_lsn
        #: serve the next poll from the log instead of the queue
        self._catchup = catchup

    # -- producer side ------------------------------------------------------

    def _passes(self, ev: Dict, doc=None) -> bool:
        return event_matches(
            self.feed.db, ev, classes=self.classes, where=self.where,
            doc=doc,
        )

    def _offer(self, events: List[Dict], doc=None) -> None:
        if self.callback is not None:
            for ev in events:
                if not self._passes(ev, doc=doc):
                    continue
                self.delivered += 1
                try:
                    self.callback(ev)
                except Exception:
                    # a raising subscriber must not break the write path
                    log.exception("cdc subscriber %s failed", self.token)
            return
        with self._cv:
            if self.closed or self._catchup:
                # catch-up mode re-reads this range from the log anyway
                return
            for ev in events:
                if ev["lsn"] <= self._floor:
                    continue
                if not self._passes(ev, doc=doc):
                    # the class/WHERE filter applies to LIVE deliveries
                    # exactly as to catch-up reads — a filtered
                    # subscription must not behave differently depending
                    # on whether it is caught up
                    continue
                if len(self._q) >= self.queue_max and self.policy == "block":
                    # bounded producer blocking: the writer waits for the
                    # consumer to drain, up to the poll timeout, then the
                    # shed path below takes over (a dead consumer must
                    # never wedge the write path forever)
                    deadline = time.monotonic() + config.cdc_poll_timeout_s
                    while (
                        len(self._q) >= self.queue_max
                        and not self.closed
                        and time.monotonic() < deadline
                    ):
                        self._cv.wait(deadline - time.monotonic())
                if len(self._q) >= self.queue_max:
                    # shed: drop the buffered window and fall back to the
                    # log — redeliverable from the cursor, so nothing is
                    # lost, only re-read (at-least-once)
                    self.shed_events += len(self._q) + 1
                    self._q.clear()
                    self._catchup = True
                    metrics.incr("cdc.shed")
                    self._cv.notify_all()
                    return
                self._q.append(ev)
            self._cv.notify_all()

    # -- consumer side ------------------------------------------------------

    def poll(
        self, max_events: int = 512, timeout: float = 0.0
    ) -> List[Dict]:
        """Next batch of events in LSN order (possibly empty after
        ``timeout``). Raises :class:`CdcGapError` when the resume point
        is no longer retained."""
        deadline = time.monotonic() + max(0.0, timeout)
        while True:
            with self._cv:
                if self.closed:
                    return []
                catchup = self._catchup
                if not catchup:
                    out: List[Dict] = []
                    while self._q:
                        if (
                            len(out) >= max_events
                            and self._q[0]["lsn"] != out[-1]["lsn"]
                        ):
                            break
                        # never split an atomic entry at the batch
                        # boundary: the floor advances per LSN, so a
                        # tx's tail events left behind would be dropped
                        # by the floor check on the next poll (the
                        # batch may overshoot max_events instead)
                        ev = self._q.popleft()
                        if ev["lsn"] <= self._floor:
                            continue  # already served by a catch-up read
                        out.append(ev)
                    if out:
                        self._floor = out[-1]["lsn"]
                        self.delivered += len(out)
                        self._cv.notify_all()  # wake a blocked producer
                        return out
                    left = deadline - time.monotonic()
                    if left <= 0:
                        return []
                    self._cv.wait(left)
                    continue
                floor = self._floor
            # catch-up OUTSIDE the condition: the log read must not block
            # producers offering to other consumers
            events, covered, head = self.feed.events_since(
                floor, limit=max_events
            )
            matched = [ev for ev in events if self._passes(ev)]
            with self._cv:
                if covered > self._floor:
                    self._floor = covered
                while self._q and self._q[0]["lsn"] <= self._floor:
                    self._q.popleft()
                # compare against the feed's CURRENT head, not the
                # scan-time one: a write committed after the scan was
                # dropped by _offer (catch-up mode) and must be picked
                # up by one more scan — clearing on the stale head
                # would strand it until the next shed
                if self._floor >= self.feed.head_lsn:
                    self._catchup = False
                self._cv.notify_all()
            if matched:
                self.delivered += len(matched)
                return matched
            if covered <= floor and time.monotonic() >= deadline:
                return []

    def ack(self, lsn: int) -> int:
        """The consumer has durably processed everything at/below
        ``lsn``; persists the named cursor when one is attached. The
        ack clamps to the feed head — a typo'd/hostile huge LSN must
        not pin the cursor past every future commit forever (acks
        never regress, so there would be no recovery path)."""
        lsn = min(int(lsn), self.feed.head_lsn)
        with self._cv:
            self.acked_lsn = max(self.acked_lsn, lsn)
            acked = self.acked_lsn
        if self.name:
            acked = self.feed.cursors.ack(self.name, acked)
        return acked

    def lag(self) -> Dict:
        with self._cv:
            depth = len(self._q)
            floor = self._floor
            acked = self.acked_lsn
        head = self.feed.head_lsn
        return {
            "token": self.token,
            "name": self.name,
            "classes": self.classes,
            "queue_depth": depth,
            "delivered_lsn": floor,
            "acked_lsn": acked,
            "lag_entries": max(0, head - floor),
            "unacked_entries": max(0, floor - acked),
            "shed_events": self.shed_events,
            "delivered": self.delivered,
            "policy": self.policy,
            "mode": "callback" if self.callback is not None else "queue",
        }

    def close(self) -> None:
        with self._cv:
            self.closed = True
            self._q.clear()
            self._cv.notify_all()


# ---------------------------------------------------------------------------
# the feed
# ---------------------------------------------------------------------------


class ChangeFeed:
    """One database's change plane. Create via :func:`feed_of` — the
    taps in the write/replication paths find the feed through the
    database, so construction order matters only for the no-WAL hook
    fallback (arm durability BEFORE the first subscription to get real,
    resumable LSNs)."""

    def __init__(self, db) -> None:
        self.db = db
        self._lock = threading.RLock()
        self._cv = threading.Condition(self._lock)
        self._decoder = EntryDecoder(db)
        self._consumers: Dict[int, Consumer] = {}
        self._next_token = 1
        self.cursors = CursorStore(db)
        wal = getattr(db, "_wal", None)
        #: newest LSN this feed knows about (tap or WAL tail; a WAL-less
        #: replica starts at its applied floor so a cursor below what
        #: this feed can serve raises a GAP instead of silence)
        self.head_lsn = (
            (wal.next_lsn - 1)
            if wal is not None
            else getattr(db, "_repl_applied_lsn", 0)
        )
        #: recent (lsn, events) pairs — INCLUDING empty event lists, so
        #: catch-up contiguity checks see protocol-only entries. Serves
        #: replica applies the local WAL never logged.
        self._ring: deque = deque(
            maxlen=max(4096, 4 * config.cdc_queue_max)
        )
        self._tl = threading.local()
        self._hook_token = None
        if wal is None:
            # no WAL to derive from: fall back to the hook tap with
            # synthetic LSNs (LIVE SELECT on a plain in-memory database).
            # Not resumable across restarts — durability brings that.
            self._hook_token = db.hooks.register(self._on_hook)
        _FEEDS.add(self)

    # -- taps ---------------------------------------------------------------

    @contextmanager
    def applying(self):
        """Mark this thread as applying a REPLICATION entry: local taps
        (WAL re-log of applied deletes, after-delete hooks fired by the
        apply's cascade) stay quiet — the apply tap delivers the entry
        once, with its SOURCE LSN."""
        self._tl.in_apply = True
        try:
            yield
        finally:
            self._tl.in_apply = False

    def on_entry(self, entry: Dict, source: str = "local") -> None:
        """The tap: one committed WAL entry (local append or replication
        apply). Decodes once, fans out to every consumer."""
        if source == "local" and getattr(self._tl, "in_apply", False):
            return
        events = self._decoder.decode(entry)
        lsn = entry.get("lsn", 0)
        with self._lock:
            self.head_lsn = max(self.head_lsn, lsn)
            self._ring.append((lsn, events))
            consumers = list(self._consumers.values())
            self._cv.notify_all()
        if events:
            metrics.incr("cdc.events", len(events))
        for c in consumers:
            c._offer(events)
        _publish_gauges()

    def _on_hook(self, event: str, doc) -> None:
        """Hook-tap fallback for WAL-less databases (synthetic LSNs)."""
        op = _HOOK_OPS.get(event)
        if op is None or getattr(self._tl, "in_apply", False):
            return
        record = doc.to_dict()
        from orientdb_tpu.models.record import Edge, Vertex

        # structural meta the WAL decode path stamps too (decode.py
        # _record_payload): edge endpoints + record kind, so the hook
        # fallback feeds the snapshot delta maintainer identically
        if isinstance(doc, Edge):
            record["@type"] = "edge"
            record["@out"] = str(doc.out_rid)
            record["@in"] = str(doc.in_rid)
        elif isinstance(doc, Vertex):
            record["@type"] = "vertex"
        with self._lock:
            lsn = self.head_lsn + 1
            self.head_lsn = lsn
            # deletes carry the PREIMAGE here — the hook tap still holds
            # the live document, unlike WAL decode where it is gone
            ev = {
                "lsn": lsn,
                "seq": 0,
                "op": op,
                "class": doc.class_name,
                "rid": str(doc.rid),
                "record": record,
                "durable": False,
            }
            self._ring.append((lsn, [ev]))
            consumers = list(self._consumers.values())
            self._cv.notify_all()
        metrics.incr("cdc.events")
        for c in consumers:
            c._offer([ev], doc=doc)
        _publish_gauges()

    # -- catch-up -----------------------------------------------------------

    def _wal_entries_above(self, lsn: int, limit: int) -> List[Dict]:
        """Like ``storage.durability.wal_entries_above`` but with an
        early stop: segments are LSN-ordered, so once ``limit`` entries
        past the cursor are collected, later segments need not be read
        or parsed — a consumer paging through a deep backlog pays
        O(segments-touched) per poll, not O(backlog)."""
        directory = getattr(self.db, "_durability_dir", None)
        if directory and os.path.isdir(directory):
            from orientdb_tpu.storage.durability import (
                WriteAheadLog,
                _wal_segments,
            )

            out: List[Dict] = []
            for seg in _wal_segments(directory):
                base = os.path.basename(seg)
                if base.startswith("wal-") and base.endswith(".log"):
                    try:
                        if int(base[4:-4]) <= lsn:
                            continue  # fully below the requested range
                    except ValueError:
                        pass
                out.extend(
                    e
                    for e in WriteAheadLog(seg).read_entries()
                    if e["lsn"] > lsn
                )
                if len(out) >= limit:
                    break
            out.sort(key=lambda e: e["lsn"])
            return out[:limit]
        wal = getattr(self.db, "_wal", None)
        if wal is not None:
            return [e for e in wal.read_entries() if e["lsn"] > lsn][
                :limit
            ]
        return []

    def events_since(
        self, lsn: int, limit: int = 1000
    ) -> Tuple[List[Dict], int, int]:
        """Decoded events with ``lsn >`` the cursor, LSN-ordered:
        ``(events, covered_lsn, head_lsn)``. ``covered_lsn`` is the last
        CONTIGUOUSLY available entry scanned (the caller's next cursor —
        it advances past protocol/DDL entries that decode to no events).
        Raises :class:`CdcGapError` when the range below the oldest
        retained entry was asked for."""
        from orientdb_tpu.obs.trace import span

        with span("cdc.catchup", lsn=lsn) as sp:
            entries = self._wal_entries_above(lsn, max(1, limit))
            dec = EntryDecoder(self.db)
            events: List[Dict] = []
            raw: Dict[int, List[Dict]] = {}
            for e in entries:
                raw[e["lsn"]] = dec.decode(e)
            with self._lock:
                ring = [
                    (rl, list(es)) for (rl, es) in self._ring if rl > lsn
                ]
                head = self.head_lsn
            for rl, es in ring:
                if rl not in raw:
                    raw[rl] = es
            covered = lsn
            taken = 0
            for rl in sorted(raw):
                if rl > covered + 1:
                    if covered == lsn:
                        raise CdcGapError(
                            f"changes in ({lsn}, {rl}) are no longer "
                            "retained (archives retired by a checkpoint "
                            "or ring rolled over); resync from current "
                            "state"
                        )
                    break  # later discontinuity: stop at the prefix
                if taken >= limit:
                    break  # the limit bounds ring-served entries too
                covered = rl
                events.extend(raw[rl])
                taken += 1
            if covered == lsn and not raw and head > lsn:
                raise CdcGapError(
                    f"changes above lsn {lsn} are no longer retained; "
                    "resync from current state"
                )
            events.sort(key=lambda ev: (ev["lsn"], ev.get("seq", 0)))
            sp.set("events", len(events))
            sp.set("covered", covered)
            return events, covered, head

    def wait_beyond(self, lsn: int, timeout: float) -> int:
        """Block until the head moves past ``lsn`` (long-poll); returns
        the current head."""
        deadline = time.monotonic() + max(0.0, timeout)
        with self._cv:
            while self.head_lsn <= lsn:
                left = deadline - time.monotonic()
                if left <= 0:
                    break
                self._cv.wait(left)
            return self.head_lsn

    # -- consumer lifecycle -------------------------------------------------

    def register(
        self,
        name: Optional[str] = None,
        classes=None,
        where=None,
        callback: Optional[Callable] = None,
        policy: str = "shed",
        queue_max: Optional[int] = None,
        since: Optional[int] = None,
    ) -> Consumer:
        """Subscribe. Resume point: explicit ``since`` wins, else the
        named cursor's stored LSN, else the current head (only new
        changes). Queue-mode consumers behind the head catch up from the
        log on their first poll."""
        resume = since
        if resume is None and name:
            resume = self.cursors.get(name)
        with self._lock:
            if resume is None:
                resume = self.head_lsn
            token = self._next_token
            self._next_token += 1
            c = Consumer(
                self,
                token,
                name=name,
                classes=classes,
                where=where,
                callback=callback,
                policy=policy,
                queue_max=queue_max,
                resume_lsn=resume,
                catchup=callback is None and resume < self.head_lsn,
            )
            self._consumers[token] = c
        _publish_gauges(force=True)
        return c

    def unregister(self, token: int) -> bool:
        with self._lock:
            c = self._consumers.pop(token, None)
        if c is None:
            return False
        c.close()
        _publish_gauges(force=True)
        return True

    def get(self, token: int) -> Optional[Consumer]:
        with self._lock:
            return self._consumers.get(token)

    def ack_cursor(self, name: str, lsn: int) -> int:
        """Stateless cursor ack (the HTTP transport's consumers hold no
        server-side object between polls). Clamped to the head — see
        :meth:`Consumer.ack`."""
        return self.cursors.ack(name, min(int(lsn), self.head_lsn))

    # -- observability ------------------------------------------------------

    def quick_stats(self) -> Dict:
        with self._lock:
            consumers = list(self._consumers.values())
            head = self.head_lsn
        depth = 0
        max_lag = 0
        for c in consumers:
            s = c.lag()
            depth += s["queue_depth"]
            max_lag = max(max_lag, s["lag_entries"])
        return {
            "consumers": len(consumers),
            "queue_depth": depth,
            "max_lag": max_lag,
            "head_lsn": head,
        }

    def stats(self) -> Dict:
        with self._lock:
            consumers = list(self._consumers.values())
            head = self.head_lsn
        return {
            "head_lsn": head,
            "consumers": [c.lag() for c in consumers],
            "cursors": self.cursors.all(),
        }

    def close(self) -> None:
        if self._hook_token is not None:
            self.db.hooks.unregister(self._hook_token)
            self._hook_token = None
        with self._lock:
            consumers = list(self._consumers.values())
            self._consumers.clear()
        for c in consumers:
            c.close()


# ---------------------------------------------------------------------------
# module helpers (the taps import these lazily)
# ---------------------------------------------------------------------------


def feed_of(db, create: bool = True) -> Optional[ChangeFeed]:
    """The database's feed, created on first use."""
    feed = db.__dict__.get("_cdc_feed")
    if feed is None and create:
        with db._lock:
            feed = db.__dict__.get("_cdc_feed")
            if feed is None:
                feed = db._cdc_feed = ChangeFeed(db)
    return feed


def live_feed(db) -> ChangeFeed:
    """Alias of :func:`feed_of` with creation forced (the LIVE SELECT
    entry point)."""
    return feed_of(db, create=True)


def notify_commit(db, entry: Dict, lsn: int) -> None:
    """WAL-append tap (database save/delete, tx commit, bulk flush):
    near-zero cost when no feed exists."""
    feed = db.__dict__.get("_cdc_feed")
    if feed is not None:
        feed.on_entry({**entry, "lsn": lsn}, source="local")


def notify_applied(db, entry: Dict) -> None:
    """Replication-apply tap: the entry carries its SOURCE LSN."""
    feed = db.__dict__.get("_cdc_feed")
    if feed is not None:
        feed.on_entry(entry, source="apply")


def apply_scope(db):
    """Context manager suppressing local taps while a replication entry
    applies (see :meth:`ChangeFeed.applying`); no-op without a feed."""
    feed = db.__dict__.get("_cdc_feed")
    if feed is not None:
        return feed.applying()

    @contextmanager
    def _noop():
        yield

    return _noop()


def feed_summary(db) -> Optional[Dict]:
    """Compact health-endpoint summary, or None when the database has
    no feed."""
    feed = db.__dict__.get("_cdc_feed")
    return None if feed is None else feed.quick_stats()
