"""Change-data-capture plane: WAL-derived, resumable changefeeds.

The reference's live-query hooks ([E] ``OLiveQueryHookV2`` /
``OLiveQueryMonitor``, SURVEY.md §2 "Live queries / hooks") fire on the
LOCAL write path only and deliver best-effort — a dropped session loses
events forever, and a replica applying the primary's WAL stream never
fires them at all. This package derives an ordered, RESUMABLE stream of
committed record changes from the WAL instead:

- ``cdc/decode.py`` — WAL entries (single ops and atomic ``tx``/``bulk``
  entries alike) → normalized change events
  ``{lsn, seq, op, class, rid, record, txid?}``;
- ``cdc/feed.py`` — per-database :class:`ChangeFeed` with durable named
  cursors (a cursor is just an LSN; catch-up reads ride
  ``storage.durability.wal_entries_above`` and skip covered archives),
  per-class/WHERE filtering via the predicate evaluator, and bounded
  per-consumer queues with shed-vs-block backpressure.

Transports live with their protocols: ``GET /changes/<db>`` long-poll in
``server/http_server.py``, ``cdc_subscribe``/``cdc_ack``/
``cdc_unsubscribe`` push in ``server/binary_server.py``, client resume
in ``client/remote.py``. ``LIVE SELECT`` (``exec/live.py``) is rebased
onto the feed, so live queries see replication-applied writes too.
"""

from orientdb_tpu.cdc.decode import EntryDecoder, decode_entry
from orientdb_tpu.cdc.feed import (
    CdcGapError,
    ChangeFeed,
    Consumer,
    feed_of,
    live_feed,
)

__all__ = [
    "CdcGapError",
    "ChangeFeed",
    "Consumer",
    "EntryDecoder",
    "decode_entry",
    "feed_of",
    "live_feed",
]
