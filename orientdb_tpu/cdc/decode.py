"""WAL entry → normalized change events.

The WAL (``storage/durability.py``) logs *logical* operations: single
``create``/``update``/``delete`` records, atomic ``tx``/``bulk``
containers, DDL, and 2PC protocol records. CDC consumers want a uniform
record-change vocabulary, so this module flattens each entry into zero
or more events::

    {"lsn": 7, "seq": 0, "op": "create", "class": "Person",
     "rid": "#9:0", "record": {...fields, "@rid", "@class", "@version"},
     "txid": "..."}        # only for 2PC-stamped tx entries
    {"lsn": 8, "seq": 1, "op": "delete", "class": "Person",
     "rid": "#9:0", "record": None, "tx": True}

- ``lsn`` is the WAL entry's LSN — the cursor unit. Ops inside one
  atomic ``tx``/``bulk`` entry share its LSN and are ordered by ``seq``
  (acking an LSN acknowledges the WHOLE entry; resume redelivers whole
  entries, which is the at-least-once contract).
- ``record`` values stay in the WAL's wire encoding (``{"@link": ...}``
  / ``{"@bytes": ...}``) so events ship over HTTP/binary unchanged;
  Python consumers decode with ``storage.durability._dec``.
- DDL and 2PC protocol entries decode to NO events — they still consume
  LSNs, so catch-up contiguity checks run on raw entries, not events.

Class attribution: ``create`` entries always carry their class; newer
``update``/``delete`` entries do too (stamped since this module exists).
For older entries the decoder falls back to classes learned from creates
earlier in the same stream, then to the live record.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Dict, List, Optional

#: ops that are record changes (everything else is schema/protocol)
CHANGE_OPS = frozenset({"create", "update", "delete"})

#: rid→class memory kept per decoder (bounded LRU; catch-up from LSN 0
#: learns every class from the creates it replays)
_CLASS_CACHE_MAX = 65536


def _record_payload(e: Dict) -> Dict:
    """Event ``record`` from a create/update entry: the WAL's
    wire-encoded fields plus the @-meta keys ``to_dict`` would carry.
    Edge entries additionally surface their endpoints (``@out``/
    ``@in``) and record kind (``@type``) so structural consumers — the
    snapshot delta maintainer (storage/deltas) foremost — can apply
    adjacency changes without a live-record lookup."""
    rec = dict(e.get("fields") or {})
    rec["@rid"] = e["rid"]
    if e.get("class") is not None:
        rec["@class"] = e["class"]
    if e.get("version") is not None:
        rec["@version"] = e["version"]
    if e.get("type") is not None:
        rec["@type"] = e["type"]
    if e.get("out") is not None:
        rec["@out"] = e["out"]
    if e.get("in") is not None:
        rec["@in"] = e["in"]
    return rec


class EntryDecoder:
    """Stateful decoder: one per feed (and one per catch-up scan), so
    class attribution survives across the entries it has seen."""

    def __init__(self, db=None) -> None:
        self.db = db
        self._classes: "OrderedDict[str, str]" = OrderedDict()

    def _learn(self, rid: str, class_name: Optional[str]) -> None:
        if class_name is None:
            return
        self._classes[rid] = class_name
        self._classes.move_to_end(rid)
        while len(self._classes) > _CLASS_CACHE_MAX:
            self._classes.popitem(last=False)

    def _class_of(self, e: Dict) -> Optional[str]:
        cname = e.get("class")
        if cname is not None:
            return cname
        cname = self._classes.get(e["rid"])
        if cname is not None:
            return cname
        if self.db is not None:
            from orientdb_tpu.models.rid import RID

            try:
                doc = self.db._load_raw(RID.parse(e["rid"]))
            except (ValueError, KeyError):
                doc = None
            if doc is not None:
                return doc.class_name
        return None

    def _one(
        self, e: Dict, lsn: int, seq: int, txid: Optional[str], in_tx: bool
    ) -> Optional[Dict]:
        op = e.get("op")
        if op not in CHANGE_OPS:
            return None
        rid = e.get("rid")
        if rid is None:
            return None
        if op == "create":
            self._learn(rid, e.get("class"))
        cname = self._class_of(e)
        if op == "delete":
            # newer delete entries carry the preimage (what consumers
            # invalidate on); pre-CDC logs yield None
            pre = e.get("preimage")
            record = None
            if pre is not None:
                record = dict(pre)
                record["@rid"] = rid
                if cname is not None:
                    record["@class"] = cname
        else:
            record = _record_payload(e)
        ev: Dict = {
            "lsn": lsn,
            "seq": seq,
            "op": op,
            "class": cname,
            "rid": rid,
            "record": record,
        }
        if txid:
            ev["txid"] = txid
        if in_tx:
            ev["tx"] = True
        if op == "delete":
            # the record is gone; forget its class AFTER attributing it
            self._classes.pop(rid, None)
        return ev

    def decode(self, entry: Dict) -> List[Dict]:
        """All change events of one WAL entry, in apply order."""
        lsn = entry.get("lsn", 0)
        op = entry.get("op")
        if op in ("tx", "bulk"):
            txid = entry.get("txid2pc")
            out: List[Dict] = []
            for i, sub in enumerate(entry.get("ops") or ()):
                ev = self._one(sub, lsn, i, txid, True)
                if ev is not None:
                    out.append(ev)
            return out
        ev = self._one(entry, lsn, 0, None, False)
        return [] if ev is None else [ev]


def decode_entry(entry: Dict, db=None) -> List[Dict]:
    """One-shot decode (unit-test / scripting convenience)."""
    return EntryDecoder(db).decode(entry)
