"""Server process entry point ([E] OServerMain / server.sh).

    python -m orientdb_tpu.server [--http-port N] [--binary-port N]
        [--admin-password PW] [--db NAME ...] [--demodb]

Ports default to ephemeral (printed on startup). With wal_enabled +
wal_dir configured (ORIENTTPU_WAL_ENABLED / ORIENTTPU_WAL_DIR), named
databases recover-or-create durably.
"""

from __future__ import annotations

import argparse
import signal
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="orientdb-tpu-server")
    ap.add_argument("--http-port", type=int, default=0)
    ap.add_argument("--binary-port", type=int, default=0)
    ap.add_argument("--admin-password", default="admin")
    ap.add_argument("--db", action="append", default=[], help="create/open a named database")
    ap.add_argument("--demodb", action="store_true", help="bundle the demodb sample database")
    args = ap.parse_args(argv)

    from orientdb_tpu.server.server import Server

    srv = Server(
        admin_password=args.admin_password,
        http_port=args.http_port,
        binary_port=args.binary_port,
    )
    for name in args.db:
        srv.create_database(name)
    if args.demodb:
        from orientdb_tpu.storage.ingest import generate_demodb
        from orientdb_tpu.storage.snapshot import attach_fresh_snapshot

        db = srv.create_database("demodb")
        if not db.schema.exists_class("Profiles"):
            # a durable demodb recovers from disk — don't regenerate
            generate_demodb(db)
        attach_fresh_snapshot(db)
    srv.startup()
    print(
        f"orientdb-tpu server up: http={srv.http_port} binary={srv.binary_port}",
        flush=True,
    )
    stop = []
    signal.signal(signal.SIGTERM, lambda *a: stop.append(1))
    signal.signal(signal.SIGINT, lambda *a: stop.append(1))
    try:
        while not stop:
            signal.pause()
    finally:
        srv.shutdown()
    return 0


if __name__ == "__main__":  # pragma: no cover - process entry
    sys.exit(main())
