"""HTTP/REST listener.

Analog of [E] ONetworkProtocolHttpDb (port 2480, SURVEY.md §2 "HTTP/REST"),
with the reference's REST shapes:

  GET    /listDatabases
  POST   /database/<db>                    create database
  GET    /database/<db>                    database info
  GET    /query/<db>/sql/<urlencoded sql>[/<limit>]
  POST   /command/<db>/sql                 body = sql text or {"command": ...}
  GET    /document/<db>/<rid>
  POST   /document/<db>                    body = JSON doc with @class
  PUT    /document/<db>/<rid>              body = JSON fields
  DELETE /document/<db>/<rid>
  GET    /class/<db>/<name>                schema info

All endpoints require HTTP Basic auth against the server's security
manager; query/command check read/write permission on the target.
"""

from __future__ import annotations

import base64
import json
import threading
import urllib.error
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple

from orientdb_tpu.models.record import Document, Edge, Vertex
from orientdb_tpu.models.rid import RID
from orientdb_tpu.models.security import (
    RES_DATABASE,
    RES_RECORD,
    SecurityError,
    classify_sql,
)
from orientdb_tpu.utils.logging import get_logger

log = get_logger("http")


def _doc_json(doc: Document) -> dict:
    out = dict(doc.to_dict())
    return out


class _DeferredHttpError(Exception):
    """An HTTP error decided inside a db-lock critical section but SENT
    after the lock releases (a stalled client socket must never block
    the database's write path)."""

    def __init__(self, code: int, msg: str) -> None:
        super().__init__(msg)
        self.code = code
        self.msg = msg


def _traced(fn):
    """Wrap an HTTP verb handler in a server span that CONTINUES the
    caller's trace when the request carries propagation headers
    (obs/propagation) — the receiving half of cross-node tracing for
    forwarding, 2PC phases, and quorum pushes. Also maintains the
    listener's in-flight depth (the admission-control signal)."""

    verb = fn.__name__[3:]

    def wrapper(self):
        import orientdb_tpu.obs.critpath as critpath
        from orientdb_tpu.obs.propagation import (
            continue_trace,
            extract_headers,
        )
        from orientdb_tpu.utils.metrics import metrics

        srv = self.server
        with srv.inflight_lock:
            srv.inflight += 1
            metrics.gauge("http.inflight", srv.inflight)
        path = urllib.parse.urlparse(self.path).path
        # the critical-path record covers the whole handler window:
        # route parse, admission, execution, response marshal+flush
        cp = critpath.begin_request("http")
        try:
            with continue_trace(
                f"http.{verb}", extract_headers(self.headers),
                path=path[:120],
            ):
                with critpath.active(cp):
                    return fn(self)
        finally:
            critpath.commit(cp)
            with srv.inflight_lock:
                srv.inflight -= 1
                metrics.gauge("http.inflight", srv.inflight)

    wrapper.__name__ = fn.__name__
    return wrapper


class _Handler(BaseHTTPRequestHandler):
    server_version = "orientdb-tpu/0.1"
    protocol_version = "HTTP/1.1"

    # -- plumbing -----------------------------------------------------------

    def log_message(self, fmt, *args):  # route through our logger
        log.debug("http: " + fmt, *args)

    def _send(self, code: int, payload) -> None:
        def enc(v):
            if isinstance(v, (bytes, bytearray)):  # blob payloads
                from orientdb_tpu.storage.durability import bytes_to_wire

                return bytes_to_wire(v)
            # anything else non-serializable stays a TypeError (a visible
            # 500), not silently stringified response data
            raise TypeError(f"not JSON-serializable: {type(v).__name__}")

        import orientdb_tpu.obs.critpath as critpath

        with critpath.segment("marshal"):
            body = json.dumps(payload, default=enc).encode()
        with critpath.segment("flush"):
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    def _error(self, code: int, msg: str) -> None:
        self._send(code, {"errors": [{"code": code, "content": msg}]})

    #: write routes exempt from admission shedding: a replication apply
    #: or a 2PC phase carries an already-made decision — refusing it
    #: would CREATE gaps / in-doubt transactions instead of load relief.
    #: A changefeed cursor ack is exempt too: acking lets a lagging
    #: consumer DRAIN, which reduces pressure rather than adding it.
    _ADMISSION_EXEMPT = frozenset({"replication", "tx2pc", "changes"})

    def _shed_write(self, head: str, dbname: Optional[str]) -> bool:
        """Admission control for write verbs: True when the request was
        shed (a 503 with Retry-After has been sent). Sheds on listener
        in-flight depth plus the shared db-pressure checks
        (server/admission: staged-2PC backlog, quorum-lost read-only
        degradation). A ``POST /command`` carrying a READ statement
        (SELECT/MATCH through the standard REST command path) is never
        shed — degradation means read-only, not read-nothing."""
        from orientdb_tpu.server.admission import db_pressure
        from orientdb_tpu.utils.config import config
        from orientdb_tpu.utils.metrics import metrics

        if head in self._ADMISSION_EXEMPT:
            return False
        if head == "command" and self._command_is_read():
            return False
        reason = None
        retry_after = config.retry_after_s
        maxin = config.http_max_inflight
        if maxin and self.server.inflight > maxin:
            reason = (
                f"in-flight depth {self.server.inflight} > {maxin}"
            )
        if reason is None:
            db = (
                self.server.ot_server.get_database(dbname)
                if dbname
                else None
            )
            reason, retry_after = db_pressure(db)
        if reason is None:
            return False
        metrics.incr("http.shed")
        body = json.dumps(
            {
                "errors": [{"code": 503, "content": reason}],
                "retry_after": retry_after,
            }
        ).encode()
        self.send_response(503)
        self.send_header("Content-Type", "application/json")
        self.send_header("Retry-After", f"{retry_after:g}")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)
        return True

    def _auth(self):
        hdr = self.headers.get("Authorization", "")
        if hdr.startswith("Basic "):
            try:
                user, _, pw = base64.b64decode(hdr[6:]).decode().partition(":")
            except Exception:
                user, pw = "", ""
            u = self.server.ot_server.security.authenticate(user, pw)
            if u is not None:
                return u
        elif hdr.startswith("Bearer "):
            # session tokens ([E] OTokenHandler): the credential carries
            # the identity, so the user field is empty — only a chain
            # with a TokenAuthenticator (server/auth.py) accepts these
            u = self.server.ot_server.security.authenticate("", hdr[7:])
            if u is not None:
                return u
        self.send_response(401)
        self.send_header("WWW-Authenticate", 'Basic realm="orientdb-tpu"')
        self.send_header("Content-Length", "0")
        self.end_headers()
        return None

    def _body(self) -> bytes:
        # _command_is_read may have consumed the stream already (the
        # request body can only be read once): serve the cached copy
        cached = self.__dict__.pop("_body_cache", None)
        if cached is not None:
            return cached
        n = int(self.headers.get("Content-Length") or 0)
        return self.rfile.read(n) if n else b""

    def _command_is_read(self) -> bool:
        """Classify a POST /command body before admission shedding: a
        READ statement rides through degradation. The body is cached
        for the route handler's own _body() call."""
        try:
            body = self._body()
            self._body_cache = body
            text = body.decode(errors="replace")
            try:
                sql = json.loads(text).get("command", text)
            except (json.JSONDecodeError, AttributeError):
                sql = text
            _resource, op = classify_sql(sql)
            return op == "read"
        except Exception:
            return False  # unclassifiable: treat as a write

    def _route(self) -> Tuple[str, list]:
        path = urllib.parse.urlparse(self.path).path
        parts = [urllib.parse.unquote(p) for p in path.split("/") if p]
        return (parts[0] if parts else ""), parts[1:]

    def _db(self, name: str):
        db = self.server.ot_server.get_database(name)
        if db is None:
            self._error(404, f"database '{name}' not found")
        return db

    def _batch_op(self, db, op):
        """One authorized /batch operation; runs inside the batch tx
        unless the payload opted out."""
        from orientdb_tpu.storage.durability import _dec

        typ = op.get("type")
        if typ == "script":
            rows = db.execute(
                op.get("language", "sql"),
                op["script"],
                op.get("parameters") or {},
            )
            return [r.to_dict() for r in rows]
        if typ == "cmd":
            return db.command(
                op.get("command", ""), op.get("parameters") or {}
            ).to_dicts()
        if typ == "c":
            rec = dict(op.get("record", {}))
            cls = rec.pop("@class", "O")
            kind = rec.pop("@type", None)
            fields = {
                k: _dec(v) for k, v in rec.items() if not k.startswith("@")
            }
            c = db.schema.get_class(cls)
            # kind dispatch mirrors the /document route: a record in a
            # vertex class must BE a Vertex or edges against it crash
            if (c is not None and c.is_vertex_type) or (
                c is None and kind == "vertex"
            ):
                doc = db.new_vertex(cls, **fields)
            else:
                doc = db.new_element(cls, **fields)
            return doc  # rendered post-commit (real rid)
        if typ == "u":
            rec = dict(op.get("record", {}))
            rid = RID.parse(rec.pop("@rid"))
            cur = db.load(rid)
            if cur is None:
                raise _DeferredHttpError(404, f"{rid} not found")
            for k, v in rec.items():
                if not k.startswith("@"):
                    cur.set(k, _dec(v))
            db.save(cur)
            return cur  # rendered post-commit
        # typ == "d" (validated upstream)
        rec = op.get("record", {})
        rid = RID.parse(rec.get("@rid") if isinstance(rec, dict) else rec)
        cur = db.load(rid)
        if cur is not None:
            db.delete(cur)
        return {"deleted": str(rid)}

    def _check_tx_ops(self, user, ops) -> None:
        """Authorize a tx op batch PER OP KIND, matching the single-op
        routes: a delete inside a tx needs the delete grant, etc."""
        _actions = {
            "create": "create",
            "edge": "create",
            "update": "update",
            "delete": "delete",
        }
        for action in sorted(
            {_actions.get(op.get("kind"), "update") for op in ops}
        ):
            self.server.ot_server.security.check(user, RES_RECORD, action)

    # -- verbs --------------------------------------------------------------

    @_traced
    def do_GET(self):  # noqa: N802
        head, rest = self._route()
        if head in ("studio", ""):
            # the Studio UI shell is public ([E] the studio webapp is
            # served pre-login too); every data call it makes carries
            # credentials and authenticates like any other client
            from orientdb_tpu.server.studio import STUDIO_HTML

            body = STUDIO_HTML.encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/html; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        user = self._auth()
        if user is None:
            return
        try:
            if head == "listDatabases":
                return self._send(
                    200, {"databases": sorted(self.server.ot_server.databases)}
                )
            if head == "metrics":
                # the [E] /profiler analog (SURVEY.md §5.1/§5.5):
                # Prometheus text exposition by default (scrapeable);
                # ?format=json or Accept: application/json keeps the
                # raw registry snapshot for programmatic readers
                q = urllib.parse.parse_qs(
                    urllib.parse.urlparse(self.path).query
                )
                accept = self.headers.get("Accept", "")
                if "json" in q.get("format", []) or (
                    "application/json" in accept
                ):
                    from orientdb_tpu.obs.registry import snapshot_all

                    # snapshot_all is the shape /cluster/metrics fans
                    # in per member — this endpoint must serve exactly
                    # it, or scraped members drift from the local one
                    return self._send(200, snapshot_all())
                from orientdb_tpu.obs.registry import render_prometheus

                body = render_prometheus().encode()
                self.send_response(200)
                self.send_header(
                    "Content-Type",
                    "text/plain; version=0.0.4; charset=utf-8",
                )
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            if head == "alerts" and not rest:
                # the alerting plane (obs/alerts, obs/watchdog): active
                # pending/firing alerts with exemplar trace ids, the
                # resolved history ring, and the watchdog summary. JSON
                # by default; ?format=prometheus serves the per-rule
                # orienttpu_alert_firing{rule=...} state gauges.
                from orientdb_tpu.obs.alerts import (
                    engine as alert_engine,
                    render_alerts_prometheus,
                )

                q = urllib.parse.parse_qs(
                    urllib.parse.urlparse(self.path).query
                )
                if "prometheus" in q.get("format", []):
                    body = render_alerts_prometheus().encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type",
                        "text/plain; version=0.0.4; charset=utf-8",
                    )
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                return self._send(200, alert_engine.report())
            if head == "slo" and not rest:
                # the SLO-verdict plane (obs/slo): the last traffic-
                # simulator run's machine-readable report — verdict,
                # per-class windowed quantiles vs targets, failures
                # naming their rule/key — or an explicit "none" marker
                # when no run has been judged in this process
                from orientdb_tpu.obs.slo import engine as slo_engine

                return self._send(200, slo_engine.report())
            if head == "stats" and rest == ["critpath"]:
                # the critical-path attribution plane (obs/critpath):
                # per-class and per-fingerprint segment breakdowns with
                # dominant bottleneck, the segment catalog, and recent
                # decompositions; ?k= bounds the fingerprint list
                from orientdb_tpu.obs.critpath import plane as cp_plane

                q = urllib.parse.parse_qs(
                    urllib.parse.urlparse(self.path).query
                )
                try:
                    k = int(q.get("k", ["20"])[0])
                except ValueError:
                    k = 20
                return self._send(200, cp_plane.report(k))
            if head == "stats" and rest in (["queries"], ["profile"]):
                # the query-statistics plane (obs/stats, obs/profile):
                # per-fingerprint cumulative cost, top-K by any column,
                # and the span-profile self-time tree. JSON by default
                # (an operator/API surface); ?format=prometheus serves
                # the promlint-clean per-fingerprint exposition.
                if rest == ["profile"]:
                    from orientdb_tpu.obs.profile import profiler

                    return self._send(200, profiler.profile())
                q = urllib.parse.parse_qs(
                    urllib.parse.urlparse(self.path).query
                )
                from orientdb_tpu.obs.stats import (
                    render_stats_prometheus,
                    resolve_sort_column,
                    stats,
                )

                if "prometheus" in q.get("format", []):
                    body = render_stats_prometheus().encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type",
                        "text/plain; version=0.0.4; charset=utf-8",
                    )
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                try:
                    k = int(q.get("k", ["50"])[0])
                except ValueError:
                    k = 50
                by = resolve_sort_column(q.get("by", ["total_s"])[0])
                return self._send(
                    200,
                    {
                        "by": by,
                        "queries": stats.top(k, by=by),
                    },
                )
            if head == "cluster" and rest in (["health"], ["metrics"]):
                # fleet-level aggregation plane (obs/cluster_view):
                # per-member liveness/role/lag/in-doubt, and the fan-in
                # exposition that merges every member's registries into
                # one scrape labeled by member
                from orientdb_tpu.obs.cluster_view import (
                    cluster_health,
                    cluster_metrics_json,
                    cluster_metrics_text,
                )

                if rest == ["health"]:
                    return self._send(
                        200, cluster_health(self.server.ot_server)
                    )
                q = urllib.parse.parse_qs(
                    urllib.parse.urlparse(self.path).query
                )
                if "json" in q.get("format", []) or (
                    "application/json" in self.headers.get("Accept", "")
                ):
                    return self._send(
                        200, cluster_metrics_json(self.server.ot_server)
                    )
                body = cluster_metrics_text(self.server.ot_server).encode()
                self.send_response(200)
                self.send_header(
                    "Content-Type",
                    "text/plain; version=0.0.4; charset=utf-8",
                )
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            if head == "debug" and rest == ["timeline"]:
                # the dispatch flight recorder (obs/timeline): the
                # recent window as Chrome-trace JSON — load it straight
                # into Perfetto (ui.perfetto.dev) or chrome://tracing.
                # Admin-only (records carry fingerprints + trace ids,
                # like the bundle); ?window=<s> bounds it (default
                # config.timeline_window_s), ?format=json serves raw
                # records + the overlap report instead.
                self.server.ot_server.security.check(
                    user, "server.debug", "read"
                )
                from orientdb_tpu.obs.timeline import recorder
                from orientdb_tpu.utils.config import config

                q = urllib.parse.parse_qs(
                    urllib.parse.urlparse(self.path).query
                )
                try:
                    window = float(q.get("window", ["0"])[0])
                except ValueError:
                    window = 0.0
                if window <= 0:
                    window = config.timeline_window_s
                if "json" in q.get("format", []):
                    return self._send(
                        200,
                        {
                            "overlap": recorder.overlap(window_s=window),
                            "records": recorder.records(
                                window_s=window, limit=500
                            ),
                        },
                    )
                return self._send(200, recorder.chrome_trace(window_s=window))
            if head == "debug" and rest == ["memory"]:
                # the device-memory ledger (obs/memledger): per-owner
                # rollup, watermark ring, live reconciliation vs
                # jax.live_arrays, outstanding/stale epoch leases, and
                # the last tier refusal. Admin-only (owner ids name
                # snapshots and plans). ?reconcile=0 skips the live
                # pass and serves the last cached report.
                self.server.ot_server.security.check(
                    user, "server.debug", "read"
                )
                from orientdb_tpu.obs.memledger import memledger

                q = urllib.parse.parse_qs(
                    urllib.parse.urlparse(self.path).query
                )
                rec = q.get("reconcile", ["1"])[0] != "0"
                return self._send(200, memledger.report(reconcile=rec))
            if head == "debug" and rest == ["fsck"]:
                # durable-state fsck (tools/fsck): per-database WAL
                # CRC chains + segment continuity, checkpoint/delta/
                # epoch content hashes, coldstore tails. Admin-only
                # (reports name on-disk paths). ?dir=<path> checks an
                # explicit tree instead of the server databases'
                # durability directories.
                self.server.ot_server.security.check(
                    user, "server.debug", "read"
                )
                from orientdb_tpu.tools.fsck import fsck_tree

                q = urllib.parse.parse_qs(
                    urllib.parse.urlparse(self.path).query
                )
                explicit = q.get("dir", [None])[0]
                if explicit:
                    dirs = {"": explicit}
                else:
                    dirs = {
                        name: d
                        for name, db in (
                            self.server.ot_server.databases.items()
                        )
                        if (d := getattr(db, "_durability_dir", None))
                    }
                reports = {
                    name or "tree": fsck_tree(d)
                    for name, d in dirs.items()
                }
                return self._send(
                    200,
                    {
                        "clean": all(
                            r["clean"] for r in reports.values()
                        ),
                        "reports": reports,
                    },
                )
            if head == "debug" and rest == ["bundle"]:
                # the flight-recorder bundle (obs/bundle): recent
                # cross-node traces assembled by trace_id, slowlog,
                # metrics snapshot, and in-doubt 2PC state — admin-only
                # (traces carry SQL text, like the replication stream
                # carries records)
                self.server.ot_server.security.check(
                    user, "server.debug", "read"
                )
                from orientdb_tpu.obs.bundle import debug_bundle

                srv = self.server.ot_server
                return self._send(
                    200,
                    debug_bundle(
                        dbs=list(srv.databases.values()),
                        member=srv.name,
                        cluster=getattr(srv, "cluster", None),
                    ),
                )
            if head == "changes" and len(rest) == 1:
                # resumable changefeed pull (orientdb_tpu/cdc): WAL-
                # derived change events with lsn > the cursor, long-poll
                # when caught up. ?since=<lsn> (explicit cursor) or
                # ?cursor=<name> (durable named cursor; since overrides);
                # ?timeout= bounds the long-poll, ?limit= the batch,
                # ?class=A,B filters (subclass-aware), ?where= adds a
                # predicate. A pruned range answers 410: resync, never a
                # silent gap.
                db = self._db(rest[0])
                if db is None:
                    return
                self.server.ot_server.security.check(user, RES_RECORD, "read")
                import time as _time

                from orientdb_tpu.cdc.feed import (
                    CdcGapError,
                    event_matches,
                    feed_of,
                    parse_where,
                )
                from orientdb_tpu.chaos import fault
                from orientdb_tpu.obs.trace import span
                from orientdb_tpu.utils.config import config

                q = urllib.parse.parse_qs(
                    urllib.parse.urlparse(self.path).query
                )
                feed = feed_of(db)
                cursor = q.get("cursor", [None])[0]
                if "since" in q:
                    since = int(q["since"][0])
                elif cursor:
                    # first contact with a NEW named cursor starts at
                    # the head (new changes only) — same semantics as
                    # binary cdc_subscribe, and it cannot 410 on a
                    # database whose early archives were retired.
                    # Explicit ?since=0 still requests a full replay.
                    # An EXPIRED cursor answers 410 loudly instead.
                    try:
                        stored = feed.cursors.get(cursor)
                    except CdcGapError as e:
                        return self._error(410, str(e))
                    since = feed.head_lsn if stored is None else stored
                else:
                    since = 0
                timeout = min(
                    float(q.get("timeout", [config.cdc_poll_timeout_s])[0]),
                    60.0,
                )
                limit = max(1, int(q.get("limit", ["1000"])[0]))
                classes = [
                    c for c in ",".join(q.get("class", [])).split(",") if c
                ] or None
                where = q.get("where", [None])[0]
                where_ast = (
                    parse_where(where, classes[0] if classes else None)
                    if where
                    else None
                )
                deadline = _time.monotonic() + timeout
                while True:
                    try:
                        events, covered, head_lsn = feed.events_since(
                            since, limit=limit
                        )
                    except CdcGapError as e:
                        return self._error(410, str(e))
                    events = [
                        ev
                        for ev in events
                        if event_matches(db, ev, classes, where_ast)
                    ]
                    left = deadline - _time.monotonic()
                    if events or covered > since or left <= 0:
                        break
                    feed.wait_beyond(since, left)
                with span(
                    "cdc.push", transport="http", events=len(events)
                ), fault.point("cdc.push"):
                    return self._send(
                        200,
                        {
                            "events": events,
                            "cursor": covered,
                            "head": head_lsn,
                        },
                    )
            if head == "replication" and len(rest) == 2:
                # WAL shipping for replicas ([E] the distributed delta-sync
                # request); admin-only — the stream exposes every record
                # "server.replication" falls outside reader/writer's
                # per-resource grants; only admin's '*' covers it
                self.server.ot_server.security.check(
                    user, "server.replication", "read"
                )
                db = self._db(rest[0])
                if db is None:
                    return
                from orientdb_tpu.parallel.replication import entries_after

                # exact=1: the replica asserts it holds state-as-of the
                # requested LSN exactly (it restored our checkpoint), so
                # a base-state checkpoint must not be re-served
                q = urllib.parse.parse_qs(
                    urllib.parse.urlparse(self.path).query
                )
                return self._send(
                    200,
                    entries_after(
                        db, int(rest[1]), exact_ok="exact" in q
                    ),
                )
            if head == "database" and rest:
                db = self._db(rest[0])
                if db is None:
                    return
                classes = [
                    {
                        "name": c.name,
                        "records": db.count_class(c.name, polymorphic=False)
                        if not c.abstract
                        else 0,
                    }
                    for c in db.schema.classes()
                ]
                return self._send(200, {"server": {}, "classes": classes})
            if head == "query" and len(rest) >= 3 and rest[1] == "sql":
                db = self._db(rest[0])
                if db is None:
                    return
                self.server.ot_server.security.check(user, RES_RECORD, "read")
                sql = rest[2]
                limit = int(rest[3]) if len(rest) > 3 else None
                # singles ride the cross-session lane path (server/
                # coalesce.py) exactly like binary `query` ops do:
                # concurrent HTTP sessions' queries merge into one
                # micro-batch instead of each paying the lone-dispatch
                # tunnel round trip
                rows, _engine = self.server.ot_server.coalescer.submit(
                    db, sql, None
                )
                if limit is not None:
                    rows = rows[:limit]
                return self._send(200, {"result": rows})
            if head == "document" and len(rest) == 2:
                db = self._db(rest[0])
                if db is None:
                    return
                self.server.ot_server.security.check(user, RES_RECORD, "read")
                doc = db.load(RID.parse(rest[1]))
                if doc is None:
                    return self._error(404, f"record {rest[1]} not found")
                return self._send(200, _doc_json(doc))
            if head == "class" and len(rest) == 2:
                db = self._db(rest[0])
                if db is None:
                    return
                cls = db.schema.get_class(rest[1])
                if cls is None:
                    return self._error(404, f"class '{rest[1]}' not found")
                return self._send(
                    200,
                    {
                        "name": cls.name,
                        "superClasses": [s.name for s in cls.superclasses],
                        "abstract": cls.abstract,
                        "properties": [
                            {"name": p.name, "type": p.type.name}
                            for p in cls.properties.values()
                        ],
                        "records": 0
                        if cls.abstract
                        else db.count_class(cls.name, polymorphic=False),
                    },
                )
            return self._error(404, f"no route for GET /{head}")
        except SecurityError as e:
            return self._error(403, str(e))
        except Exception as e:
            return self._error(500, f"{type(e).__name__}: {e}")

    @_traced
    def do_POST(self):  # noqa: N802
        head, rest = self._route()
        # auth FIRST: an unauthenticated client must see its 401 (and
        # must not get its body parsed) even while the listener sheds
        user = self._auth()
        if user is None:
            return
        if self._shed_write(head, rest[0] if rest else None):
            return
        try:
            if head == "database" and rest:
                self.server.ot_server.security.check(user, RES_DATABASE, "create")
                db = self.server.ot_server.create_database(rest[0])
                return self._send(200, {"created": db.name})
            if head == "command" and len(rest) >= 2 and rest[1] == "sql":
                db = self._db(rest[0])
                if db is None:
                    return
                body = self._body().decode()
                try:
                    sql = json.loads(body).get("command", body)
                except (json.JSONDecodeError, AttributeError):
                    sql = body
                resource, op = classify_sql(sql)
                self.server.ot_server.security.check(user, resource, op)
                rows = db.command(sql).to_dicts()
                return self._send(200, {"result": rows})
            if head == "changes" and len(rest) == 2 and rest[1] == "ack":
                # persist a named changefeed cursor: the consumer has
                # durably processed everything at/below lsn — restart
                # resumes there (at-least-once; acks never regress)
                db = self._db(rest[0])
                if db is None:
                    return
                self.server.ot_server.security.check(user, RES_RECORD, "read")
                from orientdb_tpu.cdc.feed import feed_of

                payload = json.loads(self._body() or b"{}")
                name = payload.get("cursor")
                if not name:
                    return self._error(400, "cursor name required")
                stored = feed_of(db).ack_cursor(
                    name, int(payload.get("lsn", 0))
                )
                return self._send(200, {"cursor": name, "lsn": stored})
            if head == "replication" and len(rest) == 2 and rest[1] == "apply":
                # quorum-push apply ([E] the distributed task execution
                # endpoint); admin-only like the pull stream
                self.server.ot_server.security.check(
                    user, "server.replication", "update"
                )
                db = self._db(rest[0])
                if db is None:
                    return
                from orientdb_tpu.parallel.replication import (
                    apply_pushed_entries,
                )

                payload = json.loads(self._body() or b"{}")
                floor = apply_pushed_entries(
                    db,
                    payload.get("entries", ()),
                    payload.get("term"),
                    checkpoint=payload.get("checkpoint"),
                )
                return self._send(200, {"applied_lsn": floor})
            if head == "document" and len(rest) == 1:
                db = self._db(rest[0])
                if db is None:
                    return
                self.server.ot_server.security.check(user, RES_RECORD, "create")
                from orientdb_tpu.storage.durability import _dec

                payload = json.loads(self._body() or b"{}")
                cls = payload.pop("@class", "O")
                # forwarded creates carry the record kind so an unknown
                # class is auto-created with the RIGHT type (a replica's
                # Vertex must not become a plain document class here)
                kind = payload.pop("@type", None)
                payload = {
                    k: _dec(v)
                    for k, v in payload.items()
                    if not k.startswith("@")
                }
                c = db.schema.get_class(cls)
                if kind == "blob" or cls == "OBlob":
                    doc = db.new_blob(payload.pop("data", b"") or b"")
                    if payload:
                        for k, v in payload.items():
                            doc.set(k, v)
                        db.save(doc)
                elif (c is not None and c.is_vertex_type) or (
                    c is None and kind == "vertex"
                ):
                    doc = db.new_vertex(cls, **payload)
                else:
                    doc = db.new_element(cls, **payload)
                return self._send(201, _doc_json(doc))
            if head == "batch" and len(rest) == 1:
                # [E] the REST /batch command: operations = script /
                # cmd / c(reate) / u(pdate) / d(elete), one session.
                # Transactional by default like the reference —
                # "transaction": false opts out (scripts managing their
                # OWN tx must opt out; BEGIN inside the wrapped tx
                # raises).
                db = self._db(rest[0])
                if db is None:
                    return
                payload = json.loads(self._body() or b"{}")
                ops = payload.get("operations", ())
                # authorize EVERYTHING up front: batch scripts carry
                # arbitrary statements, so each one classifies like a
                # single command would (DDL needs schema, GRANT needs
                # security, …) — no escalation through /batch
                from orientdb_tpu.exec.script import script_permissions

                for op in ops:
                    typ = op.get("type")
                    if typ == "script":
                        script = op.get("script", "")
                        if isinstance(script, list):
                            script = ";\n".join(script)
                        op["script"] = script
                        for resource, action in sorted(
                            script_permissions(script)
                        ):
                            self.server.ot_server.security.check(
                                user, resource, action
                            )
                    elif typ == "cmd":
                        resource, action = classify_sql(
                            op.get("command", "")
                        )
                        self.server.ot_server.security.check(
                            user, resource, action
                        )
                    elif typ in ("c", "u", "d"):
                        self.server.ot_server.security.check(
                            user,
                            RES_RECORD,
                            {"c": "create", "u": "update", "d": "delete"}[
                                typ
                            ],
                        )
                        if typ in ("u", "d"):
                            rec = op.get("record", {})
                            if not (
                                isinstance(rec, str)
                                or (isinstance(rec, dict) and "@rid" in rec)
                            ):
                                return self._error(
                                    400, f"batch '{typ}' op needs @rid"
                                )
                    else:
                        return self._error(
                            400, f"unknown batch op type {typ!r}"
                        )
                transactional = payload.get("transaction", True)
                if transactional:
                    db.begin()
                try:
                    results = [self._batch_op(db, op) for op in ops]
                    if transactional:
                        db.commit()
                except BaseException:
                    if transactional and db.tx is not None:
                        db.tx.rollback()
                    raise
                # created/updated docs render AFTER commit so their
                # rids are the adopted real ones, not tx temps
                rendered = [
                    _doc_json(r) if isinstance(r, Document) else r
                    for r in results
                ]
                return self._send(200, {"result": rendered})
            if head == "tx" and len(rest) == 1:
                # forwarded-transaction execution ([E] the distributed tx
                # task batch, SURVEY.md:126): the non-owner's buffered ops
                # run here inside ONE local transaction — all-or-nothing,
                # MVCC-checked against the forwarder's base versions
                db = self._db(rest[0])
                if db is None:
                    return
                from orientdb_tpu.parallel.twophase import execute_tx_ops

                payload = json.loads(self._body() or b"{}")
                ops = payload.get("ops", [])
                self._check_tx_ops(user, ops)
                results, _tm = execute_tx_ops(db, ops)
                return self._send(200, {"results": results})
            if head == "tx2pc" and len(rest) == 1:
                # 2-phase distributed tx participant ([E] SURVEY.md:126):
                # prepare validates + locks, commit executes the staged
                # batch as one local tx, abort releases — all keyed by
                # the coordinator's txid (parallel/twophase)
                db = self._db(rest[0])
                if db is None:
                    return
                from orientdb_tpu.parallel.twophase import (
                    TwoPhaseError,
                    get_registry,
                )

                payload = json.loads(self._body() or b"{}")
                phase = payload.get("phase")
                txid = payload.get("txid")
                if not txid:
                    return self._error(400, "txid required")
                reg = get_registry(db)
                if phase == "prepare":
                    ops = payload.get("ops", [])
                    self._check_tx_ops(user, ops)
                    reg.prepare(
                        txid, ops, ttl=float(payload.get("ttl", 60.0))
                    )
                    return self._send(200, {"prepared": txid})
                if phase == "commit":
                    self.server.ot_server.security.check(
                        user, RES_RECORD, "update"
                    )
                    try:
                        results, temp_map = reg.commit(
                            txid, rid_map=payload.get("rid_map")
                        )
                    except TwoPhaseError as e:
                        # expired/unknown: the coordinator maps 410 to
                        # in-doubt (participant presumed abort)
                        return self._error(410, str(e))
                    return self._send(
                        200, {"results": results, "temp_map": temp_map}
                    )
                if phase == "abort":
                    self.server.ot_server.security.check(
                        user, RES_RECORD, "update"
                    )
                    reg.abort(txid)
                    return self._send(200, {"aborted": txid})
                return self._error(400, f"unknown 2pc phase {phase!r}")
            if head == "edge" and len(rest) == 1:
                # forwarded edge create (parallel/forwarding): a typed
                # route instead of SQL so field values round-trip exactly
                db = self._db(rest[0])
                if db is None:
                    return
                self.server.ot_server.security.check(user, RES_RECORD, "create")
                from orientdb_tpu.storage.durability import _dec

                payload = json.loads(self._body() or b"{}")
                src = db.load(RID.parse(payload["from"]))
                dst = db.load(RID.parse(payload["to"]))
                if not isinstance(src, Vertex) or not isinstance(dst, Vertex):
                    return self._error(404, "edge endpoint not found")
                doc = db.new_edge(
                    payload["@class"], src, dst,
                    **{k: _dec(v) for k, v in payload.get("fields", {}).items()},
                )
                return self._send(201, _doc_json(doc))
            return self._error(404, f"no route for POST /{head}")
        except SecurityError as e:
            return self._error(403, str(e))
        except Exception as e:
            from orientdb_tpu.models.database import (
                ConcurrentModificationError,
            )

            from orientdb_tpu.parallel.twophase import TxOpError

            if isinstance(e, _DeferredHttpError):
                return self._error(e.code, e.msg)
            if isinstance(e, TxOpError):
                return self._error(e.code, e.msg)
            if isinstance(e, ConcurrentModificationError):
                # a forwarded tx losing an MVCC race maps back to the
                # forwarder's ConcurrentModificationError, not a 500
                return self._error(409, str(e))
            return self._error(500, f"{type(e).__name__}: {e}")

    @_traced
    def do_PUT(self):  # noqa: N802
        head, rest = self._route()
        # auth FIRST: an unauthenticated client must see its 401 (and
        # must not get its body parsed) even while the listener sheds
        user = self._auth()
        if user is None:
            return
        if self._shed_write(head, rest[0] if rest else None):
            return
        try:
            if head == "document" and len(rest) == 2:
                db = self._db(rest[0])
                if db is None:
                    return
                self.server.ot_server.security.check(user, RES_RECORD, "update")
                payload = json.loads(self._body() or b"{}")
                base = payload.get("@base_version")
                from orientdb_tpu.storage.durability import _dec

                if db._write_owner is not None:
                    # this node was demoted after the forwarder read its
                    # (now stale) ownership map: chain-forward to the
                    # real owner WITHOUT touching the local store and
                    # without holding db._lock across the network call
                    fields = {
                        k: _dec(v)
                        for k, v in payload.items()
                        if not k.startswith("@")
                    }
                    resp = db._write_owner.update(
                        RID.parse(rest[1]),
                        fields,
                        base_version=int(base) if base is not None else None,
                        replace=bool(payload.get("@replace")),
                    )
                    return self._send(200, resp)
                # Version check, field mutation, and save form ONE MVCC
                # critical section: two racing forwarded updates with the
                # same base version must resolve exactly like two racing
                # local saves (one wins, one 409s). _quorum_deferral sits
                # OUTSIDE the lock so replica pushes still flush after it
                # is released.
                err = None  # (code, message) — SENT OUTSIDE the lock: a
                # stalled client's socket must never block the database's
                # write path (the success path serializes inside and
                # sends outside for the same reason)
                with db._quorum_deferral():
                    with db._lock:
                        doc = db.load(RID.parse(rest[1]))
                        if doc is None:
                            err = (404, f"record {rest[1]} not found")
                        elif base is not None and int(base) != doc.version:
                            # forwarded saves carry their base version:
                            # MVCC must hold across the forward exactly
                            # as it does locally
                            err = (
                                409,
                                f"{doc.rid}: stored v{doc.version}"
                                f" != base v{base}",
                            )
                        if err is not None:
                            raise _DeferredHttpError(*err)
                        # mutate the LIVE stored object only with a way
                        # back: a failed save (mandatory/unique/hook
                        # violation) must not leave the owner's record
                        # torn with no version bump or WAL entry. The
                        # undo applies ONLY when the save did not take
                        # effect (mutation_epoch unmoved — it bumps
                        # right before the WAL append): after the WAL
                        # has the entry, reverting the live record
                        # would diverge it from its own durable log,
                        # so the error propagates over the new state
                        # exactly like a local save whose after-hook
                        # raised.
                        undo_fields = doc.fields()
                        undo_version = doc.version
                        epoch0 = db.mutation_epoch
                        try:
                            if payload.get("@replace"):
                                # forwarded full save: fields absent from
                                # the payload were removed at the
                                # forwarder — clear them so
                                # remove_field() propagates
                                sent = {
                                    k
                                    for k in payload
                                    if not k.startswith("@")
                                }
                                for k in list(doc.fields()):
                                    if k not in sent:
                                        doc.remove_field(k)
                            for k, v in payload.items():
                                if not k.startswith("@"):
                                    doc.set(k, _dec(v))
                            db.save(doc)
                        except Exception:
                            if db.mutation_epoch == epoch0:
                                doc._fields = undo_fields
                                doc.version = undo_version
                            raise
                        # serialize INSIDE the critical section: after
                        # the lock drops a later writer could bump the
                        # shared object and the forwarder would adopt
                        # that version number over ITS OWN field values
                        body = _doc_json(doc)
                return self._send(200, body)
            return self._error(404, f"no route for PUT /{head}")
        except SecurityError as e:
            return self._error(403, str(e))
        except Exception as e:
            # MVCC conflicts keep their status across a chain-forward:
            # the originating forwarder translates 409 back into
            # ConcurrentModificationError for its caller — a generic 500
            # would break retry-with-fresh-version loops during the
            # demotion window. Other owner-side HTTP errors (e.g. 404)
            # pass their code through for the same reason.
            from orientdb_tpu.models.database import (
                ConcurrentModificationError,
            )

            if isinstance(e, _DeferredHttpError):
                return self._error(e.code, e.msg)
            if isinstance(e, ConcurrentModificationError):
                return self._error(409, str(e))
            if isinstance(e, urllib.error.HTTPError):
                return self._error(
                    e.code, e.read().decode(errors="replace") or str(e)
                )
            return self._error(500, f"{type(e).__name__}: {e}")

    @_traced
    def do_DELETE(self):  # noqa: N802
        head, rest = self._route()
        # auth FIRST: an unauthenticated client must see its 401 (and
        # must not get its body parsed) even while the listener sheds
        user = self._auth()
        if user is None:
            return
        if self._shed_write(head, rest[0] if rest else None):
            return
        try:
            if head == "document" and len(rest) == 2:
                db = self._db(rest[0])
                if db is None:
                    return
                self.server.ot_server.security.check(user, RES_RECORD, "delete")
                doc = db.load(RID.parse(rest[1]))
                if doc is None:
                    return self._error(404, f"record {rest[1]} not found")
                db.delete(doc)
                self.send_response(204)
                self.send_header("Content-Length", "0")
                self.end_headers()
                return
            if head == "database" and rest:
                self.server.ot_server.security.check(user, RES_DATABASE, "delete")
                ok = self.server.ot_server.drop_database(rest[0])
                return self._send(200 if ok else 404, {"dropped": ok})
            return self._error(404, f"no route for DELETE /{head}")
        except SecurityError as e:
            return self._error(403, str(e))
        except Exception as e:
            return self._error(500, f"{type(e).__name__}: {e}")


class HttpListener:
    """Threaded HTTP listener bound to an ephemeral port by default."""

    def __init__(self, ot_server, port: int = 0) -> None:
        self.httpd = ThreadingHTTPServer(("127.0.0.1", port), _Handler)
        self.httpd.ot_server = ot_server
        # admission-control signal: requests currently being handled
        # (maintained by _traced, read by _shed_write)
        self.httpd.inflight = 0
        self.httpd.inflight_lock = threading.Lock()
        self.port = self.httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, name="http-listener", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
