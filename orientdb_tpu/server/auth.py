"""Pluggable authentication: the authenticator-chain SPI.

Analog of the reference's security module ([E] security/ — the
``OSecurityAuthenticator`` SPI with its chain in ``ODefaultServerSecurity``,
``OKerberosAuthenticator``, and the LDAP importer that materializes
directory users into local accounts; SURVEY.md §2 "Security module
(Kerberos/LDAP/audit)"). Redesign notes:

- The chain is ordered; the first authenticator returning a user wins,
  the rest are not consulted ([E] chain-of-responsibility semantics).
- Real GSSAPI/Kerberos and a live LDAP client are deployment concerns
  (no such libraries in this image); both authenticators here define the
  SPI boundary — a *validator* / *directory* callable object — with
  in-tree HMAC-ticket and in-memory-directory implementations that
  exercise the full mapping logic (principal→user, group→role import).
  A production GSSAPI validator or python-ldap directory drops into the
  same slot.
- Token auth doubles as the session-token system ([E] OTokenHandler):
  HMAC-SHA256 over ``user|expiry`` with the server secret, honored by
  the HTTP layer as ``Authorization: Bearer <token>``.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import os
import time
from typing import Callable, Dict, List, Optional

from orientdb_tpu.models.security import SecurityManager, User
from orientdb_tpu.utils.logging import get_logger

log = get_logger("auth")


class Authenticator:
    """SPI: return the authenticated User, or None to pass the request
    down the chain ([E] OSecurityAuthenticator.authenticate)."""

    name = "base"

    def authenticate(
        self, sec: SecurityManager, user: str, credential: str
    ) -> Optional[User]:  # pragma: no cover - interface
        raise NotImplementedError


def _pack(msg: bytes, sig: bytes) -> str:
    """``b64url(msg).b64url(sig)`` — the separator lives OUTSIDE the
    encodings (the b64url alphabet has no '.'), so a signature byte that
    happens to be 0x2E can never corrupt the split."""
    return (
        base64.urlsafe_b64encode(msg).decode()
        + "."
        + base64.urlsafe_b64encode(sig).decode()
    )


def _unpack(token: str):
    m, _, s = token.partition(".")
    return base64.urlsafe_b64decode(m.encode()), base64.urlsafe_b64decode(
        s.encode()
    )


class PasswordAuthenticator(Authenticator):
    """Local user/password accounts — the default chain tail ([E]
    ODatabaseSecurityAuthenticator)."""

    name = "password"

    def authenticate(self, sec, user, credential):
        u = sec.users.get(user.lower())
        if u is not None and u.check_password(credential):
            return u
        return None


class TokenAuthenticator(Authenticator):
    """HMAC session tokens ([E] OTokenHandlerImpl): ``issue()`` signs
    ``user|expiry`` with the server secret; a token authenticates as that
    user until expiry. Tamper or expiry → pass down the chain."""

    name = "token"

    def __init__(self, secret: Optional[bytes] = None, ttl: float = 3600.0):
        self.secret = secret or os.urandom(32)
        self.ttl = ttl

    def issue(self, user: User, ttl: Optional[float] = None) -> str:
        exp = int(time.time() + (self.ttl if ttl is None else ttl))
        msg = f"{user.name}|{exp}".encode()
        sig = hmac.new(self.secret, msg, hashlib.sha256).digest()
        return _pack(msg, sig)

    def authenticate(self, sec, user, credential):
        # token carries the identity; `user` may be empty (Bearer header)
        try:
            msg, sig = _unpack(credential)
            name, exp = msg.decode().split("|")
        except Exception:
            return None
        want = hmac.new(self.secret, msg, hashlib.sha256).digest()
        if not hmac.compare_digest(sig, want):
            return None
        if time.time() > int(exp):
            return None
        if user and user.lower() != name.lower():
            return None
        return sec.users.get(name.lower())


class LdapAuthenticator(Authenticator):
    """LDAP-shaped external authentication with user import.

    ``directory`` is the SPI boundary: an object with
    ``bind(user, password) -> bool`` and ``groups(user) -> List[str]``.
    On a successful bind the directory user is IMPORTED: a local account
    is created (or updated) with the roles mapped from its groups via
    ``group_role_map`` — the [E] OLDAPImporter behavior, so permissions
    keep flowing through the normal role machinery after login."""

    name = "ldap"

    def __init__(
        self,
        directory,
        group_role_map: Optional[Dict[str, str]] = None,
        default_roles: Optional[List[str]] = None,
    ) -> None:
        self.directory = directory
        self.group_role_map = group_role_map or {}
        self.default_roles = default_roles or ["reader"]

    def _mapped_roles(self, sec: SecurityManager, user: str) -> List[str]:
        roles = [
            self.group_role_map[g]
            for g in self.directory.groups(user)
            if g in self.group_role_map and sec.get_role(self.group_role_map[g])
        ]
        return roles or list(self.default_roles)

    def authenticate(self, sec, user, credential):
        try:
            if not self.directory.bind(user, credential):
                return None
        except Exception:
            log.exception("LDAP directory bind failed")
            return None
        roles = self._mapped_roles(sec, user)
        existing = sec.users.get(user.lower())
        if existing is None:
            # import: random local password — the directory remains the
            # only way to authenticate this account
            u = sec.create_user(
                user, base64.b64encode(os.urandom(24)).decode(), roles
            )
            u.ldap_imported = True
            log.info("imported LDAP user %s with roles %s", user, roles)
            return u
        if not getattr(existing, "ldap_imported", False):
            # a pre-existing LOCAL account (admin, writer, …) is never
            # hijacked by a same-named directory entry: the directory
            # must not control local role assignments — pass down the
            # chain so the local password remains the only way in
            return None
        existing.roles = [r for r in (sec.get_role(n) for n in roles) if r]
        return existing


class InMemoryDirectory:
    """Directory test double (and smallest useful deployment shim)."""

    def __init__(self, users: Dict[str, str], groups: Dict[str, List[str]]):
        self._users = users
        self._groups = groups

    def bind(self, user: str, password: str) -> bool:
        return self._users.get(user) == password

    def groups(self, user: str) -> List[str]:
        return self._groups.get(user, [])


class KerberosAuthenticator(Authenticator):
    """Kerberos-shaped ticket authentication ([E] OKerberosAuthenticator).

    ``validator(ticket) -> principal | None`` is the SPI boundary (a
    production deployment plugs a GSSAPI accept-sec-context there). The
    principal's name part (``alice@REALM`` → ``alice``) must map to an
    existing local user — Kerberos proves identity, roles stay local."""

    name = "kerberos"

    def __init__(self, validator: Callable[[str], Optional[str]]) -> None:
        self.validator = validator

    def authenticate(self, sec, user, credential):
        try:
            principal = self.validator(credential)
        except Exception:
            log.exception("kerberos validator failed")
            return None
        if principal is None:
            return None
        name = principal.split("@", 1)[0]
        if user and user.lower() != name.lower():
            return None
        return sec.users.get(name.lower())


def hmac_ticket_validator(secret: bytes, realm: str = "EXAMPLE.COM"):
    """In-tree ticket validator double: ticket = b64(principal|exp|hmac).
    Exercises the full accept→principal→user mapping without GSSAPI."""

    def validate(ticket: str) -> Optional[str]:
        try:
            msg, sig = _unpack(ticket)
            principal, exp = msg.decode().split("|")
        except Exception:
            return None
        want = hmac.new(secret, msg, hashlib.sha256).digest()
        if not hmac.compare_digest(sig, want) or time.time() > int(exp):
            return None
        if not principal.endswith("@" + realm):
            return None
        return principal

    return validate


def make_ticket(secret: bytes, principal: str, ttl: float = 300.0) -> str:
    """Mint a ticket the `hmac_ticket_validator` accepts (test/KDC double)."""
    msg = f"{principal}|{int(time.time() + ttl)}".encode()
    sig = hmac.new(secret, msg, hashlib.sha256).digest()
    return _pack(msg, sig)


class AuthenticatorChain:
    """Ordered chain; first authenticator returning a user wins."""

    def __init__(self, authenticators: Optional[List[Authenticator]] = None):
        self.authenticators: List[Authenticator] = authenticators or [
            PasswordAuthenticator()
        ]

    def add(self, auth: Authenticator, first: bool = False) -> "AuthenticatorChain":
        if first:
            self.authenticators.insert(0, auth)
        else:
            self.authenticators.append(auth)
        return self

    def authenticate(
        self, sec: SecurityManager, user: str, credential: str
    ) -> Optional[User]:
        for a in self.authenticators:
            u = a.authenticate(sec, user, credential)
            if u is not None:
                return u
        return None
