"""Security auditing.

Analog of the reference's security module's auditing plugin ([E]
security/ ``OSecurityPlugin`` + the EE auditing component; SURVEY.md §2
"Security module (Kerberos/LDAP/audit)"): an append-only JSON-lines
trail of authentication attempts, permission denials, and record
mutations, attachable to a Server (auth events) and to any Database
(record events, via the hook pipeline — so transactional events surface
post-commit only, matching the hook-buffering semantics). Kerberos/LDAP
authenticators have no offline analog and stay out of scope; the
pluggable seam is the ``authenticator`` callable on SecurityManager
consumers."""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional

from orientdb_tpu.utils.logging import get_logger

log = get_logger("audit")


class AuditLog:
    """Append-only audit trail; memory ring + optional JSON-lines file."""

    def __init__(self, path: Optional[str] = None, keep: int = 1000) -> None:
        self.path = path
        self.keep = keep
        self._events: List[Dict] = []
        self._lock = threading.Lock()
        self._fh = None
        if path:
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
            self._fh = open(path, "a")

    def record(self, kind: str, **fields) -> None:
        ev = {"ts": time.time(), "kind": kind, **fields}
        with self._lock:
            self._events.append(ev)
            del self._events[: -self.keep]
            if self._fh is not None:
                self._fh.write(json.dumps(ev, default=str) + "\n")
                self._fh.flush()

    def events(self, kind: Optional[str] = None) -> List[Dict]:
        with self._lock:
            return [e for e in self._events if kind is None or e["kind"] == kind]

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    # -- attachments --------------------------------------------------------

    def watch_database(self, db, name: Optional[str] = None) -> None:
        """Record post-commit record mutations ([E] the auditing hook is an
        ORecordHook; riding the AFTER pipeline keeps compensated-away tx
        ops out of the trail)."""
        dbname = name or db.name

        def hook(event, doc):
            self.record(
                "record." + event.split("_", 1)[1],
                db=dbname,
                rid=str(doc.rid),
                cls=doc.class_name,
            )

        for ev in ("after_create", "after_update", "after_delete"):
            db.hooks.register(hook, event=ev)

    def auth_ok(self, user: str, origin: str = "") -> None:
        self.record("auth.ok", user=user, origin=origin)

    def auth_fail(self, user: str, origin: str = "") -> None:
        self.record("auth.fail", user=user, origin=origin)

    def denied(self, user: str, resource: str, op: str) -> None:
        self.record("auth.denied", user=user, resource=resource, op=op)
