"""Binary protocol listener.

Analog of [E] ONetworkProtocolBinary / OChannelBinaryServer (port 2424,
SURVEY.md §2 "Binary protocol"): a persistent, session-oriented channel —
each frame is a 4-byte big-endian length followed by a compact JSON
envelope. Record payloads travel either as JSON dicts (default; blob
bytes framed as {"@bytes": base64}) or, when the session negotiates
``serialization: "binary"`` at db_open, as the schema-aware binary
record format (`server/binser.py` — the ORecordSerializerNetwork
analog) base85-framed inside the envelope.

Requests: {"op": ..., ...}. Ops: connect, db_list, db_create, db_open,
query, query_batch, command, load, save, delete, live_subscribe,
live_unsubscribe, close. All ops after `connect` run under the
authenticated user's permissions. Live-query events are PUSHED as
unsolicited frames {"push": true, "event": {...}} on the same channel;
clients demultiplex by the "push" key ([E] the binary protocol's push
messages).

Throughput path (VERDICT r4 #1 — the wire must deliver the engine's
batched-dispatch speed, [E] the reference's server IS its wire path):

- ``query_batch`` ships N statements in ONE frame and runs them through
  the engine's group dispatch (`exec/engine.execute_query_batch`);
- single ``query`` ops route through the server's cross-session
  coalescer (`server/coalesce.py`): concurrent sessions' singles land
  in fingerprint-keyed dispatch lanes and merge into homogeneous
  micro-batches replaying one compiled plan;
- ``pipeline: true`` at db_open turns on out-of-order dispatch for this
  session: query ops run on a worker pool and respond by ``reqid`` when
  ready, so ONE client can keep many singles in flight (they coalesce
  server-side like separate sessions' would).
"""

from __future__ import annotations

import json
import socket
import struct
import threading
from typing import Optional

import orientdb_tpu.obs.critpath as critpath
from orientdb_tpu.chaos import fault
from orientdb_tpu.models.rid import RID
from orientdb_tpu.models.security import (
    RES_DATABASE,
    RES_RECORD,
    SecurityError,
    classify_sql,
)
from orientdb_tpu.utils.logging import get_logger

log = get_logger("binary")


def send_frame(sock: socket.socket, payload: dict) -> None:
    # bytes values (blob payloads) get the shared @bytes framing; other
    # non-JSON values keep the channel's historical stringification
    from orientdb_tpu.storage.durability import json_channel_default

    # critpath stamps are thread-local no-ops on the client side of the
    # wire (client/remote.py shares this helper but never opens a
    # record); server-side, the bin.send fault point sits INSIDE the
    # flush timing so an injected send delay blames flush, not marshal
    with critpath.segment("marshal"):
        data = json.dumps(payload, default=json_channel_default).encode()
    with critpath.segment("flush"):
        with fault.point("bin.send"):
            sock.sendall(struct.pack(">I", len(data)) + data)


def recv_frame_raw(sock: socket.socket) -> Optional[bytes]:
    """One length-prefixed frame's body, undecoded — the server read
    loop takes frames raw so the JSON decode lands inside the request's
    ``parse`` segment (the record opens at frame arrival)."""
    with fault.point("bin.recv"):
        head = _recv_exact(sock, 4)
    if head is None:
        return None
    (n,) = struct.unpack(">I", head)
    return _recv_exact(sock, n)


def recv_frame(sock: socket.socket) -> Optional[dict]:
    body = recv_frame_raw(sock)
    if body is None:
        return None
    return json.loads(body.decode())


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


def _req_is_read(req: dict) -> bool:
    """A command/script op whose every statement classifies as READ
    rides through admission shedding — degradation means read-only,
    not read-nothing."""
    try:
        if req.get("op") == "command":
            _res, action = classify_sql(req.get("sql", ""))
            return action == "read"
        if req.get("op") == "script":
            from orientdb_tpu.exec.script import script_permissions

            return all(
                action == "read"
                for _res, action in script_permissions(
                    req.get("script", "")
                )
            )
    except Exception:
        # unclassifiable (parse error): treat as a write — the error
        # itself surfaces on the direct execution path
        return False
    return False


class _CdcPump:
    """Push loop for one changefeed subscription on a binary session:
    drains the feed consumer's bounded queue and ships event batches as
    unsolicited ``{"push": true, "cdc": true}`` frames (riding the
    live-push framing and the session's send lock). A dead channel ends
    the pump with ONE warning — the events stay redeliverable from the
    consumer's cursor, which is the whole point of the plane."""

    def __init__(self, session: "_Session", consumer) -> None:
        self.session = session
        self.consumer = consumer
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run,
            name=f"cdc-push-{consumer.token}",
            daemon=True,
        )

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        """Idempotent; never joins (the pump may be blocked in a send
        the caller's socket close is about to break)."""
        self._stop.set()
        self.consumer.close()  # wakes a poll() wait
        self.consumer.feed.unregister(self.consumer.token)

    def _run(self) -> None:
        from orientdb_tpu.cdc.feed import CdcGapError
        from orientdb_tpu.obs.trace import span
        from orientdb_tpu.utils.metrics import metrics

        token = self.consumer.token
        while not self._stop.is_set():
            try:
                events = self.consumer.poll(max_events=256, timeout=0.25)
            except CdcGapError as e:
                # the resume point fell off retention: tell the client
                # loudly (it must resync), then end the subscription
                try:
                    self.session._send(
                        {
                            "push": True,
                            "cdc": True,
                            "token": token,
                            "error": str(e),
                            "resync": True,
                        }
                    )
                except OSError:
                    pass
                break
            if not events:
                continue
            if self._stop.is_set():
                # teardown raced the poll: the batch is NOT sent — it
                # remains redeliverable from the cursor, and a frame at
                # a closing socket would be an event to a dead callback
                break
            try:
                with span(
                    "cdc.push", transport="binary", events=len(events)
                ), fault.point("cdc.push"):
                    self.session._send(
                        {
                            "push": True,
                            "cdc": True,
                            "token": token,
                            "events": events,
                        }
                    )
                metrics.incr("cdc.delivered", len(events))
            except OSError:
                log.warning(
                    "cdc push failed for token %s (session gone); "
                    "consumer resumes from its cursor",
                    token,
                )
                break
        self.stop()


class _Session:
    def __init__(self, server, sock: socket.socket) -> None:
        self.server = server
        self.sock = sock
        self.user = None
        self.db = None
        #: responses and live-query push frames share the socket: the
        #: send lock keeps a push from interleaving mid-response ([E] the
        #: binary protocol's push messages ride the session channel too)
        self._send_lock = threading.Lock()
        #: token -> LiveQueryMonitor subscribed over THIS session
        self._live: dict = {}
        #: token -> _CdcPump for changefeed subscriptions on THIS session
        self._cdc: dict = {}
        #: pipeline mode (db_open {"pipeline": true}): query ops run on
        #: this pool and respond out-of-order by reqid
        self._pool = None

    def _send(self, payload: dict) -> None:
        with self._send_lock:
            send_frame(self.sock, payload)

    def _record_payload(self, doc) -> dict:
        """One record for the wire: schema-aware binary bytes
        (base85-framed, self-contained batch envelope carrying the
        class's property dictionary) for sessions that negotiated
        ``serialization: "binary"`` at db_open; plain JSON otherwise."""
        if getattr(self, "binser", False):
            import base64

            from orientdb_tpu.server.binser import encode_records

            return {
                "record_b85": base64.b85encode(
                    encode_records([doc])
                ).decode()
            }
        return {"record": doc.to_dict()}

    def _dispatch_async(self, req: dict) -> None:
        """Pipeline mode: run on the session worker pool, respond by
        reqid when ready (the client demultiplexes out-of-order)."""
        cp = critpath.begin_request("binary", req.get("sql"))
        with critpath.active(cp):
            resp = self._dispatch(req)
            resp["reqid"] = req["reqid"]
            try:
                self._send(resp)
            except OSError:
                pass  # client gone; the recv loop will notice
        critpath.commit(cp)

    def run(self) -> None:
        try:
            while True:
                raw = recv_frame_raw(self.sock)
                if raw is None:
                    break
                # the decomposition record opens at frame arrival so the
                # envelope decode is attributed as parse, not lost ahead
                # of the handler window
                cp = critpath.begin_request("binary")
                with critpath.active(cp):
                    with critpath.segment("parse"):
                        req = json.loads(raw.decode())
                    critpath.note_sql(req.get("sql"))
                if (
                    self._pool is not None
                    and req.get("op") in ("query", "query_batch")
                    and "reqid" in req
                ):
                    # pipelined session: don't block the read loop on
                    # the device — in-flight singles coalesce. The read
                    # loop's record is abandoned (never committed): the
                    # worker owns the request end-to-end and opens its
                    # own
                    self._pool.submit(self._dispatch_async, req)
                    continue
                with critpath.active(cp):
                    resp = self._dispatch(req)
                    # echo the client's correlation id so its channel
                    # can discard stale replies after a response timeout
                    # instead of desynchronizing (client/remote.py _call)
                    if "reqid" in req:
                        resp["reqid"] = req["reqid"]
                    self._send(resp)
                critpath.commit(cp)
                # a cdc_subscribe's pump starts only AFTER its response
                # is on the wire: a catch-up batch pushed ahead of the
                # response would land before the client knows the token
                # and could overflow its orphan buffer (lost events)
                pending = self.__dict__.pop("_pending_pump", None)
                if pending is not None:
                    pending.start()
                if req.get("op") == "close":
                    break
        except OSError:
            pass
        finally:
            if self._pool is not None:
                self._pool.shutdown(wait=False)
            # a dropped session must not leave dangling subscriptions.
            # cdc pumps stop FIRST (their consumers close, waking any
            # in-flight poll) so no event is pushed at the dying socket
            for pump in list(self._cdc.values()):
                pump.stop()
            self._cdc.clear()
            for m in list(self._live.values()):
                try:
                    m.unsubscribe()
                except Exception:
                    log.warning(
                        "live-query unsubscribe failed during "
                        "session teardown", exc_info=True,
                    )
            self._live.clear()
            try:
                self.sock.close()
            except OSError:
                pass

    def _dispatch(self, req: dict) -> dict:
        # the envelope's "trace" field is the binary channel's
        # propagation carrier (obs/propagation.inject_frame on the
        # client): this session thread CONTINUES the caller's trace
        from orientdb_tpu.obs.propagation import continue_trace

        with continue_trace(
            f"binary.{req.get('op')}", req.get("trace")
        ):
            return self._dispatch_inner(req)

    def _dispatch_inner(self, req: dict) -> dict:
        op = req.get("op")
        try:
            if op == "connect":
                u = self.server.security.authenticate(
                    req.get("user", ""), req.get("password", "")
                )
                if u is None:
                    return {"ok": False, "error": "invalid credentials"}
                self.user = u
                return {"ok": True, "user": u.name}
            if self.user is None:
                return {"ok": False, "error": "not authenticated"}
            if op == "db_list":
                return {"ok": True, "databases": sorted(self.server.databases)}
            if op == "db_create":
                self.server.security.check(self.user, RES_DATABASE, "create")
                self.server.create_database(req["name"])
                self.db = self.server.get_database(req["name"])
                return {"ok": True}
            if op == "db_open":
                db = self.server.get_database(req["name"])
                if db is None:
                    return {"ok": False, "error": f"no database '{req['name']}'"}
                self.db = db
                # record payload encoding for THIS session ([E] the
                # serialization-impl negotiation of the reference's
                # OPEN op): "binary" routes load/save record payloads
                # through the schema-aware binary format (binser.py)
                self.binser = req.get("serialization") == "binary"
                if req.get("pipeline") and self._pool is None:
                    from concurrent.futures import ThreadPoolExecutor

                    self._pool = ThreadPoolExecutor(
                        max_workers=32, thread_name_prefix="binq"
                    )
                return {"ok": True, "serialization": (
                    "binary" if self.binser else "json"
                )}
            if self.db is None and op != "close":
                return {"ok": False, "error": "no database open"}
            if op in (
                "command", "script", "save", "delete"
            ) and not _req_is_read(req):
                from orientdb_tpu.server.admission import db_pressure

                shed, retry_after = db_pressure(self.db)
                if shed is not None:
                    from orientdb_tpu.utils.metrics import metrics

                    metrics.incr("binary.shed")
                    resp = {
                        "ok": False,
                        "error": shed,
                        "code": 503,
                        "retry_after": retry_after,
                    }
                    if shed.startswith("device memory pressure"):
                        # flag device-domain sheds so clients can tell
                        # device pressure from host overload
                        resp["device"] = True
                    return resp
            if op == "query":
                self.server.security.check(self.user, RES_RECORD, "read")
                # singles ride the cross-session lane path: concurrent
                # sessions' same-shape queries merge into one micro-batch
                rows, engine = self.server.coalescer.submit(
                    self.db, req["sql"], req.get("params")
                )
                return {"ok": True, "result": rows, "engine": engine}
            if op == "query_batch":
                # N statements, ONE frame, one group dispatch ([E] the
                # reference's OQueryRequest has no batch op — this is
                # the TPU-first addition the engine's speed demands)
                self.server.security.check(self.user, RES_RECORD, "read")
                sqls = req.get("sqls") or []
                params_list = req.get("params_list") or [None] * len(sqls)
                if len(params_list) != len(sqls):
                    # a mismatch must not reach the per-item fallback,
                    # whose zip would silently truncate the batch
                    return {
                        "ok": False,
                        "error": "params_list length "
                        f"{len(params_list)} != sqls length {len(sqls)}",
                    }
                results = []
                try:
                    for rs in self.db.query_batch(sqls, params_list):
                        results.append(
                            {"result": rs.to_dicts(), "engine": rs.engine}
                        )
                except Exception:
                    # per-item isolation: one bad statement must not
                    # void its cohort — re-run individually
                    results = []
                    for sql, p in zip(sqls, params_list):
                        try:
                            rs = self.db.query(sql, p)
                            results.append(
                                {
                                    "result": rs.to_dicts(),
                                    "engine": rs.engine,
                                }
                            )
                        except Exception as e:
                            results.append(
                                {"error": f"{type(e).__name__}: {e}"}
                            )
                return {"ok": True, "results": results}
            if op == "command":
                resource, cop = classify_sql(req["sql"])
                self.server.security.check(self.user, resource, cop)
                rs = self.db.command(req["sql"], req.get("params"))
                return {"ok": True, "result": rs.to_dicts(), "engine": rs.engine}
            if op == "script":
                # SQL batch script ([E] the REQUEST_COMMAND script
                # payload): every embedded statement authorizes like a
                # single command — no escalation through scripts
                from orientdb_tpu.exec.script import script_permissions

                for resource, action in sorted(
                    script_permissions(req["script"])
                ):
                    self.server.security.check(self.user, resource, action)
                rs = self.db.execute(
                    req.get("language", "sql"),
                    req["script"],
                    req.get("params"),
                )
                return {
                    "ok": True,
                    "result": rs.to_dicts(),
                    "engine": getattr(rs, "engine", None),
                }
            if op == "load":
                self.server.security.check(self.user, RES_RECORD, "read")
                doc = self.db.load(RID.parse(req["rid"]))
                if doc is None:
                    return {"ok": True, "record": None}
                return {"ok": True, **self._record_payload(doc)}
            if op == "save":
                self.server.security.check(self.user, RES_RECORD, "update")
                from orientdb_tpu.storage.durability import _dec

                payload = dict(req.get("record") or {})
                cls = payload.pop("@class", "O")
                rid = payload.pop("@rid", None)
                payload = {
                    k: _dec(v)
                    for k, v in payload.items()
                    if not k.startswith("@")
                }
                if rid:
                    doc = self.db.load(RID.parse(rid))
                    if doc is None:
                        return {"ok": False, "error": f"record {rid} not found"}
                    for k, v in payload.items():
                        doc.set(k, v)
                    self.db.save(doc)
                else:
                    c = self.db.schema.get_class(cls)
                    if cls == "OBlob":
                        doc = self.db.new_blob(payload.pop("data", b"") or b"")
                        if payload:
                            for k, v in payload.items():
                                doc.set(k, v)
                            self.db.save(doc)
                    elif c is not None and c.is_vertex_type:
                        doc = self.db.new_vertex(cls, **payload)
                    else:
                        doc = self.db.new_element(cls, **payload)
                return {"ok": True, **self._record_payload(doc)}
            if op == "live_subscribe":
                # push delivery over the session channel ([E]
                # OLiveQueryHookV2 pushing to remote clients)
                self.server.security.check(self.user, RES_RECORD, "read")
                from orientdb_tpu.exec.live import live_query

                session = self

                def push(ev, session=session):
                    try:
                        session._send({"push": True, "event": ev})
                    except OSError:
                        pass  # client gone; cleanup happens on recv EOF

                m = live_query(self.db, req["sql"], push)
                self._live[m.token] = m
                return {"ok": True, "token": m.token}
            if op == "cdc_subscribe":
                # resumable changefeed push over the session channel
                # (orientdb_tpu/cdc): {"classes": [...], "where": "...",
                # "since": <lsn> | "cursor": "<name>", "policy":
                # "shed"|"block"} → events arrive as {"push": true,
                # "cdc": true, "token": t, "events": [...]} frames;
                # cdc_ack persists the cursor for reconnect resume
                self.server.security.check(self.user, RES_RECORD, "read")
                from orientdb_tpu.cdc.feed import feed_of, parse_where

                classes = req.get("classes") or None
                where = req.get("where")
                consumer = feed_of(self.db).register(
                    name=req.get("cursor"),
                    classes=classes,
                    where=parse_where(
                        where, classes[0] if classes else None
                    )
                    if where
                    else None,
                    since=req.get("since"),
                    policy=req.get("policy", "shed"),
                )
                pump = _CdcPump(self, consumer)
                self._cdc[consumer.token] = pump
                # started by the run loop AFTER the response is sent
                self._pending_pump = pump
                return {
                    "ok": True,
                    "token": consumer.token,
                    "since": consumer.resume_lsn,
                }
            if op == "cdc_ack":
                pump = self._cdc.get(req.get("token"))
                if pump is None:
                    return {"ok": False, "error": "unknown cdc token"}
                acked = pump.consumer.ack(int(req.get("lsn", 0)))
                return {"ok": True, "lsn": acked}
            if op == "cdc_unsubscribe":
                pump = self._cdc.pop(req.get("token"), None)
                if pump is None:
                    return {"ok": False, "error": "unknown cdc token"}
                pump.stop()
                return {"ok": True}
            if op == "live_unsubscribe":
                m = self._live.pop(req.get("token"), None)
                if m is None:
                    return {"ok": False, "error": "unknown live token"}
                m.unsubscribe()
                return {"ok": True}
            if op == "delete":
                self.server.security.check(self.user, RES_RECORD, "delete")
                doc = self.db.load(RID.parse(req["rid"]))
                if doc is not None:
                    self.db.delete(doc)
                return {"ok": True}
            if op == "close":
                return {"ok": True}
            return {"ok": False, "error": f"unknown op {op!r}"}
        except SecurityError as e:
            return {"ok": False, "error": str(e), "code": 403}
        except Exception as e:  # protocol errors must not kill the session
            # a device fault that escaped every fallback (quarantine
            # raced the oracle path, or relief itself failed) maps to a
            # retryable 503 with the ``device`` marker: by retry_after
            # the escalation ladder has quarantined the plan and the
            # retry lands on the oracle
            from orientdb_tpu.exec import devicefault

            if isinstance(
                e, (devicefault.DeviceFaultError, devicefault.DeviceQuarantined)
            ):
                return {
                    "ok": False,
                    "error": f"{type(e).__name__}: {e}",
                    "code": 503,
                    "retry_after": float(
                        getattr(e, "retry_after", None) or 0.5
                    ),
                    "device": True,
                }
            return {"ok": False, "error": f"{type(e).__name__}: {e}"}


class BinaryListener:
    def __init__(self, ot_server, port: int = 0) -> None:
        self.server = ot_server
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.bind(("127.0.0.1", port))
        self.sock.listen(16)
        self.port = self.sock.getsockname()[1]
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._accept_loop, name="binary-listener", daemon=True
        )
        self._thread.start()

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _addr = self.sock.accept()
            except OSError:
                break
            # one thread per accepted socket, like the reference's listener
            threading.Thread(
                target=_Session(self.server, conn).run, daemon=True
            ).start()

    def stop(self) -> None:
        self._stop.set()
        try:
            self.sock.close()
        except OSError:
            pass
