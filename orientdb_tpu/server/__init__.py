"""Server & protocols: process entry, HTTP/REST and binary listeners,
per-database registry (SURVEY.md §2 "Server", §3.1 boot sequence)."""

from orientdb_tpu.server.server import Server  # noqa: F401
