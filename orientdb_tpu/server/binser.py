"""Schema-aware binary record serialization.

Analog of the reference's record binary format ([E]
``ORecordSerializerBinary`` / ``ORecordSerializerBinaryV0/V1`` /
``ORecordSerializerNetworkV37``; SURVEY.md §2 "Binary serialization":
"schema-aware field encoding"). The VERDICT marked this row partial —
the binary channel framed compact JSON, arguing rows dominate the wire;
this module supplies the missing format itself:

- **varint/zigzag** integer encoding (the reference's OVarIntSerializer),
- **schema-aware field names**: when the record's class declares a
  property, the field is encoded as a small property-id varint against
  the class's sorted property list instead of an inline string — the
  schema carried once per payload header, exactly the "schema carried
  out-of-band" trade the reference's format makes,
- typed values: null / bool / zigzag int / float64 / UTF-8 string /
  bytes / link (RID as two varints) / list / map / embedded document,
- a **record envelope** (class name, RID, version, record kind) and a
  **batch envelope** for result-row lists.

Used by the binary protocol when a session requests
``serialization: "binary"`` at `db_open` (record payloads of
load/save/query travel as these bytes, base85-framed inside the JSON
envelope so the channel framing is unchanged), and available standalone:

    data = encode_record(doc)
    fields = decode_record(data)          # dict form
"""

from __future__ import annotations

import struct
from typing import Dict, List, Optional, Tuple

from orientdb_tpu.models.record import Blob, Document, Edge, Vertex
from orientdb_tpu.models.rid import RID

FORMAT_VERSION = 1

# value type tags
T_NULL = 0
T_TRUE = 1
T_FALSE = 2
T_INT = 3  # zigzag varint
T_FLOAT = 4  # float64 big-endian
T_STR = 5  # varint len + utf8
T_BYTES = 6  # varint len + raw
T_LINK = 7  # varint cluster + varint position
T_LIST = 8  # varint count + values
T_MAP = 9  # varint count + (str key, value)*
T_EMBEDDED = 10  # embedded document: varint len + record bytes

_KIND = {"document": 0, "vertex": 1, "edge": 2, "blob": 3}
_KIND_R = {v: k for k, v in _KIND.items()}


# -- varints ----------------------------------------------------------------


def write_varint(out: bytearray, n: int) -> None:
    if n < 0:
        raise ValueError("varint must be non-negative")
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def read_varint(data: bytes, pos: int) -> Tuple[int, int]:
    shift = n = 0
    while True:
        b = data[pos]
        pos += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return n, pos
        shift += 7


def zigzag(n: int) -> int:
    # Python ints are arbitrary-precision: the fixed-width (n >> 63)
    # trick would corrupt values >= 2**63, so map sign explicitly.
    return (n << 1) if n >= 0 else ((-n) << 1) - 1


def unzigzag(n: int) -> int:
    return (n >> 1) if not n & 1 else -((n + 1) >> 1)


# -- values -----------------------------------------------------------------


def _write_str(out: bytearray, s: str) -> None:
    b = s.encode()
    write_varint(out, len(b))
    out += b


def _read_str(data: bytes, pos: int) -> Tuple[str, int]:
    n, pos = read_varint(data, pos)
    return data[pos : pos + n].decode(), pos + n


def write_value(out: bytearray, v) -> None:
    if v is None:
        out.append(T_NULL)
    elif v is True:
        out.append(T_TRUE)
    elif v is False:
        out.append(T_FALSE)
    elif isinstance(v, int):
        out.append(T_INT)
        write_varint(out, zigzag(v))
    elif isinstance(v, float):
        out.append(T_FLOAT)
        out += struct.pack(">d", v)
    elif isinstance(v, str):
        out.append(T_STR)
        _write_str(out, v)
    elif isinstance(v, (bytes, bytearray)):
        out.append(T_BYTES)
        write_varint(out, len(v))
        out += bytes(v)
    elif isinstance(v, RID):
        out.append(T_LINK)
        write_varint(out, v.cluster)
        write_varint(out, v.position)
    elif isinstance(v, Document):
        if v.rid.is_persistent:
            out.append(T_LINK)
            write_varint(out, v.rid.cluster)
            write_varint(out, v.rid.position)
        else:  # embedded document value
            out.append(T_EMBEDDED)
            rec = encode_record(v)
            write_varint(out, len(rec))
            out += rec
    elif isinstance(v, (list, tuple)):
        out.append(T_LIST)
        write_varint(out, len(v))
        for x in v:
            write_value(out, x)
    elif isinstance(v, dict):
        out.append(T_MAP)
        write_varint(out, len(v))
        for k, x in v.items():
            _write_str(out, str(k))
            write_value(out, x)
    else:
        # last resort: stringified (same policy as the JSON channel)
        out.append(T_STR)
        _write_str(out, str(v))


def read_value(data: bytes, pos: int):
    tag = data[pos]
    pos += 1
    if tag == T_NULL:
        return None, pos
    if tag == T_TRUE:
        return True, pos
    if tag == T_FALSE:
        return False, pos
    if tag == T_INT:
        n, pos = read_varint(data, pos)
        return unzigzag(n), pos
    if tag == T_FLOAT:
        return struct.unpack(">d", data[pos : pos + 8])[0], pos + 8
    if tag == T_STR:
        return _read_str(data, pos)
    if tag == T_BYTES:
        n, pos = read_varint(data, pos)
        return bytes(data[pos : pos + n]), pos + n
    if tag == T_LINK:
        c, pos = read_varint(data, pos)
        p, pos = read_varint(data, pos)
        return RID(c, p), pos
    if tag == T_LIST:
        n, pos = read_varint(data, pos)
        out = []
        for _ in range(n):
            v, pos = read_value(data, pos)
            out.append(v)
        return out, pos
    if tag == T_MAP:
        n, pos = read_varint(data, pos)
        m = {}
        for _ in range(n):
            k, pos = _read_str(data, pos)
            m[k], pos = read_value(data, pos)
        return m, pos
    if tag == T_EMBEDDED:
        n, pos = read_varint(data, pos)
        return decode_record(data[pos : pos + n]), pos + n
    raise ValueError(f"unknown value tag {tag}")


# -- records ----------------------------------------------------------------


def _schema_props(doc: Document) -> List[str]:
    """The class's declared property names, sorted — the shared
    dictionary schema-aware field encoding keys into. Empty for
    schemaless records (every field name travels inline)."""
    db = getattr(doc, "_db", None)
    if db is None:
        return []
    cls = db.schema.get_class(doc.class_name)
    if cls is None:
        return []
    return sorted(cls.properties)


def encode_record(doc: Document, props: Optional[List[str]] = None) -> bytes:
    """One record → bytes. Field names declared in the record's class
    encode as property-id varints (schema-aware); undeclared fields
    carry their name inline (the schemaless half of the hybrid model)."""
    if props is None:
        props = _schema_props(doc)
    prop_idx = {p: i for i, p in enumerate(props)}
    out = bytearray()
    out.append(FORMAT_VERSION)
    kind = (
        "vertex"
        if isinstance(doc, Vertex)
        else "edge"
        if isinstance(doc, Edge)
        else "blob" if isinstance(doc, Blob) else "document"
    )
    out.append(_KIND[kind])
    _write_str(out, doc.class_name)
    rid = doc.rid
    write_varint(out, rid.cluster if rid.is_persistent else 0)
    write_varint(out, rid.position if rid.is_persistent else 0)
    out.append(1 if rid.is_persistent else 0)
    write_varint(out, max(doc.version, 0))
    if isinstance(doc, Edge):
        write_varint(out, doc.out_rid.cluster)
        write_varint(out, doc.out_rid.position)
        write_varint(out, doc.in_rid.cluster)
        write_varint(out, doc.in_rid.position)
    fields = doc.fields()
    write_varint(out, len(fields))
    for name, value in fields.items():
        pid = prop_idx.get(name)
        if pid is not None:
            out.append(1)  # schema-indexed name
            write_varint(out, pid)
        else:
            out.append(0)  # inline name
            _write_str(out, name)
        write_value(out, value)
    return bytes(out)


def decode_record(
    data: bytes, props: Optional[List[str]] = None
) -> Dict[str, object]:
    """bytes → dict form (the `to_dict`-shaped result: fields plus
    @rid/@class/@version/@type, and @out/@in for edges)."""
    pos = 0
    ver = data[pos]
    pos += 1
    if ver != FORMAT_VERSION:
        raise ValueError(f"unknown binary record format v{ver}")
    kind = _KIND_R[data[pos]]
    pos += 1
    class_name, pos = _read_str(data, pos)
    c, pos = read_varint(data, pos)
    p, pos = read_varint(data, pos)
    persistent = data[pos] == 1
    pos += 1
    version, pos = read_varint(data, pos)
    out: Dict[str, object] = {
        "@class": class_name,
        "@type": kind,
        "@version": version,
    }
    if persistent:
        out["@rid"] = str(RID(c, p))
    if kind == "edge":
        oc, pos = read_varint(data, pos)
        op_, pos = read_varint(data, pos)
        ic, pos = read_varint(data, pos)
        ip, pos = read_varint(data, pos)
        out["@out"] = str(RID(oc, op_))
        out["@in"] = str(RID(ic, ip))
    n, pos = read_varint(data, pos)
    for _ in range(n):
        indexed = data[pos] == 1
        pos += 1
        if indexed:
            pid, pos = read_varint(data, pos)
            if props is None or pid >= len(props):
                raise ValueError(
                    f"schema-indexed field {pid} but no schema provided"
                )
            name = props[pid]
        else:
            name, pos = _read_str(data, pos)
        out[name], pos = read_value(data, pos)
    return out


# -- batch envelope ---------------------------------------------------------


def encode_records(docs: List[Document]) -> bytes:
    """Result-row batch: one shared per-class schema header (class →
    sorted property list, carried once), then each record. This is the
    'schema out-of-band' economy the reference's network serializer
    ([E] ORecordSerializerNetworkV37) gets from the shared schema."""
    classes: Dict[str, List[str]] = {}
    for d in docs:
        if d.class_name not in classes:
            classes[d.class_name] = _schema_props(d)
    out = bytearray()
    out.append(FORMAT_VERSION)
    write_varint(out, len(classes))
    for cname, props in classes.items():
        _write_str(out, cname)
        write_varint(out, len(props))
        for prop in props:
            _write_str(out, prop)
    write_varint(out, len(docs))
    for d in docs:
        rec = encode_record(d, classes[d.class_name])
        write_varint(out, len(rec))
        out += rec
    return bytes(out)


def decode_records(data: bytes) -> List[Dict[str, object]]:
    pos = 0
    ver = data[pos]
    pos += 1
    if ver != FORMAT_VERSION:
        raise ValueError(f"unknown binary batch format v{ver}")
    ncls, pos = read_varint(data, pos)
    classes: Dict[str, List[str]] = {}
    for _ in range(ncls):
        cname, pos = _read_str(data, pos)
        nprops, pos = read_varint(data, pos)
        props = []
        for _ in range(nprops):
            s, pos = _read_str(data, pos)
            props.append(s)
        classes[cname] = props
    n, pos = read_varint(data, pos)
    out = []
    for _ in range(n):
        ln, pos = read_varint(data, pos)
        rec = data[pos : pos + ln]
        pos += ln
        # peek the class name (version byte, kind byte, class string)
        # to pick its schema header
        cname, _ = _read_str(rec, 2)
        out.append(decode_record(rec, classes.get(cname, [])))
    return out
