"""Shared admission-control pressure checks for both listeners.

One definition of "writes to this database should shed" — the HTTP
listener (``http_server._shed_write``) adds its per-listener in-flight
depth check on top; the binary listener uses this alone. Keeping the
db-pressure signals here stops the two servers drifting apart (each
new signal lands in one place).
"""

from __future__ import annotations

from typing import Optional, Tuple


def quorum_degraded(q) -> bool:
    """True while the quorum pusher's write path should stay shed.

    Prefers :meth:`QuorumPusher.writes_degraded` (a half-open window:
    after it elapses, writes are admitted again so one can reach
    ``replicate()`` and actually CLEAR the latch — shedding on the raw
    latch forever would leave an HTTP/binary-only cluster read-only
    even after the replicas recovered); falls back to the plain
    ``quorum_lost`` attribute for simple stand-ins."""
    fn = getattr(q, "writes_degraded", None)
    if callable(fn):
        return bool(fn())
    return bool(getattr(q, "quorum_lost", False))


def db_pressure(db) -> Tuple[Optional[str], float]:
    """(shed reason or None, Retry-After seconds) for writes to ``db``."""
    from orientdb_tpu.obs.critpath import segment
    from orientdb_tpu.utils.config import config

    # the admission decision itself is a critical-path segment: under
    # backlog the staged-count / quorum / fault-domain probes contend
    # on their locks, and that wait must not blur into parse time
    with segment("admission"):
        retry = config.retry_after_s
        if db is None:
            return None, retry
        reg = getattr(db, "_tx2pc_registry", None)
        if reg is not None and config.tx2pc_staged_max:
            n = reg.staged_count()
            if n > config.tx2pc_staged_max:
                return (
                    f"staged 2PC backlog {n} > {config.tx2pc_staged_max}",
                    retry,
                )
        q = getattr(db, "_repl_quorum", None)
        if q is not None and quorum_degraded(q):
            return "write quorum lost; serving read-only", max(retry, 1.0)
        # device fault domain headroom shed (exec/devicefault): an OOM
        # that survived relief, or a memledger total still over the
        # headroom fraction after it, arms a half-open latch — writes
        # shed for devicefault_shed_s so admission stops feeding a
        # device that has nothing left to give (it clears itself;
        # reads keep degrading to the oracle via quarantine)
        from orientdb_tpu.exec.devicefault import domain as _fault_domain

        reason, after = _fault_domain.shed_state()
        if reason is not None:
            return f"device memory pressure: {reason}", max(retry, after)
        return None, retry
