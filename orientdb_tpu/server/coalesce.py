"""Cross-session query coalescing — the server-side group path.

The round-4 measurement story: the compiled engine's batched dispatch
(`exec/engine.execute_query_batch` → `tpu_engine.dispatch_many`) runs
~60× faster per query than lone dispatches, but only the embedded
Python API could reach it — every remote session's query paid a full
device round trip alone ([E] the reference has no such gap because its
server IS its wire path, SURVEY.md §3.2 ``ONetworkProtocolBinary``).

This module closes it with a **group-commit scheduler** per database:

- sessions submit single queries and block on a per-item event;
- one worker thread per database drains EVERYTHING queued and executes
  it as one `execute_query_batch` call — so while a batch is on the
  device, the next batch forms behind it (the WAL group-commit shape,
  `native/walappend.cpp`, applied to reads);
- a lone client therefore pays ~zero extra latency (its item is
  drained immediately), while N concurrent sessions' singles ride ONE
  device dispatch — throughput scales with offered load instead of
  serializing on the tunnel RTT.

An optional collection window (``OTPU_COALESCE_WINDOW_MS``, default 0)
adds a fixed wait before each drain for workloads where arrivals are
sparser than device time; the default relies on natural batching.

Per-item isolation: statements that cannot ride a batch (non-idempotent,
EXPLAIN, parse errors) execute directly on the submitting thread, and a
batch-level failure falls back to per-item execution so one bad query
cannot poison its cohort's results.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional, Tuple

from orientdb_tpu.utils.logging import get_logger
from orientdb_tpu.utils.metrics import metrics

log = get_logger("coalesce")


class _Item:
    __slots__ = ("sql", "params", "event", "rows", "engine", "error")

    def __init__(self, sql: str, params) -> None:
        self.sql = sql
        self.params = params
        self.event = threading.Event()
        self.rows: Optional[List[dict]] = None
        self.engine: Optional[str] = None
        self.error: Optional[Exception] = None


class _DbWorker:
    """One group-commit loop per database."""

    def __init__(self, db, window_s: float) -> None:
        self.db = db
        self.window_s = window_s
        self._cond = threading.Condition()
        self._pending: List[_Item] = []
        self._stop = False
        self._thread = threading.Thread(
            target=self._run, name=f"coalesce-{db.name}", daemon=True
        )
        self._thread.start()

    def submit(self, item: _Item) -> bool:
        """False when the worker is stopping — the item was NOT queued
        (callers fall back to direct execution): an append after the
        final drain would park the session until its timeout."""
        with self._cond:
            if self._stop:
                return False
            self._pending.append(item)
            self._cond.notify()
            return True

    def stop(self) -> None:
        with self._cond:
            self._stop = True
            self._cond.notify()

    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._pending and not self._stop:
                    self._cond.wait()
                if self._stop:
                    batch, self._pending = self._pending, []
                else:
                    if self.window_s > 0.0:
                        # optional fixed collection window (arrivals
                        # sparser than device time): release the lock so
                        # followers can queue during the wait. Followers'
                        # notify() wakes the wait early, so loop until
                        # the DEADLINE — otherwise the window degrades
                        # to wait-for-one-follower
                        import time as _time

                        deadline = _time.monotonic() + self.window_s
                        while not self._stop:
                            left = deadline - _time.monotonic()
                            if left <= 0:
                                break
                            self._cond.wait(left)
                    batch, self._pending = self._pending, []
            if batch:
                self._execute(batch)
            if self._stop:
                return

    def _execute(self, batch: List[_Item]) -> None:
        from orientdb_tpu.exec.engine import execute_query_batch

        metrics.incr("coalesce.batches")
        metrics.incr("coalesce.items", len(batch))
        if len(batch) > 1:
            metrics.incr("coalesce.grouped", len(batch))
        try:
            results = execute_query_batch(
                self.db,
                [i.sql for i in batch],
                [i.params for i in batch],
            )
            for item, rs in zip(batch, results):
                item.rows = rs.to_dicts()
                item.engine = rs.engine
        except Exception:
            # batch-level failure (one member's error classes the whole
            # call): re-run per item so each session gets ITS error and
            # the innocent members still get results
            metrics.incr("coalesce.batch_fallback")
            for item in batch:
                try:
                    rs = self.db.query(item.sql, item.params)
                    item.rows = rs.to_dicts()
                    item.engine = rs.engine
                except Exception as e:
                    item.error = e
        finally:
            for item in batch:
                item.event.set()


class QueryCoalescer:
    """Server-wide registry of per-database group-commit workers."""

    def __init__(self, window_ms: Optional[float] = None) -> None:
        if window_ms is None:
            window_ms = float(os.environ.get("OTPU_COALESCE_WINDOW_MS", "0"))
        self.window_s = window_ms / 1000.0
        self._workers: Dict[int, _DbWorker] = {}
        self._lock = threading.Lock()
        self._stopped = False
        # evicted databases, held WEAKLY: a submit racing evict() must
        # not resurrect a worker for a dropped db (which would pin it
        # forever), and weak refs mean an id() reused after GC cannot
        # false-positive — the tombstone dies with the object
        import weakref

        self._evicted = weakref.WeakSet()

    def _worker(self, db) -> Optional[_DbWorker]:
        key = id(db)
        w = self._workers.get(key)
        if w is None:
            with self._lock:
                if self._stopped or db in self._evicted:
                    return None  # shutdown/evict raced this: go direct
                w = self._workers.get(key)
                if w is None:
                    w = self._workers[key] = _DbWorker(db, self.window_s)
        return w

    def evict(self, db) -> None:
        """Stop and drop the database's worker (drop_database /
        attach-replace): the worker thread and its strong db reference
        must not outlive the database's registration."""
        with self._lock:
            self._evicted.add(db)
            w = self._workers.pop(id(db), None)
        if w is not None:
            w.stop()

    @staticmethod
    def _coalescable(db, sql: str) -> bool:
        """Only idempotent, non-EXPLAIN statements outside a tx ride the
        batch; everything else executes directly on the caller."""
        if db.tx is not None:
            return False
        try:
            from orientdb_tpu.exec.engine import parse_cached
            from orientdb_tpu.sql import ast as A

            stmt = parse_cached(sql)
            return stmt.is_idempotent and not isinstance(
                stmt, A.ExplainStatement
            )
        except Exception:
            return False  # parse errors surface on the direct path

    def submit(
        self, db, sql: str, params, timeout: float = 120.0
    ) -> Tuple[List[dict], Optional[str]]:
        """Execute `sql` through the database's group path; blocks until
        the result is ready. Returns (rows, engine)."""
        if not self._coalescable(db, sql):
            rs = db.query(sql, params)
            return rs.to_dicts(), rs.engine
        item = _Item(sql, params)
        w = self._worker(db)
        if w is None or not w.submit(item):
            # shutdown raced the submit: serve the query directly rather
            # than park the session until its timeout
            rs = db.query(sql, params)
            return rs.to_dicts(), rs.engine
        if not item.event.wait(timeout):
            raise TimeoutError(f"coalesced query timed out: {sql[:80]}")
        if item.error is not None:
            raise item.error
        return item.rows or [], item.engine

    def stop(self) -> None:
        with self._lock:
            self._stopped = True
            workers, self._workers = list(self._workers.values()), {}
        for w in workers:
            w.stop()
