"""Continuous cross-client micro-batching — fingerprint-keyed lanes.

The round-4 measurement story: the compiled engine's batched dispatch
(`exec/engine.execute_query_batch` → `tpu_engine.dispatch_many`) runs
~60× faster per query than lone dispatches, but only a client shipping
an explicit ``query_batch`` frame could reach it — every other remote
session's query paid a full device round trip alone (BENCH_r04
``phase_split``: 114.6 ms of transfer against 1.8 ms of device time for
a lone 2-hop MATCH). For "millions of users" traffic, batch formation —
not kernels — is the entire game, so this module forms the batches the
clients no longer have to:

- **dispatch lanes**: sessions submit single queries; each lands in a
  per-database lane keyed by the query's FINGERPRINT (``obs/stats``:
  literals folded, case/whitespace normalized — the same id the stats
  table and slowlog join on). A drain therefore produces a HOMOGENEOUS
  micro-batch that replays ONE compiled plan (`tpu_engine.
  dispatch_lane`) instead of a mixed bag re-planned per item; two
  different shapes can never share a micro-batch.
- **adaptive collection window**: each lane learns its recent
  inter-arrival gap and device-time-per-batch (EWMAs) and waits only
  when co-riders are actually likely — sequential lone-client traffic
  (consecutive solo drains) pays ZERO added latency, and the window is
  hard-capped at ``config.coalesce_window_max_ms`` so a single query's
  p50 is bounded by one micro-batch window, never by batch greed. The
  old fixed ``OTPU_COALESCE_WINDOW_MS`` knob is gone; a constructor
  ``window_ms`` (tests) still forces a fixed window.
- **device-resident parameter rings**: each lane owns a
  ``tpu_engine.ParamRing`` — the stacked dynamic-arg pytree of a lane
  dispatch is ``jax.device_put`` once per distinct value set and
  REUSED in place, so steady-state dispatch of repeating parameters
  ships ~zero host bytes (the deviceguard plane proves the path makes
  no implicit transfers).
- **double-buffered dispatch**: the lane worker dispatches micro-batch
  N+1 (forming it and staging its parameters into the other ring slot)
  BEFORE collecting batch N's results, so batch formation and upload
  overlap the device execution in front of them. While a batch's fetch
  blocks, new arrivals queue behind it and drain as the next batch —
  continuous batching, no idle device between drains.

Per-item isolation: statements that cannot ride a batch
(non-idempotent, EXPLAIN, parse errors, active tx) execute directly on
the submitting thread. A batch-level failure (one member's error
classes the whole call) re-runs per item on a DETACHED fallback thread
— each session gets ITS error or rows, and the lane's drain loop stays
hot instead of stalling every follower behind the poisoned cohort.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

from orientdb_tpu.obs.propagation import continue_trace, current_context
from orientdb_tpu.obs.registry import obs
from orientdb_tpu.obs.trace import span
from orientdb_tpu.utils.config import config
from orientdb_tpu.utils.logging import get_logger
from orientdb_tpu.utils.metrics import metrics

log = get_logger("coalesce")

#: consecutive single-item drains after which a lane stops windowing —
#: the traffic is sequential (one client awaiting each result), so a
#: collection wait only taxes that client; overlap re-arms it the
#: moment a drain catches more than one rider
_SOLO_OFF = 3


class _Item:
    __slots__ = (
        "sql",
        "params",
        "event",
        "rows",
        "engine",
        "error",
        "ctx",
        "t_enq",
        "epoch",
        "segs",
    )

    def __init__(self, sql: str, params) -> None:
        self.sql = sql
        self.params = params
        self.event = threading.Event()
        self.rows: Optional[List[dict]] = None
        self.engine: Optional[str] = None
        self.error: Optional[Exception] = None
        #: the submitter's trace context: the lane's dispatch span
        #: CONTINUES the first rider's trace (obs/propagation)
        self.ctx: Optional[Dict] = None
        self.t_enq: float = 0.0
        #: this item's amortized critical-path decomposition, built on
        #: the lane worker and merged into the SUBMITTER's request
        #: record when it wakes (obs/critpath.merge)
        self.segs: Optional[Dict[str, float]] = None
        #: db.mutation_epoch at ADMISSION: the lane dispatch refuses to
        #: serve this item from a snapshot older than every write that
        #: completed before the item was submitted (epoch keying — a
        #: lane window formed pre-write cannot serve post-write queries
        #: stale results)
        self.epoch: int = 0


class _Lane:
    """One fingerprint's dispatch lane: a bounded queue drained by a
    dedicated worker into homogeneous micro-batches."""

    def __init__(self, coal: "QueryCoalescer", db, fid: str) -> None:
        self.coal = coal
        self.db = db
        self.fid = fid
        self._cond = threading.Condition()
        self._pending: List[_Item] = []
        self._stop = False
        self._last_arrival: Optional[float] = None
        self._gap_ewma: Optional[float] = None  # arrival gap, seconds
        self._exec_ewma: Optional[float] = None  # batch execute wall, s
        self._solo_drains = _SOLO_OFF  # start windowless: no tax on firsts
        self._last_window = 0.0  # last adaptive window chosen (gauges)
        #: items the worker is currently executing (this drain + the
        #: double-buffered in-flight batch): the death guard must fail
        #: these too, not only the still-queued ones
        self._active: List[_Item] = []
        #: opaque engine staging state — exec/engine keeps the lane's
        #: device-resident ParamRing here, so this module stays jax-free
        self._ring_state: Dict = {}
        self._thread = threading.Thread(
            target=self._run,
            name=f"coalesce-{db.name}:{fid[:8]}",
            daemon=True,
        )
        self._thread.start()

    # -- producer side -------------------------------------------------------

    def submit(self, item: _Item) -> bool:
        """False when the lane is retiring — the item was NOT queued
        (the coalescer builds a fresh lane or goes direct): an append
        after the final drain would park the session until timeout."""
        now = time.monotonic()
        with self._cond:
            if self._stop:
                return False
            if self._last_arrival is not None:
                gap = now - self._last_arrival
                self._gap_ewma = (
                    gap
                    if self._gap_ewma is None
                    else 0.8 * self._gap_ewma + 0.2 * gap
                )
                if (
                    self._exec_ewma is not None
                    and gap < self._exec_ewma
                ):
                    # arrivals outpace service: genuine overlap, even
                    # if windowless drains keep catching singletons (a
                    # 2-client ping-pong never queues two at once) —
                    # re-arm the window so co-riders can merge
                    self._solo_drains = 0
            self._last_arrival = now
            item.t_enq = now
            self._pending.append(item)
            self._cond.notify()
            return True

    def stop(self) -> None:
        with self._cond:
            self._stop = True
            self._cond.notify()

    def last_arrival_ts(self) -> float:
        return self._last_arrival or 0.0

    # -- adaptive window -----------------------------------------------------

    def _window_s(self) -> float:
        """The collection window for the NEXT drain (caller holds
        ``_cond``). A fixed coalescer-level window (tests, back-compat)
        wins; otherwise: no wait while traffic looks sequential or
        arrivals are sparser than the cap, else wait about one batch's
        device time (co-riders accumulate while the device would be
        busy anyway), floored at two arrival gaps and hard-capped."""
        fixed = self.coal.window_s
        if fixed > 0.0:
            return fixed
        if self._solo_drains >= _SOLO_OFF:
            return 0.0
        cap = max(0.0, float(config.coalesce_window_max_ms)) / 1000.0
        if cap <= 0.0 or self._gap_ewma is None or self._gap_ewma > cap:
            return 0.0
        want = (
            self._exec_ewma
            if self._exec_ewma is not None
            else 2.0 * self._gap_ewma
        )
        return min(cap, max(want, 2.0 * self._gap_ewma))

    # -- worker side ---------------------------------------------------------

    def _run(self) -> None:
        try:
            self._run_loop()
        except BaseException as e:
            # a dying worker must not wedge its fingerprint: fail the
            # queued items LOUDLY, retire, and let the next submit
            # build a fresh lane (already-delivered items are fine)
            with self._cond:
                self._stop = True
                orphans = self._pending + [
                    i for i in self._active if not i.event.is_set()
                ]
                self._pending = []
                self._active = []
            for item in orphans:
                item.error = RuntimeError(
                    f"coalesce lane worker died: {type(e).__name__}: {e}"
                )
                item.event.set()
            self.coal._drop_lane(self)
            raise

    def _run_loop(self) -> None:
        inflight: Optional[Tuple[List[_Item], object, float]] = None
        while True:
            batch = self._collect(block=inflight is None)
            with self._cond:
                self._active = list(batch) + (
                    list(inflight[0]) if inflight else []
                )
            handle = None
            t0 = 0.0
            if batch:
                metrics.incr("coalesce.batches")
                metrics.incr("coalesce.items", len(batch))
                if len(batch) > 1:
                    metrics.incr("coalesce.grouped", len(batch))
                obs.observe_size("coalesce.batch_size", float(len(batch)))
                t0 = time.monotonic()
                # dispatch N+1 BEFORE collecting N (double buffering):
                # the new batch's params stage into the ring's other
                # slot and its Execute queues behind N's on device
                handle = self._dispatch(batch)
            if inflight is not None:
                self._finish(*inflight)
                inflight = None
            if batch:
                if handle is not None:
                    inflight = (batch, handle, t0)
                else:
                    self._execute_generic(batch, t0)
            if inflight is None:
                with self._cond:
                    done = self._stop and not self._pending
                if done:
                    self.coal._drop_lane(self)
                    return

    def _collect(self, block: bool) -> List[_Item]:
        """Drain up to ``coalesce_max_batch`` items. ``block=False``
        (an in-flight batch is executing — ITS fetch is the real wait)
        returns whatever is queued right now, window-free: continuous
        batching forms the next batch from the backlog that built up
        behind the device."""
        cap = max(1, int(config.coalesce_max_batch))
        with self._cond:
            if block and not self._pending and not self._stop:
                self._wait_locked()
            if block and self._pending and not self._stop:
                self._window_wait_locked()
            batch = self._pending[:cap]
            del self._pending[:cap]
            if len(batch) > 1:
                self._solo_drains = 0
            elif batch:
                self._solo_drains += 1
            depth, window = len(self._pending), self._last_window
        self.coal._note_drain(self, depth, window)
        return batch

    def _wait_locked(self) -> None:
        """Idle wait for traffic; a lane idle past
        ``coalesce_lane_idle_s`` retires its worker (a fresh submit
        builds a new lane)."""
        idle_s = max(0.0, float(config.coalesce_lane_idle_s))
        deadline = time.monotonic() + idle_s if idle_s > 0 else None
        while not self._pending and not self._stop:
            left = None if deadline is None else deadline - time.monotonic()
            if left is not None and left <= 0:
                self._stop = True
                return
            self._cond.wait(left if left is not None else 1.0)

    def _window_wait_locked(self) -> None:
        """Hold the drain for the adaptive window so co-riders can
        join. Followers' notify() wakes the wait early, so loop until
        the DEADLINE — otherwise the window degrades to
        wait-for-one-follower. A full batch drains immediately."""
        w = self._window_s()
        self._last_window = w
        if w <= 0.0:
            return
        cap = max(1, int(config.coalesce_max_batch))
        deadline = time.monotonic() + w
        while not self._stop and len(self._pending) < cap:
            left = deadline - time.monotonic()
            if left <= 0:
                return
            self._cond.wait(left)

    def _dispatch(self, batch: List[_Item]):
        """Non-blocking lane dispatch (`exec/engine.dispatch_lane_batch`
        — one cached plan, ring-staged params). None routes the batch
        to the generic blocking path (first execution, oracle shapes,
        group executable still compiling)."""
        from orientdb_tpu.exec.engine import dispatch_lane_batch

        try:
            return dispatch_lane_batch(
                self.db,
                [i.sql for i in batch],
                [i.params for i in batch],
                ring_state=self._ring_state,
                # flight-recorder context (obs/timeline): when the
                # first rider entered the lane, and the collection
                # window that formed this micro-batch
                enqueue_ts=min(i.t_enq for i in batch),
                window_s=self._last_window,
                # epoch keying: the snapshot must cover every rider's
                # admission epoch or the batch takes the generic path
                min_epoch=max(i.epoch for i in batch),
            )
        except Exception:
            # eligibility probing must never kill the drain loop; the
            # generic path will execute (and surface) this batch
            log.exception("lane dispatch probe failed; using generic path")
            return None

    def _finish(self, batch: List[_Item], handle, t0: float) -> None:
        """Collect a double-buffered dispatch: fetch, marshal, deliver.
        The span continues the FIRST submitter's trace — the dispatch
        is theirs; co-riders join via their own coalesce.lane spans."""
        ctx = next((i.ctx for i in batch if i.ctx), None)
        try:
            waits = [max(0.0, t0 - i.t_enq) for i in batch]
            with continue_trace(
                "coalesce.dispatch",
                ctx,
                lane=self.fid,
                n=len(batch),
                mode="lane",
            ):
                results = handle.collect(queue_waits=waits)
            item_segs = getattr(handle, "item_segs", None) or []
            for k, (item, rs) in enumerate(zip(batch, results)):
                t_m = time.monotonic()
                item.rows = rs.to_dicts()
                segs = dict(item_segs[k]) if k < len(item_segs) else {}
                segs["marshal"] = (
                    segs.get("marshal", 0.0) + time.monotonic() - t_m
                )
                item.segs = segs
                item.engine = rs.engine
            for item in batch:
                item.event.set()
            self._observe_exec(time.monotonic() - t0)
        except Exception:
            metrics.incr("coalesce.batch_fallback")
            self._fallback_async(batch)

    def _execute_generic(self, batch: List[_Item], t0: float) -> None:
        """The blocking batch path (records first executions, serves
        oracle shapes). A batch-level failure falls back per item OFF
        this thread — head-of-line isolation: the drain loop keeps
        forming and dispatching micro-batches while the poisoned
        cohort sorts itself out on a fallback thread."""
        import orientdb_tpu.obs.critpath as CP
        import orientdb_tpu.obs.stats as S
        from orientdb_tpu.exec.engine import execute_query_batch

        ctx = next((i.ctx for i in batch if i.ctx), None)
        n = max(len(batch), 1)
        try:
            # worker-side harvest record: execute_query_batch's front
            # door JOINS it (never commits), so its fold lands the whole
            # batch's device/transfer/plan/host split here for the per-
            # item amortization below
            harvest = (
                CP.CritPath("lane") if config.critpath_enabled else None
            )
            with continue_trace(
                "coalesce.dispatch",
                ctx,
                lane=self.fid,
                n=len(batch),
                mode="batch",
            ):
                with CP.active(harvest):
                    results = execute_query_batch(
                        self.db,
                        [i.sql for i in batch],
                        [i.params for i in batch],
                    )
            per_segs = (
                {k: v / n for k, v in harvest.segs.items()}
                if harvest is not None
                else {}
            )
            # materialize INSIDE the try: a lazily-raising result (an
            # oracle row stream erroring in to_dicts) must route to the
            # per-item fallback, never escape and kill the drain loop
            for item, rs in zip(batch, results):
                t_m = time.monotonic()
                item.rows = rs.to_dicts()
                segs = dict(per_segs)
                segs["queue"] = (
                    segs.get("queue", 0.0) + max(0.0, t0 - item.t_enq)
                )
                segs["marshal"] = (
                    segs.get("marshal", 0.0) + time.monotonic() - t_m
                )
                item.segs = segs
                item.engine = rs.engine
                S.stats.record_queue(item.sql, max(0.0, t0 - item.t_enq))
        except Exception:
            metrics.incr("coalesce.batch_fallback")
            self._fallback_async(batch)
            return
        for item in batch:
            item.event.set()
        self._observe_exec(time.monotonic() - t0)

    def _fallback_async(self, batch: List[_Item]) -> None:
        threading.Thread(
            target=self._fallback_run,
            args=(batch,),
            name=f"coalesce-fb-{self.db.name}",
            daemon=True,
        ).start()

    def _fallback_run(self, batch: List[_Item]) -> None:
        """Per-item re-run of a failed batch: each session gets ITS
        error and the innocent members still get results. Bounded by
        the coalescer-wide semaphore so a poison storm cannot spawn
        unbounded threads."""
        with self.coal._fb_sem:
            for item in batch:
                try:
                    rs = self.db.query(item.sql, item.params)
                    item.rows = rs.to_dicts()
                    item.engine = rs.engine
                except Exception as e:
                    item.error = e
                finally:
                    item.event.set()

    def _observe_exec(self, dur_s: float) -> None:
        with self._cond:
            self._exec_ewma = (
                dur_s
                if self._exec_ewma is None
                else 0.7 * self._exec_ewma + 0.3 * dur_s
            )


class QueryCoalescer:
    """Server-wide registry of per-database, per-fingerprint lanes."""

    def __init__(self, window_ms: Optional[float] = None) -> None:
        #: fixed collection window override (seconds). 0 = adaptive
        #: per-lane windows (the default); tests and the old API set a
        #: fixed one to make grouping deterministic on loaded runners.
        self.window_s = (float(window_ms) / 1000.0) if window_ms else 0.0
        #: id(db) → {fingerprint id → lane}
        self._lanes: Dict[int, Dict[str, _Lane]] = {}
        self._lock = threading.Lock()
        self._stopped = False
        #: bounds concurrent per-item fallback threads (poison storms)
        self._fb_sem = threading.BoundedSemaphore(4)
        #: per-lane drain gauges folded into ONE process gauge each —
        #: 64 lanes overwriting a flat gauge would export whichever
        #: lane drained last; publish the SUM of backlogs and the MAX
        #: window instead (leaf lock: never held while taking others)
        self._gauge_lock = threading.Lock()
        self._depths: Dict[int, int] = {}
        self._windows: Dict[int, float] = {}
        # evicted databases, held WEAKLY: a submit racing evict() must
        # not resurrect a lane for a dropped db (which would pin it
        # forever), and weak refs mean an id() reused after GC cannot
        # false-positive — the tombstone dies with the object
        import weakref

        self._evicted = weakref.WeakSet()

    # -- lane registry -------------------------------------------------------

    def _lane(self, db, fid: str) -> Optional[_Lane]:
        key = id(db)
        lanes = self._lanes.get(key)
        if lanes is not None:
            lane = lanes.get(fid)
            if lane is not None:
                return lane
        victims: List[_Lane] = []
        with self._lock:
            if self._stopped or db in self._evicted:
                return None  # shutdown/evict raced this: go direct
            lanes = self._lanes.setdefault(key, {})
            lane = lanes.get(fid)
            if lane is None:
                cap = max(1, int(config.coalesce_lanes_max))
                while len(lanes) >= cap:
                    # reap the longest-idle lane: its worker drains any
                    # queued items and retires
                    victim = min(
                        lanes.values(), key=_Lane.last_arrival_ts
                    )
                    lanes.pop(victim.fid, None)
                    victims.append(victim)
                lane = lanes[fid] = _Lane(self, db, fid)
            total = sum(len(d) for d in self._lanes.values())
        metrics.gauge("coalesce.lanes", float(total))
        for v in victims:  # outside the registry lock (takes lane conds)
            v.stop()
        return lane

    def _note_drain(self, lane: _Lane, depth: int, window_s: float) -> None:
        """Fold one lane's drain observation into the aggregate
        gauges: total queued backlog across lanes, worst adaptive
        window currently in force."""
        with self._gauge_lock:
            self._depths[id(lane)] = depth
            self._windows[id(lane)] = window_s
            depth_total = sum(self._depths.values())
            window_max = max(self._windows.values())
        metrics.gauge("coalesce.lane_depth", float(depth_total))
        metrics.gauge("coalesce.window_ms", round(window_max * 1000.0, 3))

    def _forget_gauges(self, lane: _Lane) -> None:
        with self._gauge_lock:
            self._depths.pop(id(lane), None)
            self._windows.pop(id(lane), None)

    def _drop_lane(self, lane: _Lane) -> None:
        """Remove a retired lane from the registry (identity-checked: a
        replacement lane under the same key must survive)."""
        with self._lock:
            lanes = self._lanes.get(id(lane.db))
            if lanes is not None and lanes.get(lane.fid) is lane:
                lanes.pop(lane.fid)
                if not lanes:
                    self._lanes.pop(id(lane.db), None)
            total = sum(len(d) for d in self._lanes.values())
        self._forget_gauges(lane)
        metrics.gauge("coalesce.lanes", float(total))

    def evict(self, db) -> None:
        """Stop and drop the database's lanes (drop_database /
        attach-replace): lane worker threads and their strong db
        references must not outlive the database's registration."""
        with self._lock:
            self._evicted.add(db)
            lanes = self._lanes.pop(id(db), None)
        for lane in (lanes or {}).values():
            self._forget_gauges(lane)
            lane.stop()

    def stop(self) -> None:
        with self._lock:
            self._stopped = True
            all_lanes = [
                lane
                for lanes in self._lanes.values()
                for lane in lanes.values()
            ]
            self._lanes = {}
        for lane in all_lanes:
            self._forget_gauges(lane)
            lane.stop()

    # -- submission ----------------------------------------------------------

    @staticmethod
    def _coalescable(db, sql: str) -> bool:
        """Only idempotent, non-EXPLAIN statements outside a tx ride a
        lane; everything else executes directly on the caller."""
        if db.tx is not None:
            return False
        try:
            from orientdb_tpu.exec.engine import parse_cached
            from orientdb_tpu.sql import ast as A

            stmt = parse_cached(sql)
            return stmt.is_idempotent and not isinstance(
                stmt, A.ExplainStatement
            )
        except Exception:
            return False  # parse errors surface on the direct path

    def submit(
        self, db, sql: str, params, timeout: float = 120.0
    ) -> Tuple[List[dict], Optional[str]]:
        """Execute ``sql`` through the database's lane for its
        fingerprint; blocks until the result is ready. Returns
        ``(rows, engine)``."""
        if not self._coalescable(db, sql):
            rs = db.query(sql, params)
            return rs.to_dicts(), rs.engine
        # materialized-view fast path (exec/views): a CDC-valid resident
        # result beats any micro-batch — served before lane formation,
        # so hot fingerprints cost neither a window nor a dispatch
        from orientdb_tpu.exec.engine import _normalize_params
        from orientdb_tpu.exec.views import views_for

        vm = views_for(db) if db.tx is None else None
        if vm is not None:
            view = vm.lookup(sql, _normalize_params(params), None, False)
            if view is not None:
                return (
                    [
                        r if isinstance(r, dict) else r.to_dict()
                        for r in view.rows
                    ],
                    view.engine,
                )
        from orientdb_tpu.obs.stats import fingerprint_cached

        fid = fingerprint_cached(sql).fid
        item = _Item(sql, params)
        item.ctx = current_context()
        item.epoch = db.mutation_epoch
        with span("coalesce.lane", lane=fid) as sp:
            queued = False
            for _attempt in (0, 1):
                lane = self._lane(db, fid)
                if lane is None:
                    break
                if lane.submit(item):
                    queued = True
                    break
                # the lane retired between lookup and submit: drop it
                # and retry once with a fresh one
                self._drop_lane(lane)
            if not queued:
                # shutdown/evict raced the submit: serve the query
                # directly rather than park the session until timeout
                rs = db.query(sql, params)
                return rs.to_dicts(), rs.engine
            if not item.event.wait(timeout):
                raise TimeoutError(f"coalesced query timed out: {sql[:80]}")
            sp.set("engine", item.engine)
        if item.error is not None:
            raise item.error
        # fold the lane-built decomposition into THIS session's request
        # record — the amortized segments are sub-intervals of the wait
        # the submitter just paid, so its segment sum tracks its wall
        import orientdb_tpu.obs.critpath as CP

        CP.merge(item.segs)
        return item.rows or [], item.engine
