"""Studio-lite: the embedded web UI.

Analog of OrientDB Studio ([E] the separate studio webapp bundled into
the server distribution and served under /studio; SURVEY.md §2 "Studio
(web UI)"). Redesign: instead of a build-step SPA, one self-contained
HTML page served by the REST listener, speaking the same REST endpoints
every other client uses (listDatabases, database/<db>, query/<db>/sql,
command/<db>/sql, metrics) with Basic credentials held client-side.
Covers Studio's core workflows: connect, browse classes, run SQL/MATCH,
inspect results, watch server metrics.
"""

STUDIO_HTML = """<!doctype html>
<html>
<head>
<meta charset="utf-8">
<title>orientdb-tpu studio</title>
<style>
  :root { --bg:#14161a; --panel:#1d2026; --line:#2c313a; --fg:#e6e8eb;
          --dim:#9aa3af; --acc:#f0894d; }
  * { box-sizing:border-box; }
  body { margin:0; background:var(--bg); color:var(--fg);
         font:14px/1.45 system-ui, sans-serif; }
  header { display:flex; gap:8px; align-items:center; padding:10px 16px;
           background:var(--panel); border-bottom:1px solid var(--line); }
  header b { color:var(--acc); margin-right:8px; }
  input, select, textarea, button {
    background:var(--bg); color:var(--fg); border:1px solid var(--line);
    border-radius:6px; padding:6px 8px; font:inherit; }
  button { cursor:pointer; background:var(--acc); color:#14161a;
           border:none; font-weight:600; }
  main { display:grid; grid-template-columns: 230px 1fr; gap:0;
         height:calc(100vh - 53px); }
  #classes { border-right:1px solid var(--line); overflow:auto;
             padding:10px; }
  #classes .cls { padding:5px 8px; border-radius:6px; cursor:pointer;
                  display:flex; justify-content:space-between; }
  #classes .cls:hover { background:var(--panel); }
  #classes .n { color:var(--dim); }
  #work { display:flex; flex-direction:column; overflow:hidden; }
  #sql { width:100%; height:90px; resize:vertical; font-family:monospace;
         border-radius:0; border:none;
         border-bottom:1px solid var(--line); }
  #bar { display:flex; gap:8px; padding:8px; align-items:center; }
  #status { color:var(--dim); }
  #out { overflow:auto; flex:1; padding:0 8px 8px; }
  table { border-collapse:collapse; width:100%; font-family:monospace;
          font-size:13px; }
  th, td { border:1px solid var(--line); padding:4px 8px; text-align:left;
           max-width:420px; overflow:hidden; text-overflow:ellipsis;
           white-space:nowrap; }
  th { background:var(--panel); position:sticky; top:0; }
  .err { color:#ef6a6a; padding:8px; font-family:monospace; }
</style>
</head>
<body>
<header>
  <b>orientdb-tpu</b>
  <input id="user" placeholder="user" value="admin" size="8">
  <input id="pw" type="password" placeholder="password" size="10">
  <select id="db"></select>
  <button onclick="connect()">Connect</button>
  <span id="status">not connected</span>
  <span style="flex:1"></span>
  <button onclick="showMetrics()" style="background:var(--panel);color:var(--fg)">Metrics</button>
</header>
<main>
  <div id="classes"></div>
  <div id="work">
    <textarea id="sql" placeholder="MATCH {class:V, as:v} RETURN v.name LIMIT 20"></textarea>
    <div id="bar">
      <button onclick="run()">Run (Ctrl+Enter)</button>
      <span id="status2" class="n"></span>
    </div>
    <div id="out"></div>
  </div>
</main>
<script>
let auth = null;
const $ = id => document.getElementById(id);
// every server-derived string passes through esc() before innerHTML —
// stored property values, class/column names, and error text are all
// user-controlled and must not execute in the operator's session
const esc = s => String(s).replace(/[&<>"']/g,
  c => ({"&":"&amp;","<":"&lt;",">":"&gt;",'"':"&quot;","'":"&#39;"}[c]));
function hdrs() { return auth ? {"Authorization": "Basic " + auth} : {}; }
async function api(path, opts) {
  const r = await fetch(path, Object.assign({headers: hdrs()}, opts || {}));
  if (!r.ok) throw new Error((await r.text()).slice(0, 500));
  return r.json();
}
async function connect() {
  auth = btoa($("user").value + ":" + $("pw").value);
  try {
    const d = await api("/listDatabases");
    const sel = $("db"), cur = sel.value;
    sel.innerHTML = d.databases.map(n => `<option>${esc(n)}</option>`).join("");
    if (d.databases.includes(cur)) sel.value = cur;
    $("status").textContent = "connected (" + d.databases.length + " dbs)";
    loadClasses();
  } catch (e) { $("status").textContent = "auth failed"; auth = null; }
}
async function loadClasses() {
  if (!$("db").value) { $("classes").innerHTML = ""; return; }
  const d = await api("/database/" + encodeURIComponent($("db").value));
  // class names ride in a data attribute read back via dataset — no
  // inline-handler string interpolation to break out of
  $("classes").innerHTML = d.classes
    .sort((a, b) => a.name.localeCompare(b.name))
    .map(c => `<div class="cls" data-cls="${esc(c.name)}">` +
              `<span>${esc(c.name)}</span>` +
              `<span class="n">${esc(c.records)}</span></div>`)
    .join("");
}
$("classes").addEventListener("click", e => {
  const el = e.target.closest(".cls");
  if (el) browse(el.dataset.cls);
});
function browse(cls) {
  $("sql").value = "SELECT FROM `" + cls + "` LIMIT 30";
  run();
}
function render(rows) {
  if (!rows.length) { $("out").innerHTML = '<p class="n">0 rows</p>'; return; }
  const cols = [...new Set(rows.flatMap(r => Object.keys(r)))];
  $("out").innerHTML = "<table><tr>" +
    cols.map(c => `<th>${esc(c)}</th>`).join("") + "</tr>" +
    rows.map(r => "<tr>" + cols.map(c =>
      `<td>${r[c] === null || r[c] === undefined ? "" : esc(JSON.stringify(r[c]))}</td>`
    ).join("") + "</tr>").join("") + "</table>";
}
async function run() {
  const sql = $("sql").value.trim(), db = $("db").value;
  if (!sql || !db) return;
  const t0 = performance.now();
  $("status2").textContent = "running…";
  try {
    const d = await api(
      "/command/" + encodeURIComponent(db) + "/sql",
      {method: "POST", body: JSON.stringify({command: sql})});
    render(d.result || []);
    $("status2").textContent = (d.result || []).length + " rows in " +
      Math.round(performance.now() - t0) + " ms";
    loadClasses();
  } catch (e) {
    $("out").innerHTML = `<div class="err">${esc(e.message)}</div>`;
    $("status2").textContent = "error";
  }
}
async function showMetrics() {
  const d = await api("/metrics?format=json");
  const rows = Object.entries(d.counters || {})
    .map(([k, v]) => ({metric: k, value: v}))
    .concat(Object.entries(d.durations || {}).map(([k, v]) =>
      ({metric: k, value: v.count + "x, total " +
        (v.total_s * 1000).toFixed(1) + " ms"})));
  render(rows);
  $("status2").textContent = "server metrics";
}
$("sql").addEventListener("keydown", e => {
  if (e.key === "Enter" && (e.ctrlKey || e.metaKey)) { e.preventDefault(); run(); }
});
$("db").addEventListener("change", loadClasses);
</script>
</body>
</html>
"""
