"""The server process ([E] OServer / OServerMain, SURVEY.md §3.1).

Hosts named databases, a security manager, a plugin registry (the
OServerPluginAbstract seam the north star hooks into), and two listeners:
HTTP/REST (`http_server`, the port-2480 analog) and the length-prefixed
binary channel (`binary_server`, the port-2424 analog). Listeners bind
ephemeral ports by default so in-process multi-server tests work exactly
like the reference's multi-OServer-per-JVM distributed tests
(SURVEY.md §4).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from orientdb_tpu.models.database import Database
from orientdb_tpu.models.security import SecurityManager
from orientdb_tpu.utils.logging import get_logger

log = get_logger("server")


def _maybe_resume_scheduler(db) -> None:
    """Start the database's scheduler loop when OSchedule events exist
    ([E] the scheduler starts with the database). Shared by the open
    and server-restart paths; never blocks either."""
    try:
        from orientdb_tpu.exec.scheduler import SCHEDULE_CLASS

        if db.schema.exists_class(SCHEDULE_CLASS) and any(
            True for _ in db.browse_class(SCHEDULE_CLASS)
        ):
            db.scheduler.start()
    except Exception:  # pragma: no cover - never blocks open/startup
        log.exception("scheduler resume failed for '%s'", db.name)


class ServerPlugin:
    """Lifecycle SPI ([E] OServerPluginAbstract): subclass and register."""

    name = "plugin"

    def config(self, server: "Server", params: Dict) -> None:  # noqa: D401
        pass

    def startup(self) -> None:
        pass

    def shutdown(self) -> None:
        pass


class Server:
    def __init__(
        self,
        name: str = "orientdb-tpu",
        admin_password: str = "admin",
        http_port: int = 0,
        binary_port: int = 0,
    ) -> None:
        self.name = name
        self.databases: Dict[str, Database] = {}
        self.security = SecurityManager(admin_password)
        # audit trail ([E] the security module's auditing plugin): auth
        # events always; attach databases via audit.watch_database
        from orientdb_tpu.server.audit import AuditLog

        self.audit = AuditLog()
        self.security.audit = self.audit
        self.plugins: List[ServerPlugin] = []
        # cross-session query coalescing (server/coalesce.py): concurrent
        # sessions' single queries ride one batched device dispatch
        from orientdb_tpu.server.coalesce import QueryCoalescer

        self.coalescer = QueryCoalescer()
        #: the cluster coordinator this server is a member of, set by
        #: parallel/cluster.Cluster at registration — the aggregation
        #: endpoints (/cluster/health, /cluster/metrics; obs/
        #: cluster_view) read it; None for a standalone server
        self.cluster = None
        self._lock = threading.Lock()
        self._watchdog = None
        self._http = None
        self._binary = None
        self._http_port = http_port
        self._binary_port = binary_port
        self.running = False

    # -- databases ----------------------------------------------------------

    _DB_NAME_RE = None  # compiled lazily

    @classmethod
    def _check_db_name(cls, name: str) -> None:
        """Database names become directory names under wal_dir — reject
        anything that could traverse out of it (client-supplied via the
        HTTP/binary create-database endpoints)."""
        import re

        if cls._DB_NAME_RE is None:
            cls._DB_NAME_RE = re.compile(r"[A-Za-z0-9_][A-Za-z0-9_.\-]*\Z")
        if (
            not name
            or len(name) > 128
            or ".." in name
            or not cls._DB_NAME_RE.match(name)
        ):
            raise ValueError(f"invalid database name {name!r}")

    def create_database(self, name: str) -> Database:
        with self._lock:
            self._check_db_name(name)
            if name in self.databases:
                raise ValueError(f"database '{name}' exists")
            from orientdb_tpu.utils.config import config

            if config.wal_enabled and config.wal_dir:
                # durable server databases: recover-or-create under
                # <wal_dir>/<name> (the plocal-analog path)
                from orientdb_tpu.storage.durability import open_database

                import os

                db = open_database(os.path.join(config.wal_dir, name), name)
            else:
                db = Database(name)
            # SQL GRANT/REVOKE/CREATE USER on this database mutate the
            # SERVER's security manager (exec/dml._security_of)
            db._security = self.security
            self.databases[name] = db
            # a durable database recovered with OSchedule events resumes
            # firing them ([E] the scheduler starts with the database)
            _maybe_resume_scheduler(db)
            return db

    def get_database(self, name: str) -> Optional[Database]:
        return self.databases.get(name)

    def drop_database(self, name: str) -> bool:
        with self._lock:
            db = self.databases.pop(name, None)
        if db is not None:
            # the coalescer's worker thread must not outlive (and pin)
            # the dropped database — nor may its scheduler keep firing
            # functions into a detached store
            self.coalescer.evict(db)
            sch = getattr(db, "_scheduler", None)
            if sch is not None:
                sch.stop()
        return db is not None

    def attach_database(self, db: Database) -> Database:
        with self._lock:
            old = self.databases.get(db.name)
            self.databases[db.name] = db
        if old is not None and old is not db:
            self.coalescer.evict(old)
        return db

    # -- plugins ------------------------------------------------------------

    def register_plugin(self, plugin: ServerPlugin, params: Optional[Dict] = None):
        plugin.config(self, params or {})
        self.plugins.append(plugin)
        if self.running:
            plugin.startup()
        return plugin

    # -- lifecycle ----------------------------------------------------------

    def startup(self) -> "Server":
        from orientdb_tpu.server.binary_server import BinaryListener
        from orientdb_tpu.server.coalesce import QueryCoalescer
        from orientdb_tpu.server.http_server import HttpListener

        if self.coalescer._stopped:
            # shutdown() stops the coalescer permanently; a restarted
            # server must not silently lose the cross-session group path
            self.coalescer = QueryCoalescer()
        # symmetric with shutdown()'s scheduler stop: databases still
        # attached with OSchedule events resume firing
        for db in list(self.databases.values()):
            _maybe_resume_scheduler(db)
        for p in self.plugins:
            p.startup()
        self._http = HttpListener(self, self._http_port)
        self._http.start()
        self._binary = BinaryListener(self, self._binary_port)
        self._binary.start()
        # scrape-time memory telemetry over this server's databases
        # (snapshot column/adjacency bytes, WAL segment bytes —
        # obs/profile refreshes them on every /metrics snapshot)
        from orientdb_tpu.obs.profile import register_server_telemetry

        self._telemetry_provider = register_server_telemetry(self)
        # health watchdog (obs/watchdog): periodic alert-rule
        # evaluation over this server's databases + cluster — started
        # and stopped with the server, like Cluster's probe thread
        from orientdb_tpu.utils.config import config

        if config.watchdog_enabled:
            from orientdb_tpu.obs.watchdog import HealthWatchdog

            self._watchdog = HealthWatchdog(self).start()
        self.running = True
        log.info(
            "server '%s' up: http=%d binary=%d",
            self.name,
            self.http_port,
            self.binary_port,
        )
        return self

    def shutdown(self) -> None:
        self.running = False
        wd = self._watchdog
        if wd is not None:
            self._watchdog = None
            wd.stop()
        for p in self.plugins:
            try:
                p.shutdown()
            except Exception:
                log.exception("plugin %s shutdown failed", p.name)
        if self._http is not None:
            self._http.stop()
        if self._binary is not None:
            self._binary.stop()
        provider = getattr(self, "_telemetry_provider", None)
        if provider is not None:
            from orientdb_tpu.obs.profile import unregister_gauge_provider

            unregister_gauge_provider(provider)
            self._telemetry_provider = None
        self.coalescer.stop()
        for db in list(self.databases.values()):
            sch = getattr(db, "_scheduler", None)
            if sch is not None:
                sch.stop()

    @property
    def http_port(self) -> int:
        return self._http.port if self._http else self._http_port

    @property
    def binary_port(self) -> int:
        return self._binary.port if self._binary else self._binary_port

    def __enter__(self) -> "Server":
        return self.startup()

    def __exit__(self, *exc) -> None:
        self.shutdown()
