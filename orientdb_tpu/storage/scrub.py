"""Device-state scrubber: host-truth checksums over resident HBM.

PRs 15-16 made the device state under every cached plan MUTABLE —
delta-slab scatters, tier-pool paging, epoch compaction swaps — so a
mis-applied patch or a torn page upload silently serves wrong rows at
full speed. The host side of every one of those writes keeps the truth
(the delta maintainer patches host mirrors in lockstep with its device
scatters; a tier partition's ``host`` arrays back every pool block), so
corruption is DETECTABLE: re-fetch a device block, re-hash it, compare
with the host-truth checksum.

Mechanics:

- **checksums** — zlib.crc32 per device key, computed from host truth
  and cached; ``DeviceGraph._put`` / ``apply_patches`` mark patched
  keys dirty (``_scrub_dirty``) so the cache re-hashes exactly what
  changed. Tier-pool keys (``t:*``) are checked block-wise against
  ``_Partition.block_values`` under the tier lock — per-block CRCs,
  since non-resident pages carry deliberately stale rows.
- **sweep** — watchdog-driven (``HealthWatchdog.tick``; also callable
  directly): a budgeted rotation (``scrub_budget_bytes`` per sweep,
  round-robin cursor per DeviceGraph) fetches device blocks, re-hashes,
  compares. Mesh-sharded graphs are skipped (replicated uploads are
  immutable; the mesh plane has no host-patched state).
- **repair ladder** — a mismatch is repaired loudly, cheapest rung
  first: (1) tier-block invalidate + reload (PR 16 ``_evict`` +
  ``_ensure_blocks``), (2) delta-overlay poison → epoch compaction
  (PR 15 — the maintainer rebuilds a clean CSR and re-uploads), (3)
  full snapshot re-upload (``release_device`` + DeviceGraph rebuild).
  Every detection counts ``scrub.corruptions`` and fires the
  ``scrub_corruption`` alert until a later sweep passes clean.

Deterministically provable: the ``scrub.flip`` chaos point corrupts
the DEVICE-BOUND copy of a delta-patch segment
(``ops/device_graph.apply_patches``) or a tier-pool block row
(``storage/tiering._load_blocks``) — host truth keeps the original, so
a seeded :class:`~orientdb_tpu.chaos.faults.FaultPlan` drives detect →
repair → alert → clean-sweep-resolve end to end in tests.
"""

from __future__ import annotations

import threading
import time
import zlib
from collections import deque
from typing import Dict, List, Optional, Tuple

import numpy as np

from orientdb_tpu.utils.config import config
from orientdb_tpu.utils.logging import get_logger
from orientdb_tpu.utils.metrics import metrics

log = get_logger("scrub")

#: bounded ring of corruption records kept for the debug surfaces
_RECENT_CAP = 64


def _crc(a: np.ndarray) -> int:
    return zlib.crc32(np.ascontiguousarray(a).tobytes()) & 0xFFFFFFFF


def chaos_flip(arr: np.ndarray) -> np.ndarray:
    """The ``scrub.flip`` chaos actuator: return a corrupted COPY of a
    device-bound upload (host truth is never touched — that is what
    makes the flip detectable)."""
    a = np.array(arr)
    if a.size:
        flat = a.reshape(-1)
        if a.dtype == np.bool_:
            flat[0] = not bool(flat[0])
        else:
            flat[0] = flat[0] + 1
    metrics.incr("scrub.chaos_flipped")
    return a


def _host_truth(snap, key: str) -> Optional[np.ndarray]:
    """Resolve a device-array key to its host-truth array (None =
    unscrubabble: derived layouts, mesh shards, pool keys handled
    block-wise elsewhere). The delta maintainer patches these same
    arrays in place, so they stay the truth across CDC batches."""
    if key == "v_class":
        return np.asarray(snap.v_class)
    if key.startswith("v:"):
        name, _, kind = key[2:].rpartition(":")
        col = snap.v_columns.get(name)
        if col is None:
            return None
        return np.asarray(col.values if kind == "v" else col.present)
    if key.startswith("bk:"):
        cname, _, d = key[3:].rpartition(":")
        ov = getattr(snap, "_overlay", None)
        bk = getattr(ov, "bk", {}).get(cname) if ov is not None else None
        if bk is None or d not in bk:
            return None
        return np.asarray(bk[d])
    if key.startswith("e:") and ":c:" in key:
        cname, rest = key[2:].split(":c:", 1)
        name, _, kind = rest.rpartition(":")
        csr = snap.edge_classes.get(cname)
        col = csr.edge_columns.get(name) if csr is not None else None
        if col is None:
            return None
        return np.asarray(col.values if kind == "v" else col.present)
    if key.startswith("e:"):
        cname, _, field = key[2:].rpartition(":")
        csr = snap.edge_classes.get(cname)
        if csr is None:
            return None
        if field == "edge_src":
            # derived on demand (edge_src_np); the maintainer patches
            # the device copy directly, so rebuild-from-indptr is the
            # same truth
            try:
                return np.asarray(csr.edge_src_np())
            except Exception:
                return None
        arr = getattr(csr, field, None)
        return np.asarray(arr) if arr is not None else None
    return None


class Scrubber:
    """Process-wide scrub state (mirrors the metrics/stats singletons):
    counters, the corruption ring, and the alert plane's
    corrupt-until-clean-sweep latch."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._sweeps = 0
        self._checked_keys = 0
        self._checked_bytes = 0
        self._corruptions = 0
        self._repairs: Dict[str, int] = {}
        self._recent: deque = deque()
        #: monotonic stamps driving the scrub_corruption alert: the
        #: rule breaches while the latest corruption is newer than the
        #: latest fully clean sweep (deterministic — no wall-clock
        #: window to tune)
        self._last_corrupt_ts = 0.0
        self._last_clean_ts = 0.0
        self._last_key: Optional[str] = None
        self._last_repair: Optional[str] = None
        self._since_clean = 0

    # -- sweeping ------------------------------------------------------------

    def sweep_all(self, dbs) -> None:
        """The watchdog hook: one budgeted sweep per database with a
        resident device graph. Never raises into the tick."""
        for db in dbs:
            try:
                self.sweep(db)
            except Exception:
                log.exception("scrub sweep failed for %s", db.name)

    def sweep(self, db, budget_bytes: Optional[int] = None) -> Dict:
        """One budgeted scrub rotation over ``db``'s resident device
        arrays. Returns the sweep report (also folded into process
        counters)."""
        from orientdb_tpu.obs.trace import span

        budget = int(
            budget_bytes
            if budget_bytes is not None
            else config.scrub_budget_bytes
        )
        report: Dict = {
            "db": db.name, "checked_keys": 0, "checked_bytes": 0,
            "corrupt": [], "repairs": [],
        }
        snap = db.current_snapshot()
        dg = getattr(snap, "_device_cache", None) if snap is not None else None
        if dg is None or getattr(dg, "mesh_graph", None) is not None:
            return report
        with span("scrub.sweep", db=db.name) as sp:
            keys = sorted(dg._arrays.keys())
            n = len(keys)
            cursor = int(getattr(dg, "_scrub_cursor", 0)) % max(n, 1)
            stepped = 0
            for i in range(n):
                if report["checked_bytes"] >= budget:
                    break
                key = keys[(cursor + i) % n]
                stepped = i + 1
                try:
                    res = self._check_key(snap, dg, key)
                except Exception:
                    log.exception("scrub check failed for %s", key)
                    continue
                if res is None:
                    continue
                ok, nbytes, blocks = res
                report["checked_keys"] += 1
                report["checked_bytes"] += nbytes
                if ok:
                    continue
                self._note_corruption(db, key)
                report["corrupt"].append(key)
                rung = self._repair(db, snap, dg, key, blocks)
                report["repairs"].append({"key": key, "rung": rung})
                if rung in ("compact", "reupload"):
                    # the repair replaced the snapshot/DeviceGraph this
                    # sweep was iterating — stop here, the next sweep
                    # scrubs the rebuilt state
                    break
            dg._scrub_cursor = (cursor + stepped) % n if n else 0
            sp.set("keys", report["checked_keys"])
            sp.set("corrupt", len(report["corrupt"]))
        now = time.monotonic()
        with self._mu:
            self._sweeps += 1
            self._checked_keys += report["checked_keys"]
            self._checked_bytes += report["checked_bytes"]
            if not report["corrupt"]:
                self._last_clean_ts = now
                self._since_clean = 0
        metrics.gauge("scrub.sweep_keys", report["checked_keys"])
        metrics.gauge("scrub.sweep_bytes", report["checked_bytes"])
        return report

    def _check_key(
        self, snap, dg, key: str
    ) -> Optional[Tuple[bool, int, List[int]]]:
        """(clean?, device bytes fetched, corrupt tier blocks) — None
        when the key has no scrubabble host truth."""
        if key.startswith("sh:"):
            return None
        if key.startswith("t:"):
            return self._check_tier_key(snap, dg, key)
        host = _host_truth(snap, key)
        if host is None:
            return None
        dev_arr = dg._arrays.get(key)
        if dev_arr is None:
            return None
        dev = np.asarray(dev_arr)
        if host.shape != dev.shape:
            # shape drift means the key was re-laid-out mid-sweep (e.g.
            # a compaction swap) — not comparable, not corruption
            return None
        expected = self._expected_crc(dg, key, host, dev.dtype)
        actual = _crc(dev)
        if actual == expected:
            return True, int(dev.nbytes), []
        # one re-check before conviction: a maintainer patch landing
        # between the device fetch and the host hash is a benign race,
        # not corruption
        self._invalidate(dg, key)
        host2 = _host_truth(snap, key)
        if host2 is None or host2.shape != np.asarray(
            dg._arrays.get(key, dev)
        ).shape:
            return None
        dev2 = np.asarray(dg._arrays[key])
        expected = self._expected_crc(dg, key, host2, dev2.dtype)
        return _crc(dev2) == expected, int(dev.nbytes) * 2, []

    def _check_tier_key(
        self, snap, dg, key: str
    ) -> Optional[Tuple[bool, int, List[int]]]:
        """Block-wise CRC check of a tier-plane key: pool rows compare
        against ``_Partition.block_values`` for RESIDENT blocks only
        (evicted pages deliberately hold stale-but-masked rows); the
        page table and block indexes compare whole."""
        tier = getattr(snap, "_tier", None)
        if tier is None:
            return None
        parts = key[2:].split(":")
        if len(parts) < 3:
            return None
        name = parts[-1]
        d = parts[-2]
        cname = ":".join(parts[:-2])
        part = tier.parts.get((cname, d))
        if part is None:
            return None
        with tier.lock:
            dev_arr = dg._arrays.get(key)
            if dev_arr is None:
                return None
            dev = np.asarray(dev_arr)
            if name in ("pageof", "blockv", "estart"):
                host = {
                    "pageof": part.page_of,
                    "blockv": part.block_of_v,
                    "estart": part.edge_start,
                }[name]
                host = np.asarray(host, dev.dtype)
                if host.shape != dev.shape:
                    return None
                return _crc(dev) == _crc(host), int(dev.nbytes), []
            if name not in ("own", "nbr", "eid"):
                return None
            bad: List[int] = []
            nbytes = 0
            for b in range(part.B):
                p = int(part.page_of[b])
                if p < 0 or p >= dev.shape[0]:
                    continue
                row = dev[p]
                nbytes += int(row.nbytes)
                if _crc(row) != _crc(
                    np.asarray(part.block_values(name, b), row.dtype)
                ):
                    bad.append(b)
            return not bad, nbytes, bad

    def _expected_crc(self, dg, key: str, host: np.ndarray, dtype) -> int:
        """Host-truth CRC, cached per DeviceGraph key; ``_put`` and
        ``apply_patches`` mark dirty keys so only changed truth
        re-hashes."""
        cache = getattr(dg, "_scrub_crc", None)
        if cache is None:
            cache = dg._scrub_crc = {}
        dirty = getattr(dg, "_scrub_dirty", None)
        if dirty is None:
            dirty = dg._scrub_dirty = set()
        if key in cache and key not in dirty:
            return cache[key]
        c = _crc(np.asarray(host, dtype))
        cache[key] = c
        dirty.discard(key)
        return c

    @staticmethod
    def _invalidate(dg, key: str) -> None:
        getattr(dg, "_scrub_dirty", set()).add(key)

    # -- repair ladder -------------------------------------------------------

    def _note_corruption(self, db, key: str) -> None:
        metrics.incr("scrub.corruptions")
        with self._mu:
            self._corruptions += 1
            self._since_clean += 1
            self._last_corrupt_ts = time.monotonic()
            self._last_key = key
            self._recent.append({
                "db": db.name, "key": key, "ts": round(time.time(), 3),
            })
            while len(self._recent) > _RECENT_CAP:
                self._recent.popleft()
        log.error(
            "SCRUB CORRUPTION: device bytes at %s (db %s) disagree "
            "with host truth", key, db.name,
        )

    def _repair(self, db, snap, dg, key: str, blocks: List[int]) -> str:
        """Walk the repair ladder for one corrupt key; returns the rung
        taken. Each rung re-derives device state from host truth, so a
        successful repair restores parity by construction."""
        from orientdb_tpu.obs.trace import span

        with span("scrub.repair", key=key) as sp:
            rung = self._repair_rung(db, snap, dg, key, blocks)
            sp.set("rung", rung)
        with self._mu:
            self._repairs[rung] = self._repairs.get(rung, 0) + 1
            self._last_repair = rung
            if self._recent:
                self._recent[-1]["rung"] = rung
        metrics.incr(f"scrub.repairs.{rung}")
        log.warning("scrub repair (%s) for %s on %s", rung, key, db.name)
        return rung

    def _repair_rung(self, db, snap, dg, key: str, blocks) -> str:
        tier = getattr(snap, "_tier", None)
        if key.startswith("t:") and tier is not None:
            parts = key[2:].split(":")
            name = parts[-1]
            d = parts[-2]
            cname = ":".join(parts[:-2])
            part = tier.parts.get((cname, d))
            if part is not None:
                with tier.lock:
                    if blocks and name in ("own", "nbr", "eid"):
                        # rung 1: invalidate + reload exactly the
                        # corrupt blocks (PR-16 machinery)
                        for b in blocks:
                            if part.page_of[b] >= 0:
                                tier._evict(part, b)
                        tier._ensure_blocks(part, list(blocks), None)
                        return "tier_reload"
                    # page table / block index: re-upload host truth
                    import jax

                    host = {
                        "pageof": part.page_of,
                        "blockv": part.block_of_v,
                        "estart": part.edge_start,
                    }.get(name)
                    if host is not None:
                        dev = dg._arrays[key]
                        dg._arrays[key] = jax.device_put(
                            np.asarray(host, np.asarray(dev).dtype)
                        )
                        from orientdb_tpu.obs.memledger import memledger

                        memledger.register_graph_array(
                            dg, key, dg._arrays[key]
                        )
                        return "tier_reload"
        maintainer = getattr(db, "_snapshot_maintainer", None)
        ov = getattr(snap, "_overlay", None)
        if maintainer is not None and ov is not None:
            # rung 2: poison the overlay so the maintainer folds the
            # slabs back into a clean CSR and re-uploads (PR-15 epoch
            # compaction — the swap releases the corrupt device state)
            if ov.poisoned is None:
                ov.poison(f"scrub: device corruption at {key}")
            try:
                maintainer.catch_up()
            except Exception:
                log.exception("scrub-triggered compaction failed")
            return "compact"
        # rung 3: full snapshot re-upload from host truth
        from orientdb_tpu.ops.device_graph import device_graph

        snap.release_device()
        self._invalidate_all(dg)
        try:
            if getattr(snap, "_device_cache", None) is dg:
                # in-flight epoch leases deferred the free, so the
                # corrupt DeviceGraph is still canonical — restore the
                # corrupt key's bytes from host truth IN PLACE (served
                # traffic reads correct rows now); the full free still
                # lands when the last lease releases
                import jax

                host = _host_truth(snap, key)
                cur = dg._arrays.get(key)
                if host is not None and cur is not None:
                    dg._arrays[key] = jax.device_put(
                        np.asarray(host, np.asarray(cur).dtype)
                    )
                    from orientdb_tpu.obs.memledger import memledger

                    memledger.register_graph_array(
                        dg, key, dg._arrays[key]
                    )
            else:
                device_graph(snap)
        except Exception:
            log.exception("scrub-triggered re-upload failed")
        return "reupload"

    @staticmethod
    def _invalidate_all(dg) -> None:
        cache = getattr(dg, "_scrub_crc", None)
        if cache is not None:
            cache.clear()

    # -- views ---------------------------------------------------------------

    def alert_state(self) -> Optional[Dict]:
        """Non-None while corruption is newer than the last fully clean
        sweep (the ``scrub_corruption`` rule's breach condition)."""
        with self._mu:
            if self._last_corrupt_ts <= self._last_clean_ts:
                return None
            return {
                "corruptions": self._since_clean,
                "last_key": self._last_key,
                "last_repair": self._last_repair,
            }

    def snapshot(self) -> Dict:
        with self._mu:
            return {
                "sweeps": self._sweeps,
                "checked_keys": self._checked_keys,
                "checked_bytes": self._checked_bytes,
                "corruptions": self._corruptions,
                "repairs": dict(self._repairs),
                "recent": list(self._recent),
            }

    def reset(self) -> None:
        """Test isolation (mirrors ``metrics.reset``)."""
        with self._mu:
            self._sweeps = 0
            self._checked_keys = 0
            self._checked_bytes = 0
            self._corruptions = 0
            self._repairs.clear()
            self._recent.clear()
            self._last_corrupt_ts = 0.0
            self._last_clean_ts = 0.0
            self._last_key = None
            self._last_repair = None
            self._since_clean = 0


#: the process-wide scrubber (mirrors metrics/stats/tracer singletons)
scrubber = Scrubber()
