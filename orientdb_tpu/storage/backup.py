"""Online BACKUP / RESTORE DATABASE.

Analog of the reference's online backup ([E] ``BACKUP DATABASE`` console
command: a zip of the storage files made consistent by a frozen
atomic-operations window; SURVEY.md §5.4). Redesign over this engine's
logical state capture: the backup takes the SAME atomic snapshot a full
checkpoint takes — payload, covered LSN, and epoch captured as one step
against writers under ``db._lock`` (pointer copies only; JSON
serialization runs outside the lock, torn captures corrected exactly as
in ``storage/durability.checkpoint``) — and zips it with a manifest.
Writers are blocked only for the pointer-copy window (the frozen-window
analog), not for the serialization or the disk write.

Restore builds a fresh Database from the archive via the same
``restore_payload`` machinery recovery uses. Surfaces: console
``BACKUP DATABASE <path>`` / ``RESTORE DATABASE <path>``, and this
module's functions."""

from __future__ import annotations

import json
import zipfile
from typing import Optional

from orientdb_tpu.models.database import Database
from orientdb_tpu.storage.durability import (
    _meta_payload,
    _rec_json,
    restore_payload,
)

MANIFEST = "manifest.json"
PAYLOAD = "database.json"


def backup_database(db: Database, path: str) -> str:
    """Write a consistent zip backup of ``db`` while writes continue.

    The consistency point is the instant the lock-held pointer capture
    completes: every write acknowledged before it is in the backup,
    every later write is not (its WAL entry carries a higher LSN)."""
    wal = getattr(db, "_wal", None)
    with db._lock:
        lsn = (wal.next_lsn - 1) if wal is not None else 0
        payload = _meta_payload(db)
        cluster_snap = [
            (cid, list(c.records)) for cid, c in db._clusters.items()
        ]
    clusters = {}
    for cid, records in cluster_snap:
        recs = []
        for pos, doc in enumerate(records):
            if doc is None:
                continue
            try:
                recs.append(_rec_json(doc, pos))
            except RuntimeError:
                with db._lock:  # doc mutated mid-serialization: quiesce
                    recs.append(_rec_json(doc, pos))
        clusters[str(cid)] = {"len": len(records), "records": recs}
    payload["clusters"] = clusters
    payload["lsn"] = lsn
    manifest = {
        "format": 1,
        "name": db.name,
        "epoch": payload["epoch"],
        "lsn": lsn,
    }
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as z:
        z.writestr(MANIFEST, json.dumps(manifest))
        z.writestr(PAYLOAD, json.dumps(payload, separators=(",", ":")))
    return path


def restore_database(path: str, name: Optional[str] = None) -> Database:
    """Rebuild a database from a backup zip."""
    with zipfile.ZipFile(path) as z:
        manifest = json.loads(z.read(MANIFEST))
        payload = json.loads(z.read(PAYLOAD))
    db = Database(name or manifest.get("name", "restored"))
    restore_payload(db, payload)
    return db
