"""Online BACKUP / RESTORE DATABASE.

Analog of the reference's online backup ([E] ``BACKUP DATABASE`` console
command: a zip of the storage files made consistent by a frozen
atomic-operations window; SURVEY.md §5.4). Redesign over this engine's
logical state capture: the backup takes the SAME atomic capture a full
checkpoint takes (`storage.durability.capture_payload` — covered LSN +
pointer copies under ``db._lock``, serialization outside it), so writers
are blocked only for the pointer-copy window.

Serialization races writers, so a captured record can be TORN (newer
state than the captured LSN). Recovery corrects that by replaying WAL
entries above the LSN from disk; a backup archive must be
self-contained, so it BUNDLES that tail: every WAL entry logged between
the capture point and the end of serialization ships in the zip, and
restore replays it over the payload — the archive is consistent as of
the LAST bundled entry. Databases without a WAL serialize entirely
under the lock instead (a stop-the-world freeze — the no-journal
fallback, documented here).

Surfaces: console ``BACKUP DATABASE <path>`` / ``RESTORE DATABASE
<path>``, and this module's functions."""

from __future__ import annotations

import json
import zipfile
from typing import Optional

from orientdb_tpu.models.database import Database
from orientdb_tpu.storage.durability import (
    _apply_entry,
    _meta_payload,
    _rec_json,
    _wal_segments,
    WriteAheadLog,
    capture_payload,
    restore_payload,
)

MANIFEST = "manifest.json"
PAYLOAD = "database.json"
TAIL = "wal_tail.json"


def _locked_payload(db: Database):
    """No-WAL fallback: serialize entirely under db._lock (no journal
    exists to correct torn captures, so the capture must be frozen)."""
    with db._lock:
        payload = _meta_payload(db)
        clusters = {}
        for cid, c in db._clusters.items():
            recs = []
            for pos, doc in enumerate(c.records):
                if doc is not None:
                    recs.append(_rec_json(doc, pos))
            clusters[str(cid)] = {"len": len(c.records), "records": recs}
        payload["clusters"] = clusters
        payload["lsn"] = 0
    return payload


def _wal_tail(db: Database, after_lsn: int, upto_lsn: int):
    """WAL entries with lsn in (after_lsn, upto_lsn], across the live
    segment and any archives a concurrent checkpoint may have rotated."""
    import os

    entries = []
    directory = getattr(db, "_durability_dir", None)
    if directory and os.path.isdir(directory):
        for seg in _wal_segments(directory):
            base = os.path.basename(seg)
            if base.startswith("wal-") and base.endswith(".log"):
                try:
                    if int(base[4:-4]) <= after_lsn:
                        continue
                except ValueError:
                    pass
            entries.extend(WriteAheadLog(seg).read_entries())
    else:
        entries = db._wal.read_entries()
    out = [e for e in entries if after_lsn < e["lsn"] <= upto_lsn]
    out.sort(key=lambda e: e["lsn"])
    return out


def backup_database(db: Database, path: str) -> str:
    """Write a consistent zip backup of ``db`` while writes continue.

    The archive restores to the database state as of its LAST bundled
    WAL entry (manifest ``upto_lsn``): every write acknowledged before
    serialization finished is included."""
    wal = getattr(db, "_wal", None)
    if wal is None:
        payload, lsn, upto = _locked_payload(db), 0, 0
        tail = []
    else:
        payload, lsn, _ = capture_payload(db)
        with db._lock:
            upto = db._wal.next_lsn - 1
        tail = _wal_tail(db, lsn, upto)
    manifest = {
        "format": 2,
        "name": db.name,
        "epoch": payload["epoch"],
        "lsn": lsn,
        "upto_lsn": upto,
    }
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as z:
        z.writestr(MANIFEST, json.dumps(manifest))
        z.writestr(PAYLOAD, json.dumps(payload, separators=(",", ":")))
        z.writestr(TAIL, json.dumps(tail, separators=(",", ":")))
    return path


def restore_database(path: str, name: Optional[str] = None) -> Database:
    """Rebuild a database from a backup zip: payload, then the bundled
    WAL tail replayed over it (exactly recovery's discipline)."""
    with zipfile.ZipFile(path) as z:
        manifest = json.loads(z.read(MANIFEST))
        payload = json.loads(z.read(PAYLOAD))
        tail = json.loads(z.read(TAIL)) if TAIL in z.namelist() else []
    db = Database(name or manifest.get("name", "restored"))
    restore_payload(db, payload)
    for e in tail:
        _apply_entry(db, e)
    return db
