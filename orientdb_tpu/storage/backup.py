"""Online BACKUP / RESTORE DATABASE.

Analog of the reference's online backup ([E] ``BACKUP DATABASE`` console
command: a zip of the storage files made consistent by a frozen
atomic-operations window; SURVEY.md §5.4). Redesign over this engine's
logical state capture: the backup takes the SAME atomic capture a full
checkpoint takes (`storage.durability.capture_payload` — covered LSN +
pointer copies under ``db._lock``, serialization outside it), so writers
are blocked only for the pointer-copy window.

Serialization races writers, so a captured record can be TORN (newer
state than the captured LSN). Recovery corrects that by replaying WAL
entries above the LSN from disk; a backup archive must be
self-contained, so it BUNDLES that tail: every WAL entry logged between
the capture point and the end of serialization ships in the zip, and
restore replays it over the payload — the archive is consistent as of
the LAST bundled entry. Databases without a WAL serialize entirely
under the lock instead (a stop-the-world freeze — the no-journal
fallback, documented here).

Surfaces: console ``BACKUP DATABASE <path>`` / ``RESTORE DATABASE
<path>``, and this module's functions."""

from __future__ import annotations

import hashlib
import json
import zipfile
from typing import Optional

from orientdb_tpu.models.database import Database
from orientdb_tpu.storage.durability import (
    _apply_entry,
    capture_payload,
    restore_payload,
    wal_entries_above,
)

MANIFEST = "manifest.json"
PAYLOAD = "database.json"
TAIL = "wal_tail.json"


def _wal_tail(db: Database, after_lsn: int, upto_lsn: int):
    """WAL entries with lsn in (after_lsn, upto_lsn], across the live
    segment and any archives a concurrent checkpoint may have rotated."""
    import os

    directory = getattr(db, "_durability_dir", None)
    if directory and os.path.isdir(directory):
        entries = wal_entries_above(directory, after_lsn)
    else:
        entries = [
            e for e in db._wal.read_entries() if e["lsn"] > after_lsn
        ]
    return [e for e in entries if e["lsn"] <= upto_lsn]


def backup_database(db: Database, path: str) -> str:
    """Write a consistent zip backup of ``db`` while writes continue.

    The archive restores to the database state as of its LAST bundled
    WAL entry (manifest ``upto_lsn``): every write acknowledged before
    serialization finished is included."""
    wal = getattr(db, "_wal", None)
    if wal is None:
        # no journal exists to correct torn captures: freeze writers for
        # the whole serialization instead
        payload, lsn, _ = capture_payload(db, serialize_in_lock=True)
        upto, tail = 0, []
    else:
        payload, lsn, _ = capture_payload(db)
        with db._lock:
            upto = db._wal.next_lsn - 1
        tail = _wal_tail(db, lsn, upto)
    payload_bytes = json.dumps(payload, separators=(",", ":")).encode()
    tail_bytes = json.dumps(tail, separators=(",", ":")).encode()
    manifest = {
        # format 3: the manifest carries content hashes of the exact
        # payload/tail bytes, so `tools/fsck.py --backup` can verify an
        # archive's integrity without (and before) restoring it
        "format": 3,
        "name": db.name,
        "epoch": payload["epoch"],
        "lsn": lsn,
        "upto_lsn": upto,
        "sha256_payload": hashlib.sha256(payload_bytes).hexdigest(),
        "sha256_tail": hashlib.sha256(tail_bytes).hexdigest(),
    }
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as z:
        z.writestr(MANIFEST, json.dumps(manifest))
        z.writestr(PAYLOAD, payload_bytes)
        z.writestr(TAIL, tail_bytes)
    return path


def restore_database(path: str, name: Optional[str] = None) -> Database:
    """Rebuild a database from a backup zip: payload, then the bundled
    WAL tail replayed over it (exactly recovery's discipline)."""
    with zipfile.ZipFile(path) as z:
        manifest = json.loads(z.read(MANIFEST))
        payload = json.loads(z.read(PAYLOAD))
        tail = json.loads(z.read(TAIL)) if TAIL in z.namelist() else []
    db = Database(name or manifest.get("name", "restored"))
    restore_payload(db, payload)
    for e in tail:
        _apply_entry(db, e)
    return db
