"""Incremental HBM snapshot maintenance off the CDC feed.

Before this module, any committed write bumped ``Database.mutation_epoch``
and the next fresh-snapshot query paid a wholesale HBM invalidation +
full re-upload (``detach_snapshot`` frees every device buffer; r04
measured ~1.34 GB of per-device adjacency at SF100 shape). Fine
read-only — fatal under the SNB interactive mix. This module keeps the
device-resident CSR alive across writes:

- **append slabs**: :func:`pad_for_deltas` grows the host snapshot with
  spare vertex rows and per-edge-class spare edge slots BEFORE the
  device upload. New vertices/edges land in slab slots; the compiled
  engine's kernels consult the slab tail alongside the base CSR
  (``tpu_engine._expand_slab`` for CSR expansions, a per-edge ``live``
  mask for the bitmap-hop edge-list path).
- **device-side delta application**: the :class:`SnapshotMaintainer`
  consumes the database's changefeed (``cdc/feed.py`` — ordered,
  resumable, replica-complete by construction), batches events per
  cursor advance, and applies them as packed scatter segments
  (``DeviceGraph.apply_patches`` → ``arr.at[idx].set(vals)``). Compiled
  plans pass graph arrays as jit *arguments*, so a same-shape functional
  update is invisible to every cached executable — per-write upload
  bytes are bounded by the delta, not the graph.
- **epoch gating**: an in-flight dispatch finishes on the epoch it was
  admitted under — its executable captured the pre-patch argument
  buffers, and :meth:`GraphSnapshot.retain`/``release`` refcounting
  defers ``release_device`` until the last dispatch drains (no
  use-after-free of device buffers across a compaction swap).
- **epoch compaction**: when a slab fills past
  ``config.delta_compact_ratio`` (or an unsupported event poisons the
  overlay), :meth:`SnapshotMaintainer.compact` folds the slabs back
  into a clean CSR — a fresh ``build_snapshot`` persisted through the
  ``storage/epochs.py`` content-addressed idiom when the database is
  durable — and re-arms the overlay on the new snapshot.

Unsupported deltas degrade LOUDLY, never silently wrong: schema renames,
new classes/properties with columnar values, column type changes, and
slab overflow POISON the overlay — the snapshot reports stale, queries
fall back to the oracle, and the next catch-up compacts. String columns
accept new dictionary entries by appending (equality predicates stay
exact); the dictionary is then UNSORTED, so new recordings refuse
ordered string compares (oracle fallback) until compaction re-sorts.

Patch ordering makes concurrent dispatches safe: deletes flip liveness
(v_class/-1, edge ``live``/False) BEFORE clearing endpoint data, inserts
write data BEFORE flipping liveness — a dispatch grabbing its argument
buffers mid-batch sees either the old state or the new one per record,
never a half-written edge.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from orientdb_tpu.models.rid import RID
from orientdb_tpu.storage.snapshot import (
    MISSING_FLOAT,
    MISSING_INT,
    GraphSnapshot,
    PropertyColumn,
)
from orientdb_tpu.utils.config import config
from orientdb_tpu.utils.logging import get_logger
from orientdb_tpu.utils.metrics import metrics

log = get_logger("deltas")


class DeltaUnsupported(Exception):
    """An event the overlay cannot apply device-side: the overlay is
    poisoned and the next catch-up compacts (full rebuild)."""


class _EdgeSlab:
    """Per-edge-class slab bookkeeping (host side)."""

    __slots__ = (
        "base",
        "cap",
        "next_slot",
        "dead",
        "_rid_pos",
        "_in_pos",
    )

    def __init__(self, base: int, cap: int) -> None:
        self.base = base  # base CSR edge count (slab starts here)
        self.cap = cap  # padded edge array length
        self.next_slot = base  # next free absolute slot
        self.dead = 0  # tombstoned edges (base + slab)
        self._rid_pos: Optional[Dict[RID, int]] = None  # lazy rid → slot
        self._in_pos: Optional[np.ndarray] = None  # out pos → in pos

    def rid_pos(self, csr) -> Dict[RID, int]:
        m = self._rid_pos
        if m is None:
            m = self._rid_pos = {
                r: i for i, r in enumerate(csr.edge_rids) if r is not None
            }
        return m

    def in_pos(self, csr) -> np.ndarray:
        inv = self._in_pos
        if inv is None:
            inv = np.full(self.cap, -1, np.int64)
            ids = np.asarray(csr.edge_id_in[: self.base], np.int64)
            inv[ids] = np.arange(self.base, dtype=np.int64)
            self._in_pos = inv
        return inv


class SnapshotOverlay:
    """Delta bookkeeping for one capacity-padded snapshot."""

    def __init__(self, snap: GraphSnapshot, base_vertices: int) -> None:
        self.snap = snap
        self.base_vertices = base_vertices  # live rows at build
        self.cap_vertices = snap.num_vertices  # padded universe
        self.next_v_slot = base_vertices
        self.dead_vertices = 0
        self.edge_slabs: Dict[str, _EdgeSlab] = {}
        #: plans recorded clean (count pushdown, no slab scan) must not
        #: replay over dirty topology: the first topology delta bumps
        #: this and clears the snapshot's plan cache
        self.topology_dirty = False
        self.plan_gen = 0
        #: bumped once per applied event batch: consumers whose replay
        #: machinery is fully static (TRAVERSE bakes roots and drops
        #: the overflow flag) re-record when ANY delta landed
        self.data_version = 0
        self.applied_events = 0
        self.upload_bytes = 0
        #: reason the overlay can no longer track the store (None =
        #: healthy). Written LOCK-FREE from write-path taps
        #: (database.rename_class holds db._lock; taking the maintainer
        #: lock there would invert the catch-up lock order).
        self.poisoned: Optional[str] = None
        #: bucketed slab index (built by pad_for_deltas): per class,
        #: flat [NB*BK] tables of RELATIVE slab slots keyed by
        #: endpoint & (NB-1) — the O(touched buckets) replacement for
        #: the O(table × slab-window) _expand_slab scan. Host mirrors
        #: here; device twins upload as ``bk:{class}:{dir}`` and are
        #: patch-maintained like every other delta array.
        self.bk: Dict[str, Dict[str, np.ndarray]] = {}
        self.bk_nb = 0
        self.bk_bk = 0
        #: classes whose bucket filled (BK same-bucket slab edges):
        #: their plans fall back to the window scan until compaction
        self.bucket_overflow: set = set()

    # -- state transitions --------------------------------------------------

    def mark_topology_dirty(self) -> None:
        if not self.topology_dirty:
            self.topology_dirty = True
            self.bump_plan_gen()

    def bump_plan_gen(self) -> None:
        """Invalidate every plan recorded under the previous structure:
        cached plans are dropped and already-picked plan objects fail
        their generation check (ScheduleOverflow → re-record)."""
        self.plan_gen += 1
        cache = getattr(self.snap, "_plan_cache", None)
        if cache is not None:
            cache.clear()

    def poison(self, reason: str) -> None:
        if self.poisoned is None:
            self.poisoned = reason
            metrics.incr("snapshot.delta.poisoned")
            log.warning("snapshot overlay poisoned: %s", reason)

    # -- geometry -----------------------------------------------------------

    def edge_base(self, class_name: str) -> int:
        return self.edge_slabs[class_name].base

    def bucket_add(
        self, cname: str, src: int, dst: int, rel: int, patches=None
    ) -> None:
        """Index a freshly appended slab edge (relative slot ``rel``)
        under both endpoints' buckets. A full bucket flips the class to
        the scan fallback (and re-records its plans); tombstones need
        no removal — the expansion ANDs the liveness mask."""
        t = self.bk.get(cname)
        if t is None or cname in self.bucket_overflow:
            return
        nb, bk = self.bk_nb, self.bk_bk
        for tab, fill, key_v, dev in (
            (t["out"], t["fill_out"], src, f"bk:{cname}:out"),
            (t["in"], t["fill_in"], dst, f"bk:{cname}:in"),
        ):
            b = int(key_v) & (nb - 1)
            n = int(fill[b])
            if n >= bk:
                self.bucket_overflow.add(cname)
                metrics.incr("snapshot.delta.bucket_overflow")
                self.bump_plan_gen()
                return
            slot = b * bk + n
            tab[slot] = rel
            fill[b] = n + 1
            if patches is not None:
                patches.add(_PH_DATA, dev, slot, np.int32(rel))

    def slab_fill(self) -> float:
        """Worst-case slab occupancy fraction (vertex slab and every
        edge slab) — the compaction trigger and the
        ``delta_slab_pressure`` alert signal."""
        fills = []
        vcap = self.cap_vertices - self.base_vertices
        if vcap > 0:
            fills.append((self.next_v_slot - self.base_vertices) / vcap)
        for slab in self.edge_slabs.values():
            ecap = slab.cap - slab.base
            if ecap > 0:
                fills.append((slab.next_slot - slab.base) / ecap)
        return max(fills) if fills else 0.0

    def dead_fraction(self) -> float:
        v = self.dead_vertices / max(1, self.base_vertices)
        e = max(
            (
                s.dead / max(1, s.next_slot)
                for s in self.edge_slabs.values()
            ),
            default=0.0,
        )
        return max(v, e)

    def stats(self) -> Dict:
        return {
            "base_vertices": self.base_vertices,
            "cap_vertices": self.cap_vertices,
            "slab_vertices": self.next_v_slot - self.base_vertices,
            "dead_vertices": self.dead_vertices,
            "slab_edges": {
                c: s.next_slot - s.base for c, s in self.edge_slabs.items()
            },
            "slab_fill": round(self.slab_fill(), 4),
            "topology_dirty": self.topology_dirty,
            "plan_gen": self.plan_gen,
            "applied_events": self.applied_events,
            "upload_bytes": self.upload_bytes,
            "poisoned": self.poisoned,
        }


# ---------------------------------------------------------------------------
# capacity padding
# ---------------------------------------------------------------------------


def _pad1(arr: np.ndarray, n: int, fill) -> np.ndarray:
    if arr.shape[0] >= n:
        return arr
    pad = np.full(n - arr.shape[0], fill, arr.dtype)
    return np.concatenate([arr, pad])


def _pad_column(col: PropertyColumn, n: int) -> None:
    fill = MISSING_FLOAT if col.kind == "float" else MISSING_INT
    col.values = _pad1(col.values, n, fill)
    col.present = _pad1(col.present.astype(bool), n, False)


def pad_for_deltas(
    snap: GraphSnapshot,
    spare_vertices: Optional[int] = None,
    spare_edges: Optional[int] = None,
) -> SnapshotOverlay:
    """Grow a freshly built snapshot with slab capacity and attach a
    :class:`SnapshotOverlay`. Spare vertex rows carry class ``-1``
    (excluded by every class mask and by the armed liveness conjunct);
    spare edge slots carry ``-1`` endpoints and ``live=False``.

    Must run BEFORE the first device upload (the padded host arrays are
    what ``device_graph`` puts in HBM). Mesh-sharded snapshots are not
    supported (the shard-wise layout re-partitions per geometry)."""
    if getattr(snap, "_mesh", None) is not None:
        raise ValueError("delta slabs are single-device only (no mesh)")
    if getattr(snap, "_tier", None) is not None:
        # the slab scan and patch kernels read the flat [E] arrays the
        # tier pages out of HBM — the two planes don't compose (yet)
        from orientdb_tpu.obs.memledger import memledger

        memledger.note_refusal(
            "overlay", "delta maintenance requested on a tiered snapshot"
        )
        raise ValueError(
            "tiered snapshots are immutable: delta maintenance needs the "
            "flat resident edge arrays — detach the tier (raise "
            "tier_hbm_cap_bytes) or serve reads tiered and compact writes "
            "into fresh snapshots"
        )
    if getattr(snap, "_device_cache", None) is not None:
        raise ValueError("pad_for_deltas must run before device upload")
    sv = config.delta_slab_vertex_rows if spare_vertices is None else spare_vertices
    se = config.delta_slab_edge_slots if spare_edges is None else spare_edges
    sv = max(1, int(sv))
    se = max(1, int(se))
    base_v = snap.num_vertices
    cap_v = base_v + sv
    snap.v_cluster = _pad1(snap.v_cluster, cap_v, -1)
    snap.v_position = _pad1(snap.v_position, cap_v, -1)
    snap.v_class = _pad1(snap.v_class, cap_v, -1)
    for col in snap.v_columns.values():
        _pad_column(col, cap_v)
    snap.num_vertices = cap_v
    ov = SnapshotOverlay(snap, base_v)
    for cname, csr in snap.edge_classes.items():
        base_e = int(csr.dst.shape[0])
        cap_e = base_e + se
        # indptr over the padded universe: slab rows have zero degree
        # in the base CSR (the slab tail is consulted separately)
        csr.indptr_out = _pad1(
            csr.indptr_out, cap_v + 1, csr.indptr_out[-1]
        )
        csr.indptr_in = _pad1(csr.indptr_in, cap_v + 1, csr.indptr_in[-1])
        # edge list padded with -1 endpoints; edge_src materialized NOW
        # so the padded form is what reaches the device
        csr._edge_src = _pad1(csr.edge_src_np(), cap_e, -1)
        csr.dst = _pad1(csr.dst, cap_e, -1)
        csr.src = _pad1(csr.src, cap_e, -1)
        csr.edge_id_in = _pad1(csr.edge_id_in, cap_e, -1)
        csr.live = np.concatenate(
            [
                np.ones(base_e, bool),
                np.zeros(cap_e - base_e, bool),
            ]
        )
        csr.edge_rids = list(csr.edge_rids) + [None] * (cap_e - base_e)
        for col in csr.edge_columns.values():
            _pad_column(col, cap_e)
        ov.edge_slabs[cname] = _EdgeSlab(base_e, cap_e)
    # bucketed slab index: NB pow2 buckets × BK slots per class+dir,
    # keyed by endpoint & (NB-1) — sized ~2× the slab so same-bucket
    # collisions (overflow → scan fallback) stay rare at full occupancy
    ov.bk_bk = 8
    ov.bk_nb = max(256, 1 << max(0, (se - 1).bit_length() - 2))
    for cname in snap.edge_classes:
        ov.bk[cname] = {
            "out": np.full(ov.bk_nb * ov.bk_bk, -1, np.int32),
            "in": np.full(ov.bk_nb * ov.bk_bk, -1, np.int32),
            "fill_out": np.zeros(ov.bk_nb, np.int32),
            "fill_in": np.zeros(ov.bk_nb, np.int32),
        }
    snap._overlay = ov
    return ov


# ---------------------------------------------------------------------------
# the maintainer
# ---------------------------------------------------------------------------

#: patch phases (see module docstring): deletes flip liveness first,
#: inserts flip it last — readers mid-batch see whole records only
_PH_DEAD, _PH_DATA, _PH_LIVE = 0, 1, 2


class _PatchSet:
    """Per-batch scatter segments: ONE (phase, value) cell per
    (device-array key, index), the last write winning. Without the
    dedupe, two same-batch events touching one cell would scatter
    duplicate indices (``.at[idx].set`` leaves the winner unspecified
    when indices repeat), and a create followed by a same-batch delete
    would resurrect the record on device — the insert's LIVE-phase
    liveness would land after the delete's DEAD-phase tombstone."""

    def __init__(self) -> None:
        #: key -> {idx: (phase, value)} — insertion-ordered, overwritten
        #: in event order, emitted into each cell's FINAL phase
        self._cells: Dict[str, Dict[int, Tuple[int, object]]] = {}

    def add(self, phase: int, key: str, idx: int, val) -> None:
        self._cells.setdefault(key, {})[int(idx)] = (phase, val)

    def empty(self) -> bool:
        return not self._cells

    @property
    def phases(self) -> List[Dict[str, Tuple[List[int], List]]]:
        out: List[Dict[str, Tuple[List[int], List]]] = [{}, {}, {}]
        for key, cells in self._cells.items():
            for idx, (phase, val) in cells.items():
                sl = out[phase].setdefault(key, ([], []))
                sl[0].append(idx)
                sl[1].append(val)
        return out


class SnapshotMaintainer:
    """Keeps a database's attached snapshot fresh across writes by
    applying CDC deltas device-side. Armed via
    :func:`arm_delta_maintenance`; the query front door's freshness
    check (``Database.current_snapshot(require_fresh=True)``) calls
    :meth:`catch_up` when the epoch moved — deltas apply in batches on
    the first stale query, so write bursts amortize into one packed
    scatter per touched array."""

    def __init__(
        self,
        db,
        spare_vertices: Optional[int] = None,
        spare_edges: Optional[int] = None,
        epoch_dir: Optional[str] = None,
    ) -> None:
        self.db = db
        self.spare_vertices = spare_vertices
        self.spare_edges = spare_edges
        #: persist compacted epochs here (content-addressed,
        #: storage/epochs.py); defaults to the durability dir
        self.epoch_dir = epoch_dir
        self._lock = threading.RLock()
        self._consumer = None
        self._stash: List[Dict] = []
        self.compactions = 0
        self.last_compact_reason: Optional[str] = None

    # -- arming -------------------------------------------------------------

    def arm(self) -> GraphSnapshot:
        """Build + pad + attach a maintained snapshot, subscribe to the
        changefeed, and register this maintainer on the database."""
        from orientdb_tpu.cdc.feed import feed_of
        from orientdb_tpu.storage.snapshot import build_snapshot

        with self._lock:
            old = self.db._snapshot
            with self.db._lock:
                snap = build_snapshot(self.db)
                pad_for_deltas(
                    snap, self.spare_vertices, self.spare_edges
                )
                self.db.attach_snapshot(snap)
                feed = feed_of(self.db, create=True)
                if self._consumer is None:
                    self._consumer = self._register(feed)
            self.db._snapshot_maintainer = self
            if old is not None and old is not snap:
                # a previously attached (classic) snapshot's buffers are
                # replaced, not kept: free them, deferred past any
                # in-flight dispatch by the retain refcount
                old.release_device()
            return snap

    def _register(self, feed):
        return feed.register(
            policy="shed",
            queue_max=max(
                config.cdc_queue_max,
                4 * config.delta_slab_edge_slots,
            ),
        )

    def disarm(self) -> None:
        with self._lock:
            if self._consumer is not None:
                self._consumer.close()
                self._consumer = None
            if getattr(self.db, "_snapshot_maintainer", None) is self:
                self.db._snapshot_maintainer = None

    @property
    def overlay(self) -> Optional[SnapshotOverlay]:
        snap = self.db._snapshot
        return getattr(snap, "_overlay", None) if snap is not None else None

    # -- catch-up -----------------------------------------------------------

    def catch_up(self) -> bool:
        """Apply every pending delta; returns True when the attached
        snapshot is fresh on exit. A poisoned overlay (or a full slab)
        compacts instead — the rebuild path. A gapped feed (the shed
        consumer's catch-up window rolled over; ``CdcGapError``) also
        compacts: the rebuild reads the host store directly, so lost
        events are folded in rather than crashing the querying thread."""
        from orientdb_tpu.cdc.feed import CdcGapError
        from orientdb_tpu.obs.trace import span

        db = self.db
        with self._lock:
            ov = self.overlay
            if ov is None or self._consumer is None:
                return False
            if ov.poisoned is not None:
                self.compact(f"poisoned: {ov.poisoned}")
                return db._snapshot_epoch == db.mutation_epoch
            try:
                with span("snapshot.delta.apply") as sp:
                    applied = 0
                    for _round in range(64):
                        events = self._stash or self._consumer.poll(
                            max_events=512, timeout=0.0
                        )
                        self._stash = []
                        if events:
                            applied += len(events)
                            if not self._apply_batch(events):
                                # poisoned mid-batch: rebuild covers the rest
                                self.compact(
                                    f"poisoned: {self.overlay.poisoned}"
                                    if self.overlay is not None
                                    else "poisoned"
                                )
                                break
                            continue
                        # queue drained: stamp freshness under db._lock —
                        # every write counted in mutation_epoch offered its
                        # event before releasing the lock, so an empty poll
                        # here proves the snapshot covers the epoch. Writes
                        # that BYPASS the feed (BulkLoader on a WAL-less
                        # db) poison the overlay atomically with their
                        # epoch bump instead — recheck before stamping, or
                        # a flush racing this drain would be stamped over.
                        with db._lock:
                            if (
                                self.overlay is not None
                                and self.overlay.poisoned is not None
                            ):
                                break  # compact below covers the epoch
                            more = self._consumer.poll(
                                max_events=512, timeout=0.0
                            )
                            if not more:
                                db._snapshot_epoch = db.mutation_epoch
                                break
                        self._stash = more
                    sp.set("events", applied)
            except CdcGapError as e:
                metrics.incr("snapshot.delta.cdc_gaps")
                log.warning("changefeed gapped (%s): compacting", e)
                self.compact("cdc gap: resync from current state")
                return db._snapshot_epoch == db.mutation_epoch
            ov = self.overlay
            if ov is not None and ov.poisoned is not None:
                # poison landed after the entry check (a feed-bypassing
                # writer, or mid-batch): rebuild now, not next call
                self.compact(f"poisoned: {ov.poisoned}")
            elif ov is not None:
                fill = ov.slab_fill()
                metrics.gauge("snapshot.delta.slab_fill", round(fill, 4))
                if (
                    fill >= config.delta_compact_ratio
                    or ov.dead_fraction() >= config.delta_compact_ratio
                ):
                    self.compact(f"slab fill {fill:.2f}")
            return db._snapshot_epoch == db.mutation_epoch

    # -- event application --------------------------------------------------

    def _apply_batch(self, events: List[Dict]) -> bool:
        """Apply one ordered event batch; False when the overlay
        poisoned (caller compacts)."""
        ov = self.overlay
        if ov is None:
            return False
        patches = _PatchSet()
        for ev in events:
            if ov.poisoned is not None:
                break
            try:
                self._apply_event(ov, ev, patches)
            except DeltaUnsupported as e:
                ov.poison(str(e))
            except Exception as e:  # defense: never wedge the feed
                ov.poison(f"{type(e).__name__}: {e}")
        self._flush_patches(ov, patches)
        ov.applied_events += len(events)
        ov.data_version += 1
        metrics.incr("snapshot.delta.events", len(events))
        return ov.poisoned is None

    def _flush_patches(self, ov: SnapshotOverlay, patches: _PatchSet) -> None:
        if patches.empty():
            return
        dg = ov.snap._device_cache
        if dg is None:
            return  # host arrays already patched; upload happens lazily
        # the scatter-patch upload is a device transfer: guard it with
        # the device fault domain (lazy import — this module loads
        # before the exec stack). A retry re-applies the same patches —
        # functional .at[].set of the same values, so idempotent. On
        # exhaustion the overlay poisons itself: the next catch-up
        # compacts (host-side rebuild, fresh upload) and queries serve
        # the oracle meanwhile — compaction is the ladder's relief
        # actuator here, not another faultable dispatch.
        from orientdb_tpu.exec import devicefault

        def _upload() -> int:
            devicefault.transfer_point()
            n = 0
            for phase in patches.phases:
                if phase:
                    n += dg.apply_patches(phase)
            return n

        try:
            nbytes = devicefault.domain.run(
                _upload, db=self.db, stage="delta_apply"
            )
        except devicefault.DeviceQuarantined as e:
            ov.poison(f"device fault during delta apply: {e}")
            return
        ov.upload_bytes += nbytes
        metrics.incr("snapshot.delta.upload_bytes", nbytes)

    def _apply_event(
        self, ov: SnapshotOverlay, ev: Dict, patches: _PatchSet
    ) -> None:
        op = ev.get("op")
        if op not in ("create", "update", "delete"):
            return
        rid = self._rid_of(ev)
        if rid is None:
            raise DeltaUnsupported("event without rid")
        snap = ov.snap
        if op == "delete":
            if rid in snap.rid_to_idx:
                self._delete_vertex(ov, rid, patches)
                return
            hit = self._find_edge(ov, rid)
            if hit is not None:
                self._tombstone_edge(ov, hit[0], hit[1], patches)
            return  # unknown rid: plain document / already gone
        cname = ev.get("class")
        if cname is None:
            raise DeltaUnsupported(f"classless {op} for {rid}")
        cls = self.db.schema.get_class(cname)
        if cls is None:
            raise DeltaUnsupported(f"unknown class {cname!r}")
        if not (cls.is_vertex_type or cls.is_edge_type):
            return  # plain documents are not in the snapshot
        record = ev.get("record") or {}
        if cls.is_edge_type:
            self._apply_edge(ov, cname, rid, record, op, patches)
        else:
            self._apply_vertex(ov, cname, rid, record, op, patches)

    @staticmethod
    def _rid_of(ev: Dict) -> Optional[RID]:
        try:
            return RID.parse(ev["rid"])
        except (KeyError, ValueError):
            return None

    # -- vertices -----------------------------------------------------------

    def _apply_vertex(
        self,
        ov: SnapshotOverlay,
        cname: str,
        rid: RID,
        record: Dict,
        op: str,
        patches: _PatchSet,
    ) -> None:
        snap = ov.snap
        idx = snap.rid_to_idx.get(rid)
        if idx is None:
            if op == "update":
                # at-least-once: the create may have been applied by an
                # earlier delivery of a later state — but an update for
                # a vertex we never saw means the stream and the
                # snapshot diverged
                raise DeltaUnsupported(f"update for unknown vertex {rid}")
            cid = snap.class_id_of.get(cname.lower())
            if cid is None:
                raise DeltaUnsupported(f"class {cname!r} not in snapshot")
            if ov.next_v_slot >= ov.cap_vertices:
                raise DeltaUnsupported("vertex slab full")
            idx = ov.next_v_slot
            ov.next_v_slot = idx + 1
            ov.mark_topology_dirty()
            snap.v_cluster[idx] = rid.cluster
            snap.v_position[idx] = rid.position
            self._patch_vertex_columns(ov, idx, record, patches)
            snap.rid_to_idx[rid] = idx
            # v_class is the liveness bit: host write + device patch
            # land LAST so a concurrent dispatch never admits a
            # half-written row
            snap.v_class[idx] = cid
            patches.add(_PH_LIVE, "v_class", idx, np.int32(cid))
            metrics.incr("snapshot.delta.vertex_inserts")
            return
        # update (or create redelivery): patch columns in place
        self._patch_vertex_columns(ov, idx, record, patches)
        metrics.incr("snapshot.delta.vertex_updates")

    def _patch_vertex_columns(
        self, ov: SnapshotOverlay, idx: int, record: Dict, patches: _PatchSet
    ) -> None:
        self._patch_columns(
            ov,
            ov.snap.v_columns,
            ov.snap.v_non_columnar,
            "v",
            idx,
            record,
            patches,
        )

    def _patch_columns(
        self,
        ov: SnapshotOverlay,
        columns: Dict[str, PropertyColumn],
        non_columnar,
        prefix: str,
        idx: int,
        record: Dict,
        patches: _PatchSet,
    ) -> None:
        from orientdb_tpu.storage.durability import _dec

        fields = {
            k: _dec(v) for k, v in record.items() if not k.startswith("@")
        }
        for name, val in fields.items():
            if name in columns or name in non_columnar:
                continue
            if isinstance(val, (bool, int, float, str)):
                # the snapshot build would have made this a column —
                # ignoring it would silently drop device predicates
                raise DeltaUnsupported(
                    f"new columnar property {name!r}"
                )
            # lists/links/maps were never columnar: host fallback reads
            # the live record, nothing to patch
        for name, col in columns.items():
            val = fields.get(name)
            have = name in fields and val is not None
            if have and not isinstance(val, (bool, int, float, str)):
                have = False  # non-scalar into a columnar slot: absent
            if have:
                code = self._encode(ov, col, val)
                patches.add(_PH_DATA, f"{prefix}:{name}:v", idx, code)
                patches.add(_PH_DATA, f"{prefix}:{name}:p", idx, True)
                col.values[idx] = code
                col.present[idx] = True
            elif bool(col.present[idx]):
                patches.add(_PH_DATA, f"{prefix}:{name}:p", idx, False)
                col.present[idx] = False

    def _encode(self, ov: SnapshotOverlay, col: PropertyColumn, val):
        if col.kind == "str":
            if not isinstance(val, str):
                raise DeltaUnsupported(
                    f"non-string into string column {col.name!r}"
                )
            code = col.dict_lookup.get(val) if col.dict_lookup else None
            if code is None:
                if col.dictionary is None:
                    raise DeltaUnsupported(
                        f"string column {col.name!r} has no dictionary"
                    )
                # append IN PLACE — DeviceColumn/predicate closures share
                # this list object, so new recordings see the grown
                # dictionary. Equality/IN stay exact on appended codes;
                # ordered compares refuse to compile until compaction
                # re-sorts (predicates._dict_sorted), and the plan-gen
                # bump re-records every cached plan whose baked code
                # tables are now too short.
                col.dictionary.append(val)
                code = len(col.dictionary) - 1
                if col.dict_lookup is None:
                    col.dict_lookup = {}
                col.dict_lookup[val] = code
                col._dict_arr = None
                col.dict_unsorted = True
                ov.bump_plan_gen()
                metrics.incr("snapshot.delta.dict_appends")
            return np.int32(code)
        if col.kind == "int":
            if isinstance(val, float) and not float(val).is_integer():
                raise DeltaUnsupported(
                    f"float into int column {col.name!r}"
                )
            if isinstance(val, str):
                raise DeltaUnsupported(
                    f"string into {col.kind} column {col.name!r}"
                )
            iv = int(val)
            if not (-(2**31) + 2 <= iv < 2**31):
                raise DeltaUnsupported(
                    f"out-of-range int into column {col.name!r}"
                )
            return np.int32(iv)
        if col.kind == "float":
            if isinstance(val, str):
                raise DeltaUnsupported(
                    f"string into float column {col.name!r}"
                )
            return np.float32(val)
        if col.kind == "bool":
            if not isinstance(val, bool):
                raise DeltaUnsupported(
                    f"non-bool into bool column {col.name!r}"
                )
            return np.int32(bool(val))
        raise DeltaUnsupported(f"column kind {col.kind!r}")

    def _delete_vertex(
        self, ov: SnapshotOverlay, rid: RID, patches: _PatchSet
    ) -> None:
        snap = ov.snap
        idx = snap.rid_to_idx.pop(rid, None)
        if idx is None:
            return
        ov.mark_topology_dirty()
        # liveness first: class -1 excludes the row from every class
        # mask and from the armed liveness conjunct
        snap.v_class[idx] = -1
        patches.add(_PH_DEAD, "v_class", idx, np.int32(-1))
        ov.dead_vertices += 1
        # cascade: tombstone every incident edge (the host store's
        # cascade does not WAL-log per-edge deletes)
        for cname, csr in snap.edge_classes.items():
            slab = ov.edge_slabs[cname]
            lo, hi = int(csr.indptr_out[idx]), int(csr.indptr_out[idx + 1])
            for pos in range(lo, hi):
                self._tombstone_edge(ov, cname, pos, patches)
            lo, hi = int(csr.indptr_in[idx]), int(csr.indptr_in[idx + 1])
            for ip in range(lo, hi):
                out_pos = int(csr.edge_id_in[ip])
                if out_pos >= 0:
                    self._tombstone_edge(ov, cname, out_pos, patches)
            for pos in range(slab.base, slab.next_slot):
                if csr.live[pos] and (
                    int(csr._edge_src[pos]) == idx
                    or int(csr.dst[pos]) == idx
                ):
                    self._tombstone_edge(ov, cname, pos, patches)
        metrics.incr("snapshot.delta.vertex_deletes")

    # -- edges --------------------------------------------------------------

    def _find_edge(
        self, ov: SnapshotOverlay, rid: RID
    ) -> Optional[Tuple[str, int]]:
        for cname, csr in ov.snap.edge_classes.items():
            pos = ov.edge_slabs[cname].rid_pos(csr).get(rid)
            if pos is not None:
                return cname, pos
        return None

    def _apply_edge(
        self,
        ov: SnapshotOverlay,
        cname: str,
        rid: RID,
        record: Dict,
        op: str,
        patches: _PatchSet,
    ) -> None:
        snap = ov.snap
        csr = snap.edge_classes.get(cname)
        if csr is None:
            # edge class created after the snapshot build
            raise DeltaUnsupported(f"edge class {cname!r} not in snapshot")
        slab = ov.edge_slabs[cname]
        pos = slab.rid_pos(csr).get(rid)
        if pos is not None:
            # update (or create redelivery): property patch only —
            # endpoints are immutable
            self._patch_columns(
                ov,
                csr.edge_columns,
                csr.non_columnar,
                f"e:{cname}:c",
                pos,
                record,
                patches,
            )
            metrics.incr("snapshot.delta.edge_updates")
            return
        if op == "update":
            raise DeltaUnsupported(f"update for unknown edge {rid}")
        try:
            src_rid = RID.parse(str(record["@out"]))
            dst_rid = RID.parse(str(record["@in"]))
        except (KeyError, ValueError):
            raise DeltaUnsupported(f"edge create without endpoints {rid}")
        src = snap.rid_to_idx.get(src_rid)
        dst = snap.rid_to_idx.get(dst_rid)
        if src is None or dst is None:
            raise DeltaUnsupported(f"edge {rid} endpoint not in snapshot")
        if slab.next_slot >= slab.cap:
            raise DeltaUnsupported(f"edge slab full for {cname!r}")
        ov.mark_topology_dirty()
        pos = slab.next_slot
        slab.next_slot = pos + 1
        p = f"e:{cname}"
        csr._edge_src[pos] = src
        csr.dst[pos] = dst
        csr.edge_rids[pos] = rid
        slab.rid_pos(csr)[rid] = pos
        patches.add(_PH_DATA, f"{p}:edge_src", pos, np.int32(src))
        patches.add(_PH_DATA, f"{p}:dst", pos, np.int32(dst))
        # bucket-index the new slot (DATA phase: the entry lands before
        # the LIVE flip below, so readers never see a live unindexed
        # edge — a dead indexed slot is filtered by the live mask)
        ov.bucket_add(cname, src, dst, pos - slab.base, patches)
        self._patch_columns(
            ov,
            csr.edge_columns,
            csr.non_columnar,
            f"{p}:c",
            pos,
            record,
            patches,
        )
        # liveness LAST (see module docstring)
        csr.live[pos] = True
        patches.add(_PH_LIVE, f"{p}:live", pos, True)
        metrics.incr("snapshot.delta.edge_inserts")

    def _tombstone_edge(
        self, ov: SnapshotOverlay, cname: str, pos: int, patches: _PatchSet
    ) -> None:
        snap = ov.snap
        csr = snap.edge_classes[cname]
        if not bool(csr.live[pos]):
            return
        ov.mark_topology_dirty()
        slab = ov.edge_slabs[cname]
        p = f"e:{cname}"
        # liveness first (bitmap-hop path), endpoints after (CSR path)
        csr.live[pos] = False
        patches.add(_PH_DEAD, f"{p}:live", pos, False)
        if pos < slab.base:
            # base CSR slots stay in the expansion output: -1 endpoints
            # turn them into padding (the CSR expand masks nbr < 0)
            csr.dst[pos] = -1
            patches.add(_PH_DATA, f"{p}:dst", pos, np.int32(-1))
            ip = int(slab.in_pos(csr)[pos])
            if ip >= 0:
                csr.src[ip] = -1
                patches.add(_PH_DATA, f"{p}:src", ip, np.int32(-1))
        slab.dead += 1
        metrics.incr("snapshot.delta.edge_deletes")

    def refresh_plans(self) -> None:
        """Drop every cached plan so the next executions re-record at
        the CURRENT slab occupancy. Recorded schedules pin their
        overflow thresholds at recording-time occupancy (+headroom);
        a long delta run replays in place until a bucket crossing
        forces a re-record mid-traffic. Callers expecting a sustained
        write burst (bulk loads, the bench's warm phase) can take the
        re-record at a time of their choosing instead."""
        with self._lock:
            ov = self.overlay
            if ov is not None:
                ov.bump_plan_gen()

    # -- compaction ---------------------------------------------------------

    def compact(self, reason: str) -> GraphSnapshot:
        """Fold the slabs back into a clean CSR: rebuild from the host
        store, persist the clean epoch through ``storage/epochs.py``
        when the database is durable (content-addressed artifact), pad
        a fresh overlay, and swap it in. The OLD snapshot's device
        buffers free when its last in-flight dispatch releases
        (``GraphSnapshot.release`` refcounting) — dispatches admitted
        on epoch N finish on epoch N."""
        from orientdb_tpu.obs.trace import span
        from orientdb_tpu.storage.snapshot import build_snapshot

        db = self.db
        with self._lock, span("snapshot.compact", reason=reason[:80]):
            old = db._snapshot
            with db._lock:
                snap = build_snapshot(db)
                directory = self.epoch_dir or getattr(
                    db, "_durability_dir", None
                )
                if directory:
                    try:
                        from orientdb_tpu.storage.epochs import (
                            save_snapshot,
                        )

                        save_snapshot(snap, directory)
                    except Exception:
                        log.exception("epoch persist failed (continuing)")
                pad_for_deltas(
                    snap, self.spare_vertices, self.spare_edges
                )
                db.attach_snapshot(snap)
                # pending events are folded into the rebuild (no write
                # can land while db._lock is held): drop them. A gapped
                # consumer cannot drain — resubscribe at the current
                # head instead (same coverage: the rebuild already holds
                # everything the stream lost)
                if self._consumer is not None:
                    from orientdb_tpu.cdc.feed import CdcGapError, feed_of

                    try:
                        while self._consumer.poll(
                            max_events=512, timeout=0.0
                        ):
                            pass
                    except CdcGapError:
                        metrics.incr("snapshot.delta.cdc_gaps")
                        feed = feed_of(db, create=True)
                        feed.unregister(self._consumer.token)
                        self._consumer = self._register(feed)
                self._stash = []
            self.compactions += 1
            self.last_compact_reason = reason
            metrics.incr("snapshot.delta.compactions")
            log.info(
                "snapshot compacted (%s): epoch %d", reason, snap.epoch
            )
            if old is not None and old is not snap:
                # the swap's device-side free routes through the epoch
                # refcount (_free_device drops the old graph's ledger
                # owner); the breadcrumb makes the swap itself visible
                # in GET /debug/memory next to the watermark it moved
                from orientdb_tpu.obs.memledger import memledger

                memledger.note_event(
                    "compaction",
                    f"{reason}: epoch {getattr(old, 'epoch', '?')} -> "
                    f"{snap.epoch}",
                )
                old.release_device()
            return snap

    def stats(self) -> Dict:
        ov = self.overlay
        return {
            "armed": ov is not None,
            "compactions": self.compactions,
            "last_compact_reason": self.last_compact_reason,
            "overlay": ov.stats() if ov is not None else None,
        }


def arm_delta_maintenance(
    db,
    spare_vertices: Optional[int] = None,
    spare_edges: Optional[int] = None,
    epoch_dir: Optional[str] = None,
) -> SnapshotMaintainer:
    """Attach a delta-maintained snapshot to ``db`` and return its
    maintainer (the incremental-HBM front door). Writes after this no
    longer invalidate the device CSR wholesale: the next fresh-snapshot
    query applies the CDC delta batch instead of re-uploading."""
    m = SnapshotMaintainer(
        db,
        spare_vertices=spare_vertices,
        spare_edges=spare_edges,
        epoch_dir=epoch_dir,
    )
    m.arm()
    return m
