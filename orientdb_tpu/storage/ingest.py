"""Data ingest: synthetic generators and portable JSON export/import.

- `generate_demodb` — a demodb-shaped social graph (Profiles/HasFriend/
  Likes), the bundled-sample-database analog ([E] distribution/ demodb,
  SURVEY.md §4) used by BASELINE configs 1/2/4;
- `generate_ldbc_snb` — a simplified LDBC SNB interactive graph (Person/
  City/Tag + knows/isLocatedIn/hasInterest) for BASELINE configs 3/5; the
  official SNB generator is unavailable offline, so this reproduces its
  *shape* (power-law-ish knows degree, typed properties) deterministically;
- `export_database` / `import_database` — portable JSON with RID remapping
  on import (the [E] ODatabaseExport/ODatabaseImport path, SURVEY.md §3.5 —
  exported RIDs are remapped to freshly allocated ones, the same remap-table
  concept the snapshot loader uses for RID → dense index).
"""

from __future__ import annotations

import base64
import gzip
import json
from typing import Dict, List, Optional

import numpy as np

from orientdb_tpu.models.database import Database
from orientdb_tpu.models.record import Blob, Document, Edge, Vertex
from orientdb_tpu.models.rid import RID
from orientdb_tpu.models.schema import PropertyType
from orientdb_tpu.utils.logging import get_logger

log = get_logger("ingest")

_FIRST = [
    "alice", "bob", "carol", "dave", "eve", "frank", "grace", "heidi",
    "ivan", "judy", "mallory", "niaj", "olivia", "peggy", "rupert", "sybil",
    "trent", "victor", "wendy", "zane",
]
_LAST = [
    "smith", "jones", "brown", "wilson", "taylor", "lee", "khan", "singh",
    "garcia", "lopez", "muller", "rossi", "ivanov", "sato", "chen", "kim",
]


def generate_demodb(
    db: Optional[Database] = None,
    n_profiles: int = 1000,
    avg_friends: int = 10,
    seed: int = 7,
) -> Database:
    """Demodb-shaped social network with deterministic content (loaded
    through the bulk path — §3.5; identical structure to a
    record-at-a-time load for a given seed)."""
    from orientdb_tpu.storage.bulk import BulkLoader

    if db is None:
        db = Database("demodb")
    rng = np.random.default_rng(seed)
    prof = db.schema.create_vertex_class("Profiles")
    prof.create_property("name", PropertyType.STRING)
    prof.create_property("surname", PropertyType.STRING)
    prof.create_property("age", PropertyType.LONG)
    prof.create_property("uid", PropertyType.LONG)
    db.schema.create_edge_class("HasFriend")
    likes = db.schema.create_edge_class("Likes")
    likes.create_property("weight", PropertyType.LONG)

    bl = BulkLoader(db)
    names = rng.integers(0, len(_FIRST), n_profiles)
    surnames = rng.integers(0, len(_LAST), n_profiles)
    ages = rng.integers(18, 80, n_profiles)
    vs: List[Vertex] = []
    for i in range(n_profiles):
        vs.append(
            bl.add_vertex(
                "Profiles",
                name=f"{_FIRST[names[i]]}{i}",
                surname=_LAST[surnames[i]],
                age=int(ages[i]),
                uid=i,
            )
        )
    # HasFriend: out-degree ~ Poisson(avg_friends), no self loops, no dup
    # (src,dst) pairs
    degrees = rng.poisson(avg_friends, n_profiles)
    for i in range(n_profiles):
        if degrees[i] == 0:
            continue
        targets = rng.choice(n_profiles, size=min(int(degrees[i]), n_profiles - 1), replace=False)
        for t in targets:
            if t == i:
                continue
            bl.add_edge("HasFriend", vs[i], vs[int(t)])
    # Likes: sparser, weighted
    n_likes = n_profiles // 2
    srcs = rng.integers(0, n_profiles, n_likes)
    dsts = rng.integers(0, n_profiles, n_likes)
    weights = rng.integers(1, 10, n_likes)
    for s, d, w in zip(srcs, dsts, weights):
        if s != d:
            bl.add_edge("Likes", vs[int(s)], vs[int(d)], weight=int(w))
    bl.flush()
    log.info(
        "demodb: %d profiles, %d HasFriend, %d Likes",
        n_profiles,
        db.count_class("HasFriend"),
        db.count_class("Likes"),
    )
    return db


def generate_ldbc_snb(
    db: Optional[Database] = None,
    n_persons: int = 1000,
    seed: int = 11,
    with_messages: bool = True,
) -> Database:
    """Simplified LDBC SNB interactive graph (shape-faithful, offline).

    Covers the entity/edge subset the interactive *short reads* IS1–IS7
    touch (BASELINE configs 3/5; SURVEY.md §6 row 3): Person/City/Tag plus,
    when ``with_messages`` (default), the message layer — abstract Message
    with Post/Comment subclasses, Forum — and the edges hasCreator
    (Message→Person), replyOf (Comment→Message, forming reply trees rooted
    at Posts), containerOf (Forum→Post), hasModerator (Forum→Person).
    Message ids share one id space (posts first, then comments) so IS4–IS7
    can address any message by ``id`` the way SNB parameters do.
    """
    from orientdb_tpu.storage.bulk import BulkLoader

    if db is None:
        db = Database("snb")
    rng = np.random.default_rng(seed)
    bl = BulkLoader(db)
    person = db.schema.create_vertex_class("Person")
    for pname, pt in [
        ("id", PropertyType.LONG),
        ("firstName", PropertyType.STRING),
        ("lastName", PropertyType.STRING),
        ("birthday", PropertyType.LONG),
        ("creationDate", PropertyType.LONG),
        ("browserUsed", PropertyType.STRING),
        ("locationIP", PropertyType.STRING),
    ]:
        person.create_property(pname, pt)
    city = db.schema.create_vertex_class("City")
    city.create_property("name", PropertyType.STRING)
    tag = db.schema.create_vertex_class("Tag")
    tag.create_property("name", PropertyType.STRING)
    knows = db.schema.create_edge_class("knows")
    knows.create_property("creationDate", PropertyType.LONG)
    db.schema.create_edge_class("isLocatedIn")
    db.schema.create_edge_class("hasInterest")

    n_cities = max(4, n_persons // 100)
    n_tags = max(8, n_persons // 50)
    cities = [bl.add_vertex("City", name=f"city{i}") for i in range(n_cities)]
    tags = [bl.add_vertex("Tag", name=f"tag{i}") for i in range(n_tags)]
    browsers = ["Firefox", "Chrome", "Safari"]
    persons: List[Vertex] = []
    first = rng.integers(0, len(_FIRST), n_persons)
    last = rng.integers(0, len(_LAST), n_persons)
    bdays = rng.integers(0, 2**30, n_persons)
    created = rng.integers(2**28, 2**31 - 1, n_persons)
    browser_pick = rng.integers(0, 3, n_persons)
    for i in range(n_persons):
        persons.append(
            bl.add_vertex(
                "Person",
                id=int(i),
                firstName=_FIRST[first[i]].capitalize(),
                lastName=_LAST[last[i]].capitalize(),
                birthday=int(bdays[i]),
                creationDate=int(created[i]),
                browserUsed=browsers[browser_pick[i]],
                locationIP=f"10.0.{i % 256}.{(i // 256) % 256}",
            )
        )
    # knows: power-law-ish degrees (Zipf capped), undirected modeled as one
    # directed edge per pair (SNB stores one direction + symmetric query) —
    # the pair set dedup keeps reciprocal i↔t draws from emitting two edges,
    # which would double-count friendships in undirected IS3/IS7 reads
    raw = rng.zipf(2.0, n_persons)
    degrees = np.minimum(raw, 50)
    known_pairs = set()
    for i in range(n_persons):
        k = int(degrees[i])
        if k <= 0:
            continue
        targets = rng.choice(n_persons, size=min(k, n_persons - 1), replace=False)
        for t in targets:
            pair = (min(i, int(t)), max(i, int(t)))
            if int(t) != i and pair not in known_pairs:
                known_pairs.add(pair)
                bl.add_edge(
                    "knows",
                    persons[i],
                    persons[int(t)],
                    creationDate=int(rng.integers(2**28, 2**31 - 1)),
                )
    city_pick = rng.integers(0, n_cities, n_persons)
    for i in range(n_persons):
        bl.add_edge("isLocatedIn", persons[i], cities[city_pick[i]])
    n_interests = rng.integers(1, 5, n_persons)
    for i in range(n_persons):
        for t in rng.choice(n_tags, size=int(n_interests[i]), replace=False):
            bl.add_edge("hasInterest", persons[i], tags[int(t)])
    if with_messages:
        _generate_snb_messages(db, bl, persons, rng)
    bl.flush()
    # the SNB schema's id lookup keys ([E] LDBC DDL): indexed so the
    # compiled engine seeds IS point-lookup roots from the index instead
    # of hull-scanning the class — V-independent short reads
    db.indexes.create_index(
        "Person.id", "Person", ["id"], "NOTUNIQUE_HASH_INDEX"
    )
    if with_messages:
        db.indexes.create_index(
            "Message.id", "Message", ["id"], "NOTUNIQUE_HASH_INDEX"
        )
    log.info(
        "snb-ish: %d persons, %d knows", n_persons, db.count_class("knows")
    )
    return db


def _generate_snb_messages(db: Database, bl, persons: List[Vertex], rng) -> None:
    """Forum/Post/Comment layer for the IS1–IS7 short reads."""
    n_persons = len(persons)
    message = db.schema.create_vertex_class("Message", abstract=True)
    for pname, pt in [
        ("id", PropertyType.LONG),
        ("content", PropertyType.STRING),
        ("creationDate", PropertyType.LONG),
        ("browserUsed", PropertyType.STRING),
        ("locationIP", PropertyType.STRING),
    ]:
        message.create_property(pname, pt)
    db.schema.create_class("Post", superclasses=["Message"])
    db.schema.create_class("Comment", superclasses=["Message"])
    forum = db.schema.create_vertex_class("Forum")
    forum.create_property("id", PropertyType.LONG)
    forum.create_property("title", PropertyType.STRING)
    forum.create_property("creationDate", PropertyType.LONG)
    db.schema.create_edge_class("hasCreator")
    db.schema.create_edge_class("containerOf")
    db.schema.create_edge_class("hasModerator")
    db.schema.create_edge_class("replyOf")

    browsers = ["Firefox", "Chrome", "Safari"]
    n_forums = max(2, n_persons // 25)
    n_posts = n_persons * 2
    n_comments = n_posts * 2
    forums: List[Vertex] = []
    for i in range(n_forums):
        f = bl.add_vertex(
            "Forum",
            id=int(i),
            title=f"forum{i}",
            creationDate=int(rng.integers(2**28, 2**31 - 1)),
        )
        forums.append(f)
        bl.add_edge("hasModerator", f, persons[int(rng.integers(0, n_persons))])
    # posts: ids [0, n_posts); comments continue the same id space —
    # one message-id namespace, as SNB's substitution parameters assume
    messages: List[Vertex] = []
    post_forum = rng.integers(0, n_forums, n_posts)
    post_creator = rng.integers(0, n_persons, n_posts)
    for i in range(n_posts):
        p = bl.add_vertex(
            "Post",
            id=int(i),
            content=f"post {i} text",
            creationDate=int(rng.integers(2**28, 2**31 - 1)),
            browserUsed=browsers[int(rng.integers(0, 3))],
            locationIP=f"10.1.{i % 256}.{(i // 256) % 256}",
        )
        messages.append(p)
        bl.add_edge("containerOf", forums[int(post_forum[i])], p)
        bl.add_edge("hasCreator", p, persons[int(post_creator[i])])
    # comments: each replies to a uniformly random earlier message, giving
    # reply trees of expected logarithmic depth rooted at posts
    comment_creator = rng.integers(0, n_persons, n_comments)
    for j in range(n_comments):
        mid = n_posts + j
        parent = messages[int(rng.integers(0, len(messages)))]
        c = bl.add_vertex(
            "Comment",
            id=int(mid),
            content=f"comment {mid} text",
            creationDate=int(rng.integers(2**28, 2**31 - 1)),
            browserUsed=browsers[int(rng.integers(0, 3))],
            locationIP=f"10.2.{mid % 256}.{(mid // 256) % 256}",
        )
        messages.append(c)
        bl.add_edge("replyOf", c, parent)
        bl.add_edge("hasCreator", c, persons[int(comment_creator[j])])


# ---------------------------------------------------------------------------
# portable JSON export / import (RID remapping)
# ---------------------------------------------------------------------------


def _value_to_json(v):
    if isinstance(v, RID):
        return {"@link": str(v)}
    if isinstance(v, Document):
        return {"@link": str(v.rid)}
    if isinstance(v, (bytes, bytearray)):
        return {"@bytes": base64.b64encode(bytes(v)).decode()}
    if isinstance(v, (list, tuple)):
        return [_value_to_json(x) for x in v]
    if isinstance(v, dict):
        return {k: _value_to_json(x) for k, x in v.items()}
    return v


def export_database(db: Database, path: str) -> None:
    """Portable JSON export ([E] ODatabaseExport). `.gz` paths gzip."""
    schema = []
    for cls in db.schema.classes():
        if cls.name in ("V", "E"):
            continue
        schema.append(
            {
                "name": cls.name,
                "superclasses": cls.superclass_names,
                "abstract": cls.abstract,
                "properties": [
                    {
                        "name": p.name,
                        "type": p.type.value,
                        "mandatory": p.mandatory,
                        "notNull": p.not_null,
                        "min": p.min_value,
                        "max": p.max_value,
                    }
                    for p in cls.properties.values()
                ],
            }
        )
    indexes = []
    for i in db._indexes.all() if db._indexes is not None else []:
        entry = {
            "name": i.name,
            "class": i.class_name,
            "fields": i.fields,
            "type": i.type,
        }
        analyzer = getattr(i, "analyzer_name", None)
        if analyzer is not None:  # Lucene-grade fulltext engine survives
            entry["engine"] = "LUCENE"
            entry["metadata"] = {"analyzer": analyzer}
        indexes.append(entry)
    records = []
    for cls in db.schema.classes():
        if cls.is_edge_type:
            continue
        for doc in db.browse_class(cls.name, polymorphic=False):
            rec = {
                "@rid": str(doc.rid),
                "@class": doc.class_name,
                "@type": (
                    "vertex"
                    if isinstance(doc, Vertex)
                    else "blob" if isinstance(doc, Blob) else "document"
                ),
                "fields": _value_to_json(doc.fields()),
            }
            records.append(rec)
    edges = []
    for cls in db.schema.classes():
        if not cls.is_edge_type or cls.name == "E":
            continue
        for doc in db.browse_class(cls.name, polymorphic=False):
            if isinstance(doc, Edge):
                edges.append(
                    {
                        "@rid": str(doc.rid),
                        "@class": doc.class_name,
                        "out": str(doc.out_rid),
                        "in": str(doc.in_rid),
                        "fields": _value_to_json(doc.fields()),
                    }
                )
    payload = {
        "name": db.name,
        "schema": schema,
        "indexes": indexes,
        "records": records,
        "edges": edges,
    }
    data = json.dumps(payload).encode()
    if path.endswith(".gz"):
        with gzip.open(path, "wb") as f:
            f.write(data)
    else:
        with open(path, "wb") as f:
            f.write(data)


def import_database(path: str, name: Optional[str] = None) -> Database:
    """Portable JSON import with RID remapping ([E] ODatabaseImport: new
    RIDs are allocated and link fields rewritten through the remap table)."""
    if path.endswith(".gz"):
        with gzip.open(path, "rb") as f:
            payload = json.loads(f.read())
    else:
        with open(path, "rb") as f:
            payload = json.loads(f.read())
    db = Database(name or payload.get("name", "imported"))
    # schema first (superclasses before subclasses: simple fixpoint loop)
    pending = list(payload["schema"])
    while pending:
        progressed = False
        for entry in list(pending):
            if all(db.schema.exists_class(s) for s in entry["superclasses"]):
                cls = db.schema.create_class(
                    entry["name"],
                    superclasses=entry["superclasses"],
                    abstract=entry["abstract"],
                )
                for p in entry["properties"]:
                    cls.create_property(
                        p["name"],
                        PropertyType(p["type"]),
                        mandatory=p["mandatory"],
                        not_null=p["notNull"],
                        min_value=p.get("min"),
                        max_value=p.get("max"),
                    )
                pending.remove(entry)
                progressed = True
        if not progressed:
            raise ValueError(f"unresolvable schema superclasses: {pending}")
    remap: Dict[str, RID] = {}
    deferred_links: List[tuple] = []

    def _value_from_json(v):
        if isinstance(v, dict):
            if "@link" in v:
                return ("@deferred", v["@link"])
            if "@bytes" in v and len(v) == 1:
                return base64.b64decode(v["@bytes"])
            return {k: _value_from_json(x) for k, x in v.items()}
        if isinstance(v, list):
            return [_value_from_json(x) for x in v]
        return v

    for rec in payload["records"]:
        fields = {k: _value_from_json(v) for k, v in rec["fields"].items()}
        clean = {
            k: v
            for k, v in fields.items()
            if not (isinstance(v, tuple) and v and v[0] == "@deferred")
        }
        if rec["@type"] == "vertex":
            doc: Document = db.new_vertex(rec["@class"], **clean)
        elif rec["@type"] == "blob":
            doc = db.new_blob(clean.get("data", b""))
            for k, v in clean.items():
                if k != "data":
                    doc.set(k, v)
            if len(clean) > 1:
                db.save(doc)
        else:
            doc = db.new_element(rec["@class"], **clean)
        remap[rec["@rid"]] = doc.rid
        for k, v in fields.items():
            if isinstance(v, tuple) and v and v[0] == "@deferred":
                deferred_links.append((doc.rid, k, v[1]))
    for edge in payload["edges"]:
        src = db.load(remap[edge["out"]])
        dst = db.load(remap[edge["in"]])
        assert isinstance(src, Vertex) and isinstance(dst, Vertex)
        fields = {
            k: _value_from_json(v)
            for k, v in edge["fields"].items()
            if not isinstance(_value_from_json(v), tuple)
        }
        e = db.new_edge(edge["@class"], src, dst, **fields)
        remap[edge["@rid"]] = e.rid
    # second pass: rewrite deferred link fields through the remap table
    for rid, field, old in deferred_links:
        doc = db.load(rid)
        if doc is not None and old in remap:
            doc.set(field, remap[old])
            db.save(doc)
    for idx in payload["indexes"]:
        db.indexes.create_index(
            idx["name"], idx["class"], idx["fields"], idx["type"],
            engine=idx.get("engine"), metadata=idx.get("metadata"),
        )
    return db
