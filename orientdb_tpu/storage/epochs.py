"""On-disk snapshot epochs.

SURVEY.md §5.4's checkpoint design verbatim: "immutable graph **snapshot
epochs** (columnar CSR + properties on disk, content-addressed);
'resume' = reload + replay ingest tail". The record-level side of resume
lives in ``storage/durability.py`` (WAL + checkpoints); this module
persists the READ-side artifact — the columnar :class:`GraphSnapshot`
the compiled engine consumes — so a restarted server re-attaches by
decompressing one npz instead of an O(V+E) rebuild from the record
store. (Peak load RSS is ~2x the snapshot size — file bytes plus
decompressed arrays; an uncompressed mmap-able layout is the upgrade
path if that ever binds.)

Format: ``snapshot-<epoch>-<digest>.npz`` (all arrays, keys namespaced)
plus the JSON-encodable metadata inside the same npz under ``__meta__``.
The digest covers the metadata and array bytes, making epochs
content-addressed: identical stores produce identical filenames, and a
truncated/corrupt file fails its digest check on load instead of
attaching silently wrong data.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
from typing import List, Optional

import numpy as np

from orientdb_tpu.models.rid import RID
from orientdb_tpu.storage.snapshot import (
    EdgeClassCSR,
    GraphSnapshot,
    PropertyColumn,
)
from orientdb_tpu.utils.logging import get_logger

log = get_logger("epochs")

PREFIX = "snapshot-"


def _col_arrays(out, prefix: str, col: PropertyColumn) -> dict:
    out[f"{prefix}:v"] = col.values
    out[f"{prefix}:p"] = col.present
    return {"name": col.name, "kind": col.kind, "dictionary": col.dictionary}


def _col_restore(arrays, prefix: str, meta) -> PropertyColumn:
    return PropertyColumn(
        meta["name"],
        meta["kind"],
        arrays[f"{prefix}:v"],
        arrays[f"{prefix}:p"],
        dictionary=meta["dictionary"],
    )


def save_snapshot(snap: GraphSnapshot, directory: str) -> str:
    """Persist a snapshot epoch; returns its path."""
    if getattr(snap, "_overlay", None) is not None:
        # slab-padded form is a runtime layout, not an archival one
        # (spare rows, None edge rids); the maintainer persists the
        # CLEAN rebuild during compaction instead
        raise ValueError(
            "delta-maintained snapshots persist via epoch compaction "
            "(storage/deltas.SnapshotMaintainer.compact), not directly"
        )
    os.makedirs(directory, exist_ok=True)
    arrays: dict = {
        "v_cluster": snap.v_cluster,
        "v_position": snap.v_position,
        "v_class": snap.v_class,
    }
    meta: dict = {
        "format": 1,
        "epoch": snap.epoch,
        "num_vertices": snap.num_vertices,
        "class_names": snap.class_names,
        "class_vertex_range": {
            k: list(v) for k, v in snap.class_vertex_range.items()
        },
        "edge_closure": snap.edge_closure,
        "v_non_columnar": sorted(snap.v_non_columnar),
        "v_columns": {},
        "edges": {},
    }
    for k, arr in snap.class_closure.items():
        arrays[f"closure:{k}"] = arr
    for name, col in snap.v_columns.items():
        meta["v_columns"][name] = _col_arrays(arrays, f"vc:{name}", col)
    for cname, csr in snap.edge_classes.items():
        p = f"e:{cname}"
        arrays[f"{p}:indptr_out"] = csr.indptr_out
        arrays[f"{p}:dst"] = csr.dst
        arrays[f"{p}:indptr_in"] = csr.indptr_in
        arrays[f"{p}:src"] = csr.src
        arrays[f"{p}:edge_id_in"] = csr.edge_id_in
        arrays[f"{p}:erid_c"] = np.array(
            [r.cluster for r in csr.edge_rids], np.int32
        )
        arrays[f"{p}:erid_p"] = np.array(
            [r.position for r in csr.edge_rids], np.int32
        )
        emeta = {
            "non_columnar": sorted(csr.non_columnar),
            "out_degree_max": int(csr.out_degree_max),
            "in_degree_max": int(csr.in_degree_max),
            "columns": {},
        }
        for n, col in csr.edge_columns.items():
            emeta["columns"][n] = _col_arrays(arrays, f"{p}:c:{n}", col)
        meta["edges"][cname] = emeta
    buf = io.BytesIO()
    np.savez_compressed(
        buf, __meta__=np.frombuffer(json.dumps(meta).encode(), np.uint8), **arrays
    )
    data = buf.getvalue()
    digest = hashlib.sha256(data).hexdigest()[:16]
    name = f"{PREFIX}{snap.epoch:012d}-{digest}.npz"
    path = os.path.join(directory, name)
    if os.path.exists(path):
        # content-addressed: identical epoch already on disk (retention
        # still runs — a dedup-hit save must enforce the policy too)
        _prune_epochs(directory, keep=path)
        return path
    from orientdb_tpu.storage.durability import atomic_write

    atomic_write(path, data)
    _prune_epochs(directory, keep=path)
    log.info("snapshot epoch %d saved: %s (%d bytes)", snap.epoch, name, len(data))
    return path


def _prune_epochs(directory: str, keep: str) -> None:
    """Retention: keep the newest two epochs, plus ``keep`` — after a
    recovery that fell back to an older checkpoint, newer-epoch files may
    exist on disk and the current epoch would otherwise be pruned as "old"
    the moment it was saved."""
    for old in list_epochs(directory)[:-2]:
        if old == keep:
            continue
        try:
            os.remove(old)
        except OSError:
            pass


def load_snapshot(path: str) -> GraphSnapshot:
    """Load a persisted epoch, verifying its content digest."""
    with open(path, "rb") as f:
        data = f.read()
    digest = hashlib.sha256(data).hexdigest()[:16]
    want = os.path.basename(path).rsplit("-", 1)[-1].split(".")[0]
    if digest != want:
        raise ValueError(
            f"snapshot {os.path.basename(path)} fails its content digest "
            "(truncated or corrupt)"
        )
    arrays = np.load(io.BytesIO(data), allow_pickle=False)
    meta = json.loads(bytes(arrays["__meta__"]))
    if meta.get("format") != 1:
        raise ValueError(f"unsupported snapshot format {meta.get('format')!r}")
    snap = GraphSnapshot()
    snap.epoch = meta["epoch"]
    snap.num_vertices = meta["num_vertices"]
    snap.v_cluster = arrays["v_cluster"]
    snap.v_position = arrays["v_position"]
    snap.v_class = arrays["v_class"]
    snap.rid_to_idx = {
        RID(int(c), int(p)): i
        for i, (c, p) in enumerate(zip(snap.v_cluster, snap.v_position))
    }
    snap.class_names = meta["class_names"]
    snap.class_id_of = {n.lower(): i for i, n in enumerate(snap.class_names)}
    snap.class_vertex_range = {
        k: tuple(v) for k, v in meta["class_vertex_range"].items()
    }
    snap.edge_closure = meta["edge_closure"]
    snap.v_non_columnar = set(meta["v_non_columnar"])
    for key in arrays.files:
        if key.startswith("closure:"):
            snap.class_closure[key[len("closure:"):]] = arrays[key]
    for name, cmeta in meta["v_columns"].items():
        snap.v_columns[name] = _col_restore(arrays, f"vc:{name}", cmeta)
    for cname, emeta in meta["edges"].items():
        p = f"e:{cname}"
        csr = EdgeClassCSR(cname)
        csr.indptr_out = arrays[f"{p}:indptr_out"]
        csr.dst = arrays[f"{p}:dst"]
        csr.indptr_in = arrays[f"{p}:indptr_in"]
        csr.src = arrays[f"{p}:src"]
        csr.edge_id_in = arrays[f"{p}:edge_id_in"]
        csr.edge_rids = [
            RID(int(c), int(pp))
            for c, pp in zip(arrays[f"{p}:erid_c"], arrays[f"{p}:erid_p"])
        ]
        csr.non_columnar = set(emeta["non_columnar"])
        csr.out_degree_max = emeta["out_degree_max"]
        csr.in_degree_max = emeta["in_degree_max"]
        for n, colmeta in emeta["columns"].items():
            csr.edge_columns[n] = _col_restore(arrays, f"{p}:c:{n}", colmeta)
        snap.edge_classes[cname] = csr
    return snap


def list_epochs(directory: str) -> List[str]:
    """Epoch files, oldest → newest."""
    if not os.path.isdir(directory):
        return []
    return sorted(
        os.path.join(directory, f)
        for f in os.listdir(directory)
        if f.startswith(PREFIX) and f.endswith(".npz")
    )


def attach_latest_epoch(db, directory: str, mesh=None) -> Optional[GraphSnapshot]:
    """Resume the read path: attach the newest persisted epoch whose epoch
    stamp matches the store's mutation epoch ('reload'); a stale or absent
    epoch returns None — the caller rebuilds ('replay ingest tail')."""
    for path in reversed(list_epochs(directory)):
        # the stamp is in the filename — skip stale epochs without
        # reading/hashing multi-GB files (e.g. after recovery fell back
        # to an older checkpoint, only an older epoch matches)
        try:
            stamp = int(os.path.basename(path)[len(PREFIX):].split("-")[0])
        except ValueError:
            stamp = -1
        if stamp != db.mutation_epoch:
            continue
        try:
            snap = load_snapshot(path)
        except Exception:
            log.exception("epoch %s unreadable; trying older", path)
            continue
        db.attach_snapshot(snap, mesh=mesh)
        return snap
    return None


def save_current_epoch(db, directory: str) -> Optional[str]:
    """Persist the database's attached snapshot (if fresh)."""
    snap = db.current_snapshot(require_fresh=True)
    if snap is None:
        return None
    return save_snapshot(snap, directory)
