"""Durable storage for the host record store: WAL + checkpoints.

The reference's durability stack is a page-oriented WAL with fuzzy/full
checkpoints and crash-recovery replay ([E]
core/.../storage/impl/local/paginated/wal/ `CASDiskWriteAheadLog`,
`OLogSequenceNumber`; SURVEY.md §2 "WAL", §3.4, §5.4). This redesign
logs *logical* operations instead of page deltas — the host store is an
in-RAM object store whose pages don't exist; what must survive a crash
is the op stream:

- ``WriteAheadLog`` — append-only file of CRC-framed JSON entries, each
  carrying a monotonically increasing LSN. A torn tail (crash mid-append)
  is detected by the CRC/framing and discarded, which is exactly the
  atomicity boundary: entries are whole or gone.
- transactions commit as ONE ``{"op": "tx", "ops": [...]}`` entry,
  appended only after the in-memory commit succeeded — a crash between
  apply and append loses the tx wholesale (it was never acknowledged
  durable), never partially ([E] OTransactionOptimistic's all-or-nothing
  commit, SURVEY.md §3.4).
- ``checkpoint(db)`` — RID-faithful full snapshot of schema + clusters +
  indexes (the [E] full-checkpoint analog), stamped with the mutation
  epoch and the last LSN it covers; recovery loads the newest valid
  checkpoint and replays only WAL entries with ``lsn >`` that.
- ``open_database(dir)`` — recovery entry point: checkpoint load + WAL
  tail replay + re-arm logging.

Unlike EXPORT/IMPORT (``storage/ingest.py``), which remaps RIDs for
portability, everything here preserves RIDs exactly — WAL entries
reference records by RID, so the checkpoint beneath them must too.
"""

from __future__ import annotations

import base64
import json
import os
import zlib
from typing import Dict, List, Optional, Tuple

from orientdb_tpu.models.database import Database
from orientdb_tpu.models.record import Blob, Direction, Document, Edge, Vertex
from orientdb_tpu.models.rid import RID
from orientdb_tpu.models.schema import PropertyType
from orientdb_tpu.utils.config import config
from orientdb_tpu.utils.logging import get_logger
from orientdb_tpu.utils.metrics import metrics

log = get_logger("durability")

WAL_FILE = "wal.log"
CHECKPOINT_PREFIX = "checkpoint-"


# ---------------------------------------------------------------------------
# value codec (RID-faithful; contrast ingest._value_to_json which remaps)
# ---------------------------------------------------------------------------


def bytes_to_wire(v) -> Dict:
    """The ONE definition of the ``{"@bytes": base64}`` wire framing for
    raw byte values — shared by the durability/export codecs, the HTTP
    and binary channels, and write forwarding (decoder: ``_dec``)."""
    return {"@bytes": base64.b64encode(bytes(v)).decode()}


def json_channel_default(v):
    """``json.dumps`` default for the lenient wire channels: bytes get
    the @bytes framing, everything else the channel's historical
    stringification."""
    if isinstance(v, (bytes, bytearray)):
        return bytes_to_wire(v)
    return str(v)


def _enc(v):
    if isinstance(v, RID):
        return {"@link": str(v)}
    if isinstance(v, Document):
        return {"@link": str(v.rid)}
    if isinstance(v, (bytes, bytearray)):
        return bytes_to_wire(v)
    if isinstance(v, (list, tuple)):
        return [_enc(x) for x in v]
    if isinstance(v, dict):
        return {k: _enc(x) for k, x in v.items()}
    return v


def _dec(v):
    if isinstance(v, dict):
        if "@link" in v and len(v) == 1:
            return RID.parse(v["@link"])
        if "@bytes" in v and len(v) == 1:
            return base64.b64decode(v["@bytes"])
        return {k: _dec(x) for k, x in v.items()}
    if isinstance(v, list):
        return [_dec(x) for x in v]
    return v


def _enc_fields(doc: Document) -> Dict:
    return {k: _enc(v) for k, v in doc.fields().items()}


# ---------------------------------------------------------------------------
# the WAL
# ---------------------------------------------------------------------------


class WriteAheadLog:
    """Append-only logical op log with CRC framing and LSNs.

    Line format: ``<crc32-hex-8> <json>\\n`` where the CRC covers the JSON
    bytes. Reading stops at the first torn/corrupt line — everything
    before it is durable, everything from it on never happened."""

    def __init__(self, path: str, fsync: Optional[bool] = None) -> None:
        self.path = path
        self.fsync = config.wal_fsync if fsync is None else fsync
        self.next_lsn = 1
        self.replaying = False
        self._fh = None
        self._native = None  # group-commit appender (native/walappend.cpp)
        self._native_tried = False
        # latched ONCE: consulting the mutable config per append could
        # interleave a synchronous Python write ahead of still-queued
        # native batches, breaking file-order == LSN-order
        self._use_native = self.fsync and config.wal_native
        self._native_waiters = 0  # appenders inside nat.wait (see close)
        self._closing = False  # gate: appends hold off while close drains
        # append serialization: record saves run under the database lock,
        # but DDL observers and sequence.next() append from arbitrary
        # threads — LSN allocation and the file write must be atomic
        import threading

        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        if self._use_native:
            # warm the native build OUTSIDE the append lock: first-ever
            # use compiles the .so (seconds) and must not stall the first
            # commit plus everyone queued behind it
            from orientdb_tpu import native

            native.load("walappend")

    # -- append ------------------------------------------------------------

    def _handle(self):
        if self._fh is None:
            self._fh = open(self.path, "ab")
        return self._fh

    def _native_handle(self):
        """The C++ group-commit appender, when fsync is on and the native
        build is available ([E] the OWriteAheadLog fsync path). Without
        fsync the Python buffered write is already cheap; with it, N
        concurrent appenders share ~one fsync per batch instead of one
        each. None → the caller uses the Python path."""
        if not self._use_native:
            return None
        if self._native is None and not self._native_tried:
            self._native_tried = True
            from orientdb_tpu import native

            self._native = native.wal_appender(self.path, do_fsync=True)
        return self._native

    def append(self, entry: Dict) -> int:
        import time as _time

        from orientdb_tpu.chaos import fault
        from orientdb_tpu.obs.trace import span

        t0 = _time.perf_counter()
        # the durability fault point: a drop/error here is a failed
        # append (the entry never becomes durable — the caller's write
        # fails BEFORE acknowledgment), a delay is an fsync stall, a
        # crash is death mid-commit (recovery finds no entry)
        with span(
            "wal.append", fsync=bool(self.fsync)
        ) as sp, fault.point("wal.fsync"):
            # stamp the originating trace onto the entry IN PLACE:
            # replication ships WAL entries verbatim, so a replica's
            # apply span — on a thread that never saw the request — can
            # join the write's trace (continue_trace force=True). The
            # caller's dict is mutated deliberately: the quorum-push
            # payload (_quorum_push) is built from the same object and
            # must carry the stamp too.
            if "trace" not in entry:
                entry["trace"] = {
                    "trace_id": sp.trace_id,
                    "span_id": sp.span_id,
                }
            lsn = self._append_inner(entry)
            sp.set("lsn", lsn)
        # the whole append — including the (group-commit) fsync wait —
        # is the durability latency a committer pays
        from orientdb_tpu.obs.registry import obs

        obs.observe("wal.append_s", _time.perf_counter() - t0)
        return lsn

    def _append_inner(self, entry: Dict) -> int:
        gen = None
        with self._lock:
            # a close() in progress is draining the native flusher; new
            # entries must wait for it or they would hit the file ahead
            # of lower-LSN batches still pending in the C++ queue
            while self._closing:
                self._cond.wait()
            lsn = self.next_lsn
            self.next_lsn += 1
            entry = {"lsn": lsn, **entry}
            data = json.dumps(entry, separators=(",", ":")).encode()
            line = b"%08x %s\n" % (zlib.crc32(data) & 0xFFFFFFFF, data)
            nat = self._native_handle()
            if nat is not None:
                # enqueue under the lock (file order must equal LSN order
                # for torn-tail recovery semantics) …
                gen = nat.enqueue(line)
                self._native_waiters += 1
            else:
                fh = self._handle()
                fh.write(line)
                fh.flush()
                if self.fsync:
                    os.fsync(fh.fileno())
        if gen is not None:
            # … but wait for durability OUTSIDE it, GIL released: other
            # threads frame their entries meanwhile and the flusher
            # batches everything into one write+fsync (group commit)
            try:
                nat.wait(gen)
            finally:
                with self._lock:
                    self._native_waiters -= 1
                    self._cond.notify_all()
        metrics.incr("wal.append")
        return lsn

    def _drain_and_close_locked(self) -> None:
        """Drain in-flight native waiters and close both handles. Caller
        holds the lock with ``_closing`` set (appends are gated out —
        under load they would keep the waiter count from ever draining).
        Closing frees the C++ Wal (joins its flusher, deletes the
        mutex/condvar), so an appender still blocked in nat.wait would be
        a use-after-free; their batches complete independently, so the
        drain is bounded."""
        while self._native_waiters > 0:
            self._cond.wait()
        if self._fh is not None:
            self._fh.close()
            self._fh = None
        if self._native is not None:
            self._native.close()
            self._native = None
        self._native_tried = False

    def close(self) -> None:
        with self._lock:
            self._closing = True
            try:
                self._drain_and_close_locked()
            finally:
                self._closing = False
                self._cond.notify_all()

    # -- read --------------------------------------------------------------

    def _scan(self) -> Tuple[List[Dict], int]:
        """(intact entries in order, byte length of the valid prefix);
        a torn/corrupt tail is excluded from both."""
        if not os.path.exists(self.path):
            return [], 0
        out: List[Dict] = []
        with open(self.path, "rb") as f:
            raw = f.read()
        pos = 0
        while pos < len(raw):
            nl = raw.find(b"\n", pos)
            if nl < 0:
                break  # torn final line (no newline)
            line = raw[pos:nl]
            if not line:
                pos = nl + 1
                continue
            if len(line) < 10 or line[8:9] != b" ":
                break
            crc_hex, data = line[:8], line[9:]
            try:
                if int(crc_hex, 16) != (zlib.crc32(data) & 0xFFFFFFFF):
                    break
                out.append(json.loads(data))
            except Exception:
                break
            pos = nl + 1
        if pos < len(raw):
            log.warning(
                "wal %s: torn/corrupt tail after lsn=%s",
                os.path.basename(self.path),
                out[-1]["lsn"] if out else 0,
            )
        return out, pos

    def read_entries(self) -> List[Dict]:
        """All intact entries, in order; a torn/corrupt tail is dropped."""
        return self._scan()[0]

    def truncate_torn_tail(self) -> None:
        """Cut the file back to its valid prefix — recovery MUST do this
        before re-arming appends, or new (acknowledged!) entries land
        after the garbage and every later recovery discards them."""
        with self._lock:
            # appends stay GATED through the whole drain+scan+truncate:
            # an append landing between a drain and the truncate would
            # sit after the torn garbage and be chopped despite having
            # been acknowledged
            self._closing = True
            try:
                self._drain_and_close_locked()
                entries, valid = self._scan()
                if os.path.exists(self.path):
                    size = os.path.getsize(self.path)
                    if valid < size:
                        with open(self.path, "rb+") as f:
                            f.truncate(valid)
            finally:
                self._closing = False
                self._cond.notify_all()

    def reset(self) -> None:
        """Truncate after a checkpoint has made the log redundant."""
        self.close()
        with open(self.path, "wb"):
            pass


# ---------------------------------------------------------------------------
# entry construction (called from Database/Schema/IndexManager hooks)
# ---------------------------------------------------------------------------


def entry_for_save(doc: Document, is_new: bool) -> Dict:
    if is_new:
        e: Dict = {
            "op": "create",
            "rid": str(doc.rid),
            "class": doc.class_name,
            "type": (
                "vertex"
                if isinstance(doc, Vertex)
                else "edge"
                if isinstance(doc, Edge)
                else "blob" if isinstance(doc, Blob) else "document"
            ),
            "version": doc.version,
            "fields": _enc_fields(doc),
        }
        if isinstance(doc, Edge):
            e["out"] = str(doc.out_rid)
            e["in"] = str(doc.in_rid)
        return e
    return {
        "op": "update",
        "rid": str(doc.rid),
        # class attribution for CDC decode (replay keys on rid alone and
        # ignores it; older logs without it fall back to the decoder's
        # learned-class cache / live lookup)
        "class": doc.class_name,
        "version": doc.version,
        "fields": _enc_fields(doc),
    }


def entry_for_delete(doc: Document) -> Dict:
    # class + preimage ride along for CDC decode (see entry_for_save):
    # a delete event's consumers (cache invalidation, search indexers)
    # need what was deleted, and only this call site still holds it.
    # Replay keys on rid alone and ignores both.
    return {
        "op": "delete",
        "rid": str(doc.rid),
        "class": doc.class_name,
        "preimage": _enc_fields(doc),
    }


# ---------------------------------------------------------------------------
# replay
# ---------------------------------------------------------------------------


def _place(db: Database, rid: RID, doc: Document) -> None:
    c = db._cluster(rid.cluster)
    while len(c.records) <= rid.position:
        c.records.append(None)
    c.records[rid.position] = doc


def _apply_entry(db: Database, e: Dict) -> None:
    op = e["op"]
    if op in ("tx", "bulk"):
        for sub in e["ops"]:
            _apply_entry(db, sub)
        return
    if op in (
        "tx2pc_prepare",
        "tx2pc_decision",
        "tx2pc_coord",
        "tx2pc_coord_done",
    ):
        # 2PC protocol records (parallel/twophase): not data — replay
        # ignores them here; recover_from_wal classifies them instead
        return
    if op == "create":
        rid = RID.parse(e["rid"])
        fields = {k: _dec(v) for k, v in e["fields"].items()}
        typ = e["type"]
        if typ == "vertex":
            doc: Document = Vertex(e["class"], fields)
        elif typ == "edge":
            doc = Edge(e["class"], fields)
            doc.out_rid = RID.parse(e["out"])
            doc.in_rid = RID.parse(e["in"])
        elif typ == "blob":
            doc = Blob.from_fields(fields)
        else:
            doc = Document(e["class"], fields)
        doc._db = db
        doc.rid = rid
        doc.version = e.get("version", 1)
        _place(db, rid, doc)
        if db._indexes is not None:
            db._indexes.on_save(doc)
        if isinstance(doc, Edge):
            # re-wire adjacency exactly as new_edge does
            src = db._load_raw(doc.out_rid)
            dst = db._load_raw(doc.in_rid)
            if isinstance(src, Vertex):
                bag = src._bag(Direction.OUT, doc.class_name)
                if rid not in bag:
                    bag.append(rid)
                    src.version += 1
            if isinstance(dst, Vertex):
                bag = dst._bag(Direction.IN, doc.class_name)
                if rid not in bag:
                    bag.append(rid)
                    dst.version += 1
        db.mutation_epoch += 1
    elif op == "update":
        rid = RID.parse(e["rid"])
        doc = db._load_raw(rid)
        if doc is None:
            log.warning("wal replay: update of missing %s skipped", rid)
            return
        if db._indexes is not None:
            db._indexes.on_delete(doc)
        doc._fields = {k: _dec(v) for k, v in e["fields"].items()}
        doc.version = e["version"]
        if db._indexes is not None:
            db._indexes.on_save(doc)
        db.mutation_epoch += 1
    elif op == "delete":
        rid = RID.parse(e["rid"])
        doc = db._load_raw(rid)
        if doc is not None:
            db.delete(doc)  # cascades exactly as the original did
    elif op == "create_class":
        db.schema.create_class(
            e["name"],
            superclasses=e.get("superclasses", ()),
            abstract=e.get("abstract", False),
            clusters=e.get("clusters", 1),
        )
    elif op == "create_property":
        cls = db.schema.get_class_or_raise(e["class"])
        cls.create_property(
            e["name"], PropertyType(e["ptype"]), **e.get("kw", {})
        )
    elif op == "alter_property":
        cls = db.schema.get_class_or_raise(e["class"])
        prop = cls.get_property(e["name"])
        if prop is not None:
            attr, v = e["attribute"], e["value"]
            if attr == "MANDATORY":
                prop.mandatory = bool(v)
            elif attr == "NOTNULL":
                prop.not_null = bool(v)
            elif attr == "READONLY":
                prop.read_only = bool(v)
            elif attr == "MIN":
                prop.min_value = v
            elif attr == "MAX":
                prop.max_value = v
    elif op == "drop_class":
        db.schema.drop_class(e["name"])
    elif op == "alter_class":
        v = e["value"]
        db.schema.alter_class(
            e["name"],
            e["attribute"],
            tuple(v) if isinstance(v, list) else v,
        )
    elif op == "rename_class":
        db.rename_class(e["old"], e["new"])
    elif op == "add_cluster":
        db.schema.add_cluster(e["class"])
    elif op == "create_index":
        db.indexes.create_index(
            e["name"], e["class"], e["fields"], e["type"],
            engine=e.get("engine"), metadata=e.get("metadata"),
        )
    elif op == "drop_index":
        db.indexes.drop_index(e["name"])
    elif op == "create_sequence":
        if db.sequences.get(e["name"]) is not None:
            # legacy alter-format entries ({op:'create_sequence',
            # alter:true}) and idempotent re-creates must not abort replay
            db.sequences.alter(
                e["name"], e.get("start"), e.get("increment"), e.get("cache")
            )
        else:
            db.sequences.create(
                e["name"], e.get("type", "ORDERED"), e.get("start", 0),
                e.get("increment", 1), e.get("cache", 20),
            )
    elif op == "alter_sequence":
        if db.sequences.get(e["name"]) is not None:
            db.sequences.alter(
                e["name"], e.get("start"), e.get("increment"), e.get("cache")
            )
    elif op == "drop_sequence":
        db.sequences.drop(e["name"])
    elif op == "seq_set":
        s = db.sequences.get(e["name"])
        if s is not None:
            s.set_value(e["value"])
    elif op == "create_function":
        db.functions.create(
            e["name"], e["body"], e.get("parameters", ()),
            language=e.get("language", "sql"),
            idempotent=e.get("idempotent", True),
        )
    elif op == "drop_function":
        db.functions.drop(e["name"])
    else:
        log.warning("wal replay: unknown op %r skipped", op)


# ---------------------------------------------------------------------------
# checkpoint (RID-faithful full snapshot)
# ---------------------------------------------------------------------------


def _rec_json(doc: Document, pos: int) -> Dict:
    """One record's checkpoint form (shared by full and delta payloads)."""
    if hasattr(doc, "rec_json"):
        # cold-tier marker (storage/coldstore.ColdRef): serialize from
        # its spilled bytes directly — checkpoints of a mostly-cold
        # database stay O(hot set) in memory, no fault-in
        return doc.rec_json(pos)
    r: Dict = {
        "pos": pos,
        "class": doc.class_name,
        "type": (
            "vertex"
            if isinstance(doc, Vertex)
            else "edge"
            if isinstance(doc, Edge)
            else "blob" if isinstance(doc, Blob) else "document"
        ),
        "version": doc.version,
        "fields": _enc_fields(doc),
    }
    if isinstance(doc, Edge):
        r["out"] = str(doc.out_rid)
        r["in"] = str(doc.in_rid)
    if isinstance(doc, Vertex):
        bags = {}
        for dname, table in (("out", doc._out_edges), ("in", doc._in_edges)):
            b = {k: [str(x) for x in v] for k, v in table.items() if v}
            if b:
                bags[dname] = b
        if bags:
            r["bags"] = bags
    return r


def _meta_payload(db: Database) -> Dict:
    """Schema/metadata part of a checkpoint (small, O(schema) not O(DB));
    shared by full checkpoints and delta checkpoints."""
    classes = []
    for cls in db.schema.classes():
        classes.append(
            {
                "name": cls.name,
                "superclasses": cls.superclass_names,
                "abstract": cls.abstract,
                "cluster_ids": list(cls.cluster_ids),
                "properties": [
                    {
                        "name": p.name,
                        "type": p.type.value,
                        "mandatory": p.mandatory,
                        "notNull": p.not_null,
                        "readOnly": p.read_only,
                        "min": p.min_value,
                        "max": p.max_value,
                        "linkedClass": p.linked_class,
                    }
                    for p in cls.properties.values()
                ],
            }
        )
    indexes = []
    for i in db._indexes.all() if db._indexes is not None else []:
        entry = {
            "name": i.name,
            "class": i.class_name,
            "fields": i.fields,
            "type": i.type,
        }
        analyzer = getattr(i, "analyzer_name", None)
        if analyzer is not None:  # Lucene-grade fulltext engine
            entry["engine"] = "LUCENE"
            entry["metadata"] = {"analyzer": analyzer}
        indexes.append(entry)
    sequences = [
        {
            "name": s.name,
            "type": s.seq_type,
            "start": s.start,
            "increment": s.increment,
            "cache": s.cache,
            "value": s.current(),
        }
        for s in (db._sequences.all() if db._sequences is not None else [])
    ]
    functions = [
        {
            "name": f.name,
            "body": f.body,
            "parameters": list(f.parameters),
            "language": f.language,
            "idempotent": f.idempotent,
        }
        for f in (db._functions.all() if db._functions is not None else [])
    ]
    return {
        "format": 1,
        "name": db.name,
        "epoch": db.mutation_epoch,
        "next_cluster": db.schema._next_cluster,
        "classes": classes,
        "indexes": indexes,
        "sequences": sequences,
        "functions": functions,
        "rr_state": dict(db._rr_state),
    }


def _checkpoint_payload(db: Database) -> Dict:
    payload = _meta_payload(db)
    clusters = {}
    for cid, c in db._clusters.items():
        recs = []
        for pos, doc in enumerate(c.records):
            if doc is None:
                continue
            recs.append(_rec_json(doc, pos))
        clusters[str(cid)] = {"len": len(c.records), "records": recs}
    payload["clusters"] = clusters
    return payload


def atomic_write(path: str, data: bytes) -> None:
    """Crash-safe publish: tmp write + flush + fsync + rename. The tmp
    name is unique per process+thread so concurrent publishers (e.g. a
    delta checkpoint racing a full one) can never clobber each other's
    in-flight tmp — which also makes the failure-path unlink below safe.
    Orphans from crashes are swept by open_database()."""
    import threading

    tmp = f"{path}.{os.getpid()}.{threading.get_ident()}.tmp"
    try:
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        # a failed publish (ENOSPC/EIO) must not leak its tmp until the
        # next restart — retried checkpoints on a tight disk would
        # otherwise accumulate one per attempt
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise


def _ckpt_lsn_from_name(filename: str) -> int:
    """checkpoint-<epoch>-<lsn>-<digest>.json → lsn (0 if unparsable)."""
    try:
        return int(filename[len(CHECKPOINT_PREFIX):].split("-")[1])
    except (IndexError, ValueError):
        return 0


def _delta_lsn_from_name(filename: str) -> int:
    """delta-<epoch>-<lsn>-<digest>.json → lsn (0 if unparsable)."""
    try:
        return int(filename[len("delta-"):].split("-")[1])
    except (IndexError, ValueError):
        return 0


def _serialize_clusters(db: Database, cluster_snap, quiesce: bool) -> Dict:
    """cluster pointer-snapshot → checkpoint JSON form. ``quiesce``
    retries a mid-mutation RuntimeError under db._lock (only meaningful
    when serializing OUTSIDE the lock)."""
    clusters: Dict = {}
    for cid, records in cluster_snap:
        recs = []
        for pos, doc in enumerate(records):
            if doc is None:
                continue
            try:
                recs.append(_rec_json(doc, pos))
            except RuntimeError:
                if not quiesce:
                    raise
                # the doc's dicts mutated mid-iteration: retry quiesced
                with db._lock:
                    recs.append(_rec_json(doc, pos))
        clusters[str(cid)] = {"len": len(records), "records": recs}
    return clusters


def wal_entries_above(directory: str, lsn: int) -> List[Dict]:
    """Every WAL entry with lsn > ``lsn`` across archives + the live
    segment, LSN-sorted. Archives whose name-encoded max LSN is covered
    are skipped unread (shared by recovery and online backup)."""
    entries: List[Dict] = []
    for seg in _wal_segments(directory):
        base = os.path.basename(seg)
        if base.startswith("wal-") and base.endswith(".log"):
            try:
                if int(base[4:-4]) <= lsn:
                    continue  # fully below the requested range
            except ValueError:
                pass
        entries.extend(WriteAheadLog(seg).read_entries())
    entries = [e for e in entries if e["lsn"] > lsn]
    entries.sort(key=lambda e: e["lsn"])
    return entries


def capture_payload(db: Database, under_lock=None, serialize_in_lock=False):
    """Shared full-state capture for checkpoint() and online backup:
    covered LSN, metadata, and POINTER copies of the cluster tables
    captured as one atomic step against writers under ``db._lock``
    (``under_lock()``, when given, runs inside that same critical
    section — checkpoint's dirty-set swap); JSON serialization runs
    OUTSIDE the lock, so writers stall only for the pointer copy.

    A record mutated after the capture may serialize torn; every such
    mutation's WAL entry carries lsn > the returned LSN, so callers must
    arrange for those entries to be replayed over the restored payload
    (recovery replays them from disk; backup bundles them in the
    archive) — or pass ``serialize_in_lock=True`` to freeze writers for
    the whole serialization (the no-journal backup fallback, where no
    tail exists to correct a torn capture). Returns (payload, lsn,
    under_lock's result)."""
    wal: Optional[WriteAheadLog] = getattr(db, "_wal", None)
    with db._lock:
        lsn = (wal.next_lsn - 1) if wal is not None else 0
        payload = _meta_payload(db)
        cluster_snap = [
            (cid, list(c.records)) for cid, c in db._clusters.items()
        ]
        extra = under_lock(lsn) if under_lock is not None else None
        if serialize_in_lock:
            payload["clusters"] = _serialize_clusters(
                db, cluster_snap, quiesce=False
            )
    if not serialize_in_lock:
        payload["clusters"] = _serialize_clusters(db, cluster_snap, quiesce=True)
    payload["lsn"] = lsn
    return payload, lsn, extra


def _tx2pc_snapshot(db: Database) -> Dict:
    """2PC protocol state for a checkpoint/delta payload. Captured
    AFTER the payload's covered LSN (callers invoke this once the
    locked capture has returned): a prepare staged since the LSN cut
    shows up in both the snapshot and the replayed WAL tail —
    recovery classifies idempotently — while capturing BEFORE the cut
    could miss a prepare whose record the checkpoint then archives.
    Taken outside ``db._lock`` because the registry acquires its own
    mutex before ``db._lock`` (prepare's lock-order); nesting the
    other way around would deadlock."""
    reg = getattr(db, "_tx2pc_registry", None)
    if reg is None:
        return {"staged": [], "decided": {}}
    return reg.snapshot_for_checkpoint()


def checkpoint(db: Database, directory: Optional[str] = None) -> str:
    """Write a full checkpoint; returns its path. With an attached WAL the
    checkpoint records the last covered LSN and ARCHIVES the log segment
    (``wal-<uptolsn>.log``) rather than deleting it — recovery that has to
    fall back to an older checkpoint (newest corrupt) replays the archived
    segments between the two, so no acknowledged write is ever lost (the
    [E] full-checkpoint + WAL-segment cut behavior)."""
    directory = directory or _dir_of(db)
    os.makedirs(directory, exist_ok=True)
    wal: Optional[WriteAheadLog] = getattr(db, "_wal", None)

    # The covered LSN, the delta-tracking baseline swap, and the state
    # capture must be ONE atomic step against writers (which mark dirty
    # under db._lock): a write landing between the capture and a later
    # reset would lose its dirty mark while being absent from the
    # payload, and the LSN-keyed archive skip in open_database would
    # then never replay it — an acknowledged, fsynced write silently
    # dropped. Recovery replays the WAL entries above the captured LSN,
    # which is what corrects capture_payload's torn serializations.
    def swap_dirty(lsn_in_lock):
        dirty_snap = db.__dict__.get("_ckpt_dirty") or set()
        db._ckpt_dirty = set()  # post-snapshot writes mark the NEW set
        prev_base = getattr(db, "_ckpt_base_lsn", None)
        db._ckpt_base_lsn = lsn_in_lock  # same critical section: a
        # concurrent delta must never see the NEW empty dirty set with
        # the OLD baseline
        return dirty_snap, prev_base

    payload, lsn, (dirty_snap, prev_base) = capture_payload(db, swap_dirty)
    # prepared-undecided 2PC stages + decided memory must cross the
    # checkpoint boundary in the payload: this checkpoint archives (and
    # eventually retires) the WAL segments holding their tx2pc_prepare
    # records, so recovery can no longer re-stage them from the log
    payload["tx2pc"] = _tx2pc_snapshot(db)
    try:
        data = json.dumps(payload, separators=(",", ":")).encode()
    except BaseException:
        with db._lock:
            db._ckpt_dirty |= dirty_snap
            if db.__dict__.get("_ckpt_base_lsn") == lsn:
                db._ckpt_base_lsn = prev_base
        raise
    digest = format(zlib.crc32(data) & 0xFFFFFFFF, "08x")
    name = (
        f"{CHECKPOINT_PREFIX}{payload['epoch']:012d}-"
        f"{payload['lsn']:012d}-{digest}.json"
    )
    path = os.path.join(directory, name)
    try:
        atomic_write(path, data)
    except BaseException:
        # publish failed: re-track the swapped-out dirty records so the
        # next delta still covers them; restore the baseline only if no
        # concurrent checkpoint has advanced it since (CAS discipline)
        with db._lock:
            db._ckpt_dirty |= dirty_snap
            if db.__dict__.get("_ckpt_base_lsn") == lsn:
                db._ckpt_base_lsn = prev_base
        raise
    if wal is not None:
        _rotate_wal(db, directory)
    # retire older checkpoints (keep the newest two for paranoia), deltas
    # covered by the newest full checkpoint, and WAL archives fully
    # covered by the oldest KEPT checkpoint
    cps = sorted(
        p for p in os.listdir(directory) if p.startswith(CHECKPOINT_PREFIX)
    )
    for old in cps[:-2]:
        try:
            os.remove(os.path.join(directory, old))
        except OSError:
            pass
    newest_lsn = _ckpt_lsn_from_name(cps[-1]) if cps else 0
    # NOTE: half-written *.tmp artifacts are swept only during
    # open_database() recovery — a live process may have a concurrent
    # atomic_write (e.g. a delta on another thread) mid-flight whose tmp
    # a sweep here would delete out from under it (os.replace → ENOENT)
    for f2 in os.listdir(directory):
        covered_delta = (
            f2.startswith(DELTA_PREFIX)
            and f2.endswith(".json")
            and _delta_lsn_from_name(f2) <= newest_lsn
        )
        if covered_delta:
            try:
                os.remove(os.path.join(directory, f2))
            except OSError:
                pass
    kept = cps[-2:]
    if kept:
        oldest_kept_lsn = min(_ckpt_lsn_from_name(c) for c in kept)
        for f2 in os.listdir(directory):
            if f2.startswith("wal-") and f2.endswith(".log"):
                try:
                    if int(f2[4:-4]) <= oldest_kept_lsn:
                        os.remove(os.path.join(directory, f2))
                except (ValueError, OSError):
                    pass
    if db._cold_tier is not None:
        # refresh the cold restart metadata: WAL archives below the
        # checkpoint may now be pruned, so the meta must advance too or
        # the cold reopen would need a range that no longer exists
        db._cold_tier.write_meta()
    return path


def _load_checkpoint(db: Database, path: str) -> int:
    with open(path, "rb") as f:
        payload = json.loads(f.read())
    return restore_payload(db, payload)


# ---------------------------------------------------------------------------
# delta checkpoints (O(writes-since-last), [E] the fuzzy-checkpoint analog)
# ---------------------------------------------------------------------------

DELTA_PREFIX = "delta-"


def _rotate_wal(db: Database, directory: str) -> int:
    """Archive the live log as ``wal-<upto>.log``; returns ``upto``."""
    wal: WriteAheadLog = db._wal
    upto = wal.next_lsn - 1
    wal.close()
    if upto > 0 and os.path.exists(wal.path):
        os.replace(wal.path, os.path.join(directory, f"wal-{upto:012d}.log"))
    wal.next_lsn = upto + 1
    return upto


def delta_checkpoint(db: Database, directory: Optional[str] = None) -> str:
    """Write an incremental checkpoint: current state of the records
    DIRTY since the last (full or delta) checkpoint, plus the (small)
    full schema/metadata — cost O(writes-since-last), not O(DB)
    (VERDICT r2 #6; [E] the WAL fuzzy-checkpoint low-water-mark,
    SURVEY.md §5.4). Recovery = newest full checkpoint, then every delta
    above it in LSN order, then the WAL tail; deltas are self-contained
    state patches (absolute record states + absolute deletions), so
    applying them over an older base after a corrupt-newest fallback is
    still correct. Falls back to a FULL checkpoint when none exists yet
    (the base the deltas build on)."""
    directory = directory or _dir_of(db)
    os.makedirs(directory, exist_ok=True)
    has_full = any(
        p.startswith(CHECKPOINT_PREFIX) for p in os.listdir(directory)
    )
    base_lsn = getattr(db, "_ckpt_base_lsn", None)
    if not has_full or db._wal is None or base_lsn is None:
        return checkpoint(db, directory)
    with db._lock:
        # re-read the baseline under the lock (authoritative value: a
        # concurrent full checkpoint may have advanced it since the
        # fallback check above), and SWAP the dirty set (don't
        # snapshot-and-subtract later): a record in the snapshot that is
        # written AGAIN after this lock releases must stay tracked for
        # the NEXT delta — subtracting the snapshot from the shared set
        # would clear it even though the newer write is absent from this
        # delta's payload. A publish failure merges the swapped-out set
        # back below.
        base_lsn = getattr(db, "_ckpt_base_lsn", None)
        dirty = db.__dict__.get("_ckpt_dirty") or set()
        db._ckpt_dirty = set()
        records = []
        deleted = []
        for rid_s in sorted(dirty):
            rid = RID.parse(rid_s)
            doc = db._load_raw(rid)
            if doc is None:
                deleted.append(rid_s)
            else:
                r = _rec_json(doc, rid.position)
                r["cluster"] = rid.cluster
                records.append(r)
        payload = _meta_payload(db)  # O(schema), not O(DB)
        payload.update(
            kind="delta",
            base_lsn=base_lsn,
            cluster_lens={
                str(cid): len(c.records) for cid, c in db._clusters.items()
            },
            records=records,
            deleted=deleted,
            lsn=db._wal.next_lsn - 1,
        )
    # same discipline as the full checkpoint: the delta advances the
    # covered LSN, so undecided 2PC state must ride with it
    payload["tx2pc"] = _tx2pc_snapshot(db)
    data = json.dumps(payload, separators=(",", ":")).encode()
    digest = format(zlib.crc32(data) & 0xFFFFFFFF, "08x")
    name = (
        f"{DELTA_PREFIX}{payload['epoch']:012d}-"
        f"{payload['lsn']:012d}-{digest}.json"
    )
    path = os.path.join(directory, name)
    try:
        atomic_write(path, data)
    except BaseException:
        # baseline was never touched pre-publish; only re-track dirty
        with db._lock:
            db._ckpt_dirty |= dirty
        raise
    with db._lock:
        # CAS: a concurrent FULL checkpoint that advanced the baseline
        # past our snapshot must not be regressed — regressing it would
        # forge delta-chain contiguity over a span only that full
        # checkpoint (and the WAL archives it retired) covers
        if db.__dict__.get("_ckpt_base_lsn") == base_lsn:
            db._ckpt_base_lsn = payload["lsn"]
    _rotate_wal(db, directory)
    metrics.incr("checkpoint.delta")
    if db._cold_tier is not None:
        db._cold_tier.write_meta()  # keep the cold restart meta current
    return path


def _apply_delta(db: Database, payload: Dict) -> int:
    """Apply a delta payload onto a recovered base; returns its LSN."""
    if "tx2pc" in payload:
        # newer 2PC protocol snapshot than the base checkpoint's
        db._tx2pc_ckpt_state = payload["tx2pc"]
    # schema/metadata: absolute — create what's missing, drop what's gone
    _sync_schema(db, payload)
    # deletions first (cascade fixes survivors' adjacency, like WAL replay)
    for rid_s in payload.get("deleted", ()):
        doc = db._load_raw(RID.parse(rid_s))
        if doc is not None:
            db.delete(doc)
    # grow clusters to their checkpointed lengths (positions are absolute)
    for cid_s, ln in payload.get("cluster_lens", {}).items():
        c = db._cluster(int(cid_s))
        while len(c.records) < ln:
            c.records.append(None)
    # place records: docs/vertices first, edges second (endpoints exist)
    idx = db._indexes
    deferred = []
    placed = []
    for r in payload.get("records", ()):
        rid = RID(r["cluster"], r["pos"])
        if r["type"] == "edge":
            deferred.append((rid, r))
            continue
        placed.append(_place_rec(db, rid, r, idx))
    for rid, r in deferred:
        placed.append(_place_rec(db, rid, r, idx))
        e = db._load_raw(rid)
        # rewire endpoints that were NOT themselves dirty (dirty ones
        # carry their full final bags and get them below)
        for end_rid, dname in ((e.out_rid, "out"), (e.in_rid, "in")):
            v = db._load_raw(end_rid)
            if isinstance(v, Vertex):
                bag = v._bag(
                    Direction.OUT if dname == "out" else Direction.IN,
                    e.class_name,
                )
                if rid not in bag:
                    bag.append(rid)
    # authoritative bags for dirty vertices
    for r in payload.get("records", ()):
        if r["type"] != "vertex" or not r.get("bags"):
            continue
        doc = db._load_raw(RID(r["cluster"], r["pos"]))
        if not isinstance(doc, Vertex):
            continue
        for dname, table in r["bags"].items():
            target = doc._out_edges if dname == "out" else doc._in_edges
            target.clear()
            for cls_name, rids in table.items():
                target[cls_name] = [RID.parse(x) for x in rids]
    db._rr_state = dict(payload.get("rr_state", {}))
    db.mutation_epoch = max(db.mutation_epoch + 1, payload["epoch"])
    return payload.get("lsn", 0)


def _place_rec(db: Database, rid: RID, r: Dict, idx) -> RID:
    old = db._load_raw(rid)
    if old is not None and idx is not None:
        idx.on_delete(old)
    fields = {k: _dec(v) for k, v in r["fields"].items()}
    typ = r["type"]
    if typ == "vertex":
        doc: Document = Vertex(r["class"], fields)
    elif typ == "edge":
        doc = Edge(r["class"], fields)
        doc.out_rid = RID.parse(r["out"])
        doc.in_rid = RID.parse(r["in"])
    elif typ == "blob":
        doc = Blob.from_fields(fields)
    else:
        doc = Document(r["class"], fields)
    doc._db = db
    doc.rid = rid
    doc.version = r["version"]
    _place(db, rid, doc)
    if idx is not None:
        idx.on_save(doc)
    return rid


def _sync_schema(db: Database, payload: Dict) -> None:
    """Make the live schema/metadata match a delta's absolute lists."""
    schema = db.schema
    pending = [c for c in payload["classes"]]
    while pending:
        progressed = False
        for entry in list(pending):
            if not all(schema.exists_class(s) for s in entry["superclasses"]):
                continue
            cls = schema.get_class(entry["name"])
            if cls is None:
                cls = schema.create_class(
                    entry["name"],
                    superclasses=entry["superclasses"],
                    abstract=entry["abstract"],
                    clusters=0,
                )
            # cluster ids are forced for EXISTING classes too: clusters
            # added after the base checkpoint (add_cluster) must be
            # re-registered or their records become unreachable
            for cid in cls.cluster_ids:
                schema._cluster_to_class.pop(cid, None)
            cls.cluster_ids = list(entry["cluster_ids"])
            for cid in cls.cluster_ids:
                schema._cluster_to_class[cid] = cls.name
            for p in entry["properties"]:
                if cls.get_property(p["name"]) is None:
                    cls.create_property(
                        p["name"],
                        PropertyType(p["type"]),
                        mandatory=p["mandatory"],
                        not_null=p["notNull"],
                        read_only=p.get("readOnly", False),
                        min_value=p.get("min"),
                        max_value=p.get("max"),
                        linked_class=p.get("linkedClass"),
                    )
            pending.remove(entry)
            progressed = True
        if not progressed:
            log.warning("delta schema: unresolved classes %s", pending)
            break
    wanted = {c["name"].lower() for c in payload["classes"]}
    for cls in list(schema.classes()):
        if cls.name.lower() not in wanted and cls.name not in ("V", "E"):
            try:
                schema.drop_class(cls.name)
            except Exception:
                pass  # e.g. still has subclasses listed later
    db.schema._next_cluster = max(
        db.schema._next_cluster, payload.get("next_cluster", 0)
    )
    have_idx = (
        {i.name: i for i in db._indexes.all()}
        if db._indexes is not None
        else {}
    )
    wanted_idx = {i["name"] for i in payload.get("indexes", ())}
    for i in payload.get("indexes", ()):
        have = have_idx.get(i["name"])
        if have is not None and (
            have.class_name != i["class"]
            or list(have.fields) != list(i["fields"])
            or have.type != i["type"]
            or getattr(have, "analyzer_name", None)
            != (i.get("metadata") or {}).get("analyzer")
        ):
            # same name, different definition: an index dropped and
            # recreated between the base checkpoint and this delta must
            # not keep its stale (class, fields, type) after recovery
            db.indexes.drop_index(i["name"])
            have = None
        if have is None:
            db.indexes.create_index(
                i["name"], i["class"], i["fields"], i["type"],
                engine=i.get("engine"), metadata=i.get("metadata"),
            )
    for name in list(have_idx):
        if name not in wanted_idx:
            db.indexes.drop_index(name)
    have_seq = (
        {s.name for s in db._sequences.all()}
        if db._sequences is not None
        else set()
    )
    for s in payload.get("sequences", ()):
        if s["name"] in have_seq:
            db.sequences.alter(s["name"], s["start"], s["increment"], s["cache"])
        else:
            db.sequences.create(
                s["name"], s["type"], s["start"], s["increment"], s["cache"]
            )
        db.sequences.get(s["name"]).set_value(s["value"])
    for s in list(have_seq):
        if s not in {x["name"] for x in payload.get("sequences", ())}:
            db.sequences.drop(s)
    have_fn = (
        {f.name for f in db._functions.all()}
        if db._functions is not None
        else set()
    )
    wanted_fn = {f["name"] for f in payload.get("functions", ())}
    for f in payload.get("functions", ()):
        if f["name"] not in have_fn:
            db.functions.create(
                f["name"],
                f["body"],
                f.get("parameters", ()),
                language=f.get("language", "sql"),
                idempotent=f.get("idempotent", True),
            )
    for f in list(have_fn):
        if f not in wanted_fn:
            db.functions.drop(f)


def restore_payload(db: Database, payload: Dict) -> int:
    """Rebuild a database from a checkpoint payload (recovery and the
    replication full-sync bootstrap both land here)."""
    if "tx2pc" in payload:
        # 2PC protocol state that rode in the payload: stashed for
        # open_database's recovery scan (a later delta's stash wins)
        db._tx2pc_ckpt_state = payload["tx2pc"]
    schema = db.schema
    # classes: fixpoint loop honors superclass order; cluster ids forced
    # to the checkpointed values (V/E already exist from bootstrap)
    pending = [c for c in payload["classes"]]
    while pending:
        progressed = False
        for entry in list(pending):
            if not all(schema.exists_class(s) for s in entry["superclasses"]):
                continue
            cls = schema.get_class(entry["name"])
            if cls is None:
                cls = schema.create_class(
                    entry["name"],
                    superclasses=entry["superclasses"],
                    abstract=entry["abstract"],
                    clusters=0,
                )
            # force exact cluster ids
            for cid in cls.cluster_ids:
                schema._cluster_to_class.pop(cid, None)
            cls.cluster_ids = list(entry["cluster_ids"])
            for cid in cls.cluster_ids:
                schema._cluster_to_class[cid] = cls.name
            for p in entry["properties"]:
                if cls.get_property(p["name"]) is None:
                    cls.create_property(
                        p["name"],
                        PropertyType(p["type"]),
                        mandatory=p["mandatory"],
                        not_null=p["notNull"],
                        read_only=p.get("readOnly", False),
                        min_value=p.get("min"),
                        max_value=p.get("max"),
                        linked_class=p.get("linkedClass"),
                    )
            pending.remove(entry)
            progressed = True
        if not progressed:
            raise ValueError(f"checkpoint schema unresolvable: {pending}")
    schema._next_cluster = payload["next_cluster"]
    # records: vertices/documents first, then edges, then bags verbatim
    deferred_edges: List[Tuple[RID, Dict]] = []
    bags_by_rid: List[Tuple[RID, Dict]] = []
    for cid_s, cdata in payload["clusters"].items():
        cid = int(cid_s)
        c = db._cluster(cid)
        while len(c.records) < cdata["len"]:
            c.records.append(None)
        for r in cdata["records"]:
            rid = RID(cid, r["pos"])
            if r["type"] == "edge":
                deferred_edges.append((rid, r))
                continue
            fields = {k: _dec(v) for k, v in r["fields"].items()}
            if r["type"] == "vertex":
                doc: Document = Vertex(r["class"], fields)
            elif r["type"] == "blob":
                doc = Blob.from_fields(fields)
            else:
                doc = Document(r["class"], fields)
            doc._db = db
            doc.rid = rid
            doc.version = r["version"]
            c.records[rid.position] = doc
            if r.get("bags"):
                bags_by_rid.append((rid, r["bags"]))
    for rid, r in deferred_edges:
        fields = {k: _dec(v) for k, v in r["fields"].items()}
        e = Edge(r["class"], fields)
        e._db = db
        e.rid = rid
        e.version = r["version"]
        e.out_rid = RID.parse(r["out"])
        e.in_rid = RID.parse(r["in"])
        db._cluster(rid.cluster).records[rid.position] = e
    for rid, bags in bags_by_rid:
        doc = db._load_raw(rid)
        if not isinstance(doc, Vertex):
            continue
        for dname, table in bags.items():
            target = doc._out_edges if dname == "out" else doc._in_edges
            for cls_name, rids in table.items():
                target[cls_name] = [RID.parse(x) for x in rids]
    # indexes last: definitions re-created, contents rebuilt from records
    for idx in payload["indexes"]:
        db.indexes.create_index(
            idx["name"], idx["class"], idx["fields"], idx["type"],
            engine=idx.get("engine"), metadata=idx.get("metadata"),
        )
    for s in payload.get("sequences", ()):
        seq = db.sequences.create(
            s["name"], s["type"], s["start"], s["increment"], s["cache"]
        )
        seq.set_value(s["value"])
    for f in payload.get("functions", ()):
        db.functions.create(
            f["name"], f["body"], f.get("parameters", ()),
            language=f.get("language", "sql"),
            idempotent=f.get("idempotent", True),
        )
    db._rr_state = dict(payload.get("rr_state", {}))
    # never move the epoch backwards onto a value already stamped into
    # this db's command cache: a replica full-sync restoring the source's
    # (smaller) counter could make pre-sync cached rows read as fresh.
    # Bumping past the local epoch invalidates every cached entry; the
    # cache itself is also dropped for immediate reclamation.
    db.mutation_epoch = max(db.mutation_epoch + 1, payload["epoch"])
    if getattr(db, "_command_cache", None) is not None:
        db._command_cache = None
    return payload.get("lsn", 0)


# ---------------------------------------------------------------------------
# open / attach
# ---------------------------------------------------------------------------


def _dir_of(db: Database) -> str:
    d = getattr(db, "_durability_dir", None) or config.wal_dir
    if d is None:
        raise ValueError(
            "no durability directory: pass one or set config.wal_dir"
        )
    return d


def enable_durability(
    db: Database, directory: Optional[str] = None, fsync: Optional[bool] = None
) -> Database:
    """Arm WAL logging on a live database (new writes become durable).

    Honors ``config.wal_enabled``'s companions ``wal_dir``/``wal_fsync``
    when arguments are omitted."""
    directory = directory or config.wal_dir
    if directory is None:
        raise ValueError("enable_durability needs a directory (or config.wal_dir)")
    os.makedirs(directory, exist_ok=True)
    db._durability_dir = directory
    wal = WriteAheadLog(os.path.join(directory, WAL_FILE), fsync=fsync)
    # continue LSNs after whatever the log (and its archives) already hold
    last = 0
    for seg in _wal_segments(directory):
        entries = WriteAheadLog(seg).read_entries()
        if entries:
            last = max(last, entries[-1]["lsn"])
    wal.next_lsn = last + 1
    db._wal = wal
    if db.mutation_epoch > 0 and last == 0 and not any(
        p.startswith(CHECKPOINT_PREFIX) for p in os.listdir(directory)
    ):
        # the database already holds data the (empty) log never saw — a
        # WAL replay or replication delta from LSN 0 cannot reproduce it.
        # Mark the base so consumers (replication full-sync, and honesty
        # in general) know deltas start above it.
        db._wal_base_lsn = 0
        db._wal_has_base = True
    db.schema.on_ddl = db._wal_log
    return db


def _wal_segments(directory: str) -> List[str]:
    """All WAL segment paths, archives first (ordered by covered lsn),
    the live log last."""
    archives = sorted(
        f for f in os.listdir(directory)
        if f.startswith("wal-") and f.endswith(".log")
    )
    out = [os.path.join(directory, f) for f in archives]
    live = os.path.join(directory, WAL_FILE)
    if os.path.exists(live):
        out.append(live)
    return out


def open_database(directory: str, name: Optional[str] = None) -> Database:
    """Recover a database from ``directory``: newest valid checkpoint (if
    any) + WAL tail replay, then re-arm logging ([E] the
    OLocalPaginatedStorage open → WAL recovery path, SURVEY.md §3.4)."""
    db = Database(name or os.path.basename(os.path.abspath(directory)))
    db._durability_dir = directory
    os.makedirs(directory, exist_ok=True)
    # sweep half-written atomic_write tmps from a crash: recovery is the
    # only point where no concurrent publisher can exist (checkpoint()
    # deliberately does NOT sweep — see the note there)
    for f2 in os.listdir(directory):
        if f2.endswith(".tmp"):
            try:
                os.remove(os.path.join(directory, f2))
            except OSError:
                pass
    ckpt_lsn = 0
    cps = sorted(
        p for p in os.listdir(directory) if p.startswith(CHECKPOINT_PREFIX)
    )
    for cp in reversed(cps):
        try:
            ckpt_lsn = _load_checkpoint(db, os.path.join(directory, cp))
            break
        except Exception:
            log.exception("checkpoint %s unreadable; trying older", cp)
            db = Database(name or os.path.basename(os.path.abspath(directory)))
            db._durability_dir = directory
    # apply delta checkpoints above the base, in LSN order. A delta only
    # covers records dirty since ITS base, so it is applied only when the
    # chain is contiguous (base_lsn <= ckpt_lsn); after a corrupt-newest
    # fallback to an older full checkpoint the chain is broken, and the
    # uncovered span replays from the kept WAL archives instead — slower
    # but exact (no acknowledged write can be skipped silently)
    deltas = sorted(
        (
            p
            for p in os.listdir(directory)
            if p.startswith(DELTA_PREFIX) and p.endswith(".json")
        ),
        key=_delta_lsn_from_name,
    )
    for dp in deltas:
        if _delta_lsn_from_name(dp) <= ckpt_lsn:
            continue
        try:
            with open(os.path.join(directory, dp), "rb") as f:
                data = f.read()
            payload = json.loads(data)
            if payload.get("base_lsn", 0) > ckpt_lsn:
                log.warning(
                    "delta %s builds on lsn %s > recovered %s (fallback "
                    "to an older base?); replaying WAL instead",
                    dp,
                    payload.get("base_lsn"),
                    ckpt_lsn,
                )
                break
            ckpt_lsn = max(ckpt_lsn, _apply_delta(db, payload))
        except Exception:
            log.exception("delta %s unreadable/unappliable; stopping at "
                          "the last good state", dp)
            break
    db._ckpt_base_lsn = ckpt_lsn
    wal = WriteAheadLog(os.path.join(directory, WAL_FILE))
    # a torn tail (crash mid-append) must be CUT, not just skipped: the
    # recovered process appends new acknowledged entries to this file, and
    # readers stop at the first corrupt line
    wal.truncate_torn_tail()
    # gather every segment (archives + live log): falling back to an older
    # checkpoint needs the archived tail between the two checkpoints.
    # Archives whose name-encoded max LSN is covered are skipped unread,
    # so replay cost tracks the uncovered tail, not total history.
    entries: List[Dict] = []
    for seg in _wal_segments(directory):
        base = os.path.basename(seg)
        if base.startswith("wal-") and base.endswith(".log"):
            try:
                if int(base[4:-4]) <= ckpt_lsn:
                    continue
            except ValueError:
                pass
        entries.extend(WriteAheadLog(seg).read_entries())
    entries.sort(key=lambda e: e["lsn"])
    wal.replaying = True
    db._wal = wal
    try:
        for e in entries:
            if e["lsn"] <= ckpt_lsn:
                continue
            try:
                _apply_entry(db, e)
                # tail entries are changes SINCE the newest checkpoint:
                # seed the dirty set so the next delta captures them
                db._mark_ckpt_dirty(e)
            except Exception:
                log.exception("wal replay failed at lsn=%s; stopping", e["lsn"])
                break
    finally:
        wal.replaying = False
    if entries:
        wal.next_lsn = max(wal.next_lsn, entries[-1]["lsn"] + 1)
    db.schema.on_ddl = db._wal_log
    # re-stage prepared-undecided 2PC transactions (locks and all): a
    # participant crash between prepare and commit must not silently
    # lose what the coordinator was told is prepared. The checkpoint's
    # embedded 2PC snapshot covers prepares whose WAL records the
    # checkpoint archived; synthesized FIRST so the replayed tail's
    # decisions override it
    from orientdb_tpu.parallel.twophase import recover_from_wal

    ckpt2pc = db.__dict__.pop("_tx2pc_ckpt_state", None) or {}
    synth: List[Dict] = [
        {
            "op": "tx2pc_prepare",
            "txid": st["txid"],
            "ops": st["ops"],
            "ttl": st.get("ttl", 60.0),
        }
        for st in ckpt2pc.get("staged", ())
    ] + [
        {"op": "tx2pc_decision", "txid": txid, "decision": d}
        for txid, d in (ckpt2pc.get("decided") or {}).items()
    ]
    try:
        recover_from_wal(db, synth + entries)
    except Exception:  # pragma: no cover - recovery must finish
        log.exception("2pc recovery scan failed for %s", db.name)
    return db
