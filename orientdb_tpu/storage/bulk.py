"""Bulk graph ingest.

Analog of the reference's bulk-import path ([E] §3.5: ODatabaseImport /
the ETL loader's batch mode with massive-insert intent; SURVEY.md §3.5
"how demodb/LDBC data gets in — matters for the loader"): the
per-record ``save()`` pipeline costs a lock round-trip, hook dispatch,
validation, and an epoch bump per record — at SNB scale that is minutes
of pure Python overhead before a single query runs. The BulkLoader
amortizes all of it:

- records append straight into clusters under ONE lock acquisition per
  flush, with schema validation and index maintenance still applied
  (uniqueness violations raise, as save() would);
- adjacency bags wire directly; endpoint versions bump exactly as
  ``new_edge`` does, so MVCC behavior matches record-at-a-time loads;
- the mutation epoch bumps once per flush, and an armed WAL receives
  one atomic ``bulk`` entry (replayed like a tx);
- hooks do NOT fire (documented intent: bulk loads bypass triggers, the
  same contract as the reference's massive-insert mode).

Usage:

    with BulkLoader(db) as bl:
        vs = [bl.add_vertex("Person", uid=i) for i in range(100_000)]
        for s, d in pairs:
            bl.add_edge("Knows", vs[s], vs[d])
    # flushed on exit; vertices/edges now have persistent RIDs
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from orientdb_tpu.models.database import Database
from orientdb_tpu.models.indexes import DuplicateKeyError
from orientdb_tpu.models.record import Direction, Edge, Vertex
from orientdb_tpu.models.rid import NEW_RID, RID
from orientdb_tpu.utils.logging import get_logger

log = get_logger("bulk")


class BulkLoader:
    def __init__(self, db: Database, wal_log: bool = True) -> None:
        self.db = db
        self.wal_log = wal_log
        self._vertices: List[Vertex] = []
        self._edges: List[Tuple[Edge, Vertex, Vertex]] = []

    # -- staging ------------------------------------------------------------

    def add_vertex(self, class_name: str, **fields) -> Vertex:
        cls = self.db._resolve_vertex_class(class_name)
        v = Vertex(cls.name, fields)
        v._db = self.db
        self._vertices.append(v)
        return v

    def add_edge(self, class_name: str, src: Vertex, dst: Vertex, **fields) -> Edge:
        cls = self.db._resolve_edge_class(class_name)
        e = Edge(cls.name, fields)
        e._db = self.db
        self._edges.append((e, src, dst))
        return e

    # -- flush --------------------------------------------------------------

    def flush(self) -> None:
        """Validate-then-place: EVERY constraint (schema validation,
        unique-index keys — including collisions within the staged batch —
        and edge-endpoint resolvability) is checked before the first
        record is placed, so a validation failure mutates nothing and the
        loader can be corrected and re-flushed. An unexpected
        placement-phase failure compensates by tombstoning whatever was
        placed, then clears the stage."""
        db = self.db
        if db.tx is not None:
            raise RuntimeError(
                "BulkLoader cannot run inside a transaction (bulk loads "
                "bypass the tx workspace; commit or rollback first)"
            )
        if not self._vertices and not self._edges:
            return
        wal_entries: Optional[List[Dict]] = (
            [] if (self.wal_log and db._wal is not None) else None
        )
        with db._lock:
            self._validate_all()
            placed: List = []
            try:
                self._place_docs(self._vertices, wal_entries, placed)
                for e, src, dst in self._edges:
                    e.out_rid = src.rid
                    e.in_rid = dst.rid
                self._place_docs(
                    [e for e, _, _ in self._edges], wal_entries, placed
                )
                for e, src, dst in self._edges:
                    src._bag(Direction.OUT, e.class_name).append(e.rid)
                    dst._bag(Direction.IN, e.class_name).append(e.rid)
                    src.version += 1
                    dst.version += 1
            except Exception:
                # compensate: nothing from this flush stays visible
                idx_mgr = db._indexes
                for d in reversed(placed):
                    if idx_mgr is not None:
                        idx_mgr.on_delete(d)
                    db._cluster(d.rid.cluster).tombstone(d.rid.position)
                    d.rid = NEW_RID
                self._vertices = []
                self._edges = []
                raise
            db.mutation_epoch += 1
            if wal_entries:
                bulk_entry = {"op": "bulk", "ops": wal_entries}
                lsn = db._wal.append(bulk_entry)
                db._mark_ckpt_dirty(bulk_entry)
                from orientdb_tpu.cdc.feed import notify_commit

                notify_commit(db, bulk_entry, lsn)
            else:
                # hooks do not fire and nothing reached the changefeed,
                # yet the epoch bumped: a CDC-derived device plane would
                # stamp itself fresh against an empty queue while
                # missing this whole flush. Poison the delta overlay
                # (next catch-up rebuilds from the host store) and drop
                # materialized views — atomically with the epoch bump,
                # so a racing catch_up can't stamp stale-fresh in
                # between. (db._lock → view lock is the same edge the
                # notify_commit callback path above already holds.)
                maint = getattr(db, "_snapshot_maintainer", None)
                ov = maint.overlay if maint is not None else None
                if ov is not None:
                    ov.poison("bulk flush bypassed the changefeed")
                vm = getattr(db, "_view_manager", None)
                if vm is not None:
                    vm.invalidate_all("bulk flush bypassed the changefeed")
        n_v, n_e = len(self._vertices), len(self._edges)
        self._vertices = []
        self._edges = []
        log.info("bulk flush: %d vertices, %d edges", n_v, n_e)

    def _validate_all(self) -> None:
        """All checks that may legitimately fail, before any mutation."""
        db = self.db
        idx_mgr = db._indexes
        staged_vertices = set(map(id, self._vertices))
        for e, src, dst in self._edges:
            for end in (src, dst):
                if not end.rid.is_persistent and id(end) not in staged_vertices:
                    raise ValueError(
                        "edge endpoints must be bulk-added vertices or "
                        "already-saved records"
                    )
        staged_keys: Dict[str, set] = {}
        by_class: Dict[str, List] = {}
        for d in self._vertices + [e for e, _, _ in self._edges]:
            by_class.setdefault(d.class_name, []).append(d)
        for cname, batch in by_class.items():
            cls = db.schema.get_class_or_raise(cname)
            db._require_concrete(cls)
            has_constraints = any(
                p.mandatory or p.not_null or p.min_value is not None
                or p.max_value is not None
                for p in cls.effective_properties().values()
            ) or cls.strict_mode
            uniques = (
                [i for i in idx_mgr.applicable_for_class(cname) if i.unique]
                if idx_mgr is not None
                else []
            )
            for d in batch:
                if has_constraints:
                    cls.validate(d.fields())
                for idx in uniques:
                    key = idx._key_of(d)
                    if key is None:
                        continue
                    if idx.get(key):
                        raise DuplicateKeyError(
                            f"index '{idx.name}': key {key!r} already mapped"
                        )
                    seen = staged_keys.setdefault(idx.name, set())
                    if key in seen:
                        raise DuplicateKeyError(
                            f"index '{idx.name}': key {key!r} duplicated "
                            "within the bulk batch"
                        )
                    seen.add(key)

    def _place_docs(self, docs, wal_entries, placed: List) -> None:
        """Placement after validation — records land in clusters/indexes
        and (when armed) the pending WAL entry list."""
        db = self.db
        idx_mgr = db._indexes
        if wal_entries is not None:
            from orientdb_tpu.storage.durability import entry_for_save
        for d in docs:
            cid = db._select_cluster(d.class_name)
            cluster = db._cluster(cid)
            pos = cluster.append(d)
            d.rid = RID(cid, pos)
            d.version = 1
            placed.append(d)
            if idx_mgr is not None:
                idx_mgr.on_save(d)
            if wal_entries is not None:
                wal_entries.append(entry_for_save(d, True))

    # -- context manager -----------------------------------------------------

    def __enter__(self) -> "BulkLoader":
        return self

    def __exit__(self, exc_type, *a) -> None:
        if exc_type is None:
            self.flush()
