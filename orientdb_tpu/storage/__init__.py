from orientdb_tpu.storage.snapshot import GraphSnapshot, build_snapshot

__all__ = ["GraphSnapshot", "build_snapshot"]
