"""Array-native snapshot builder for SF100-scale benchmarking.

The SF100 north star (BASELINE.md row 5; SURVEY.md §6 row 5 and §7 step
7) needs graphs of 10^8 edges in HBM. The record-store ingest path
(`storage/ingest.generate_*` → Documents → `build_snapshot`) tops out
around 10^6 edges per minute because it materializes every vertex/edge
as a host object; this builder constructs the columnar `GraphSnapshot`
DIRECTLY as numpy arrays — the same CSR + property-column layout
`build_snapshot` emits (snapshot.py:327) without the object detour —
so a 10^8-edge Person–knows graph builds in under a minute and uploads
as int32 CSR (the §7 "int32 compaction" memory plan).

Degree skew (SURVEY.md §5.7 "supernode degree skew", VERDICT r3 #7):
``supernodes``/``supernode_degree`` plant celebrity vertices with 10^4+
out-degrees on top of the Poisson base, so kernels see the frontier
shapes a power-law graph produces.

The Python oracle cannot run here (there are no host records), so
parity for the benched COUNT shapes comes from `numpy_2hop_count` /
`numpy_1hop_count` — exact int64 reference computations over the same
arrays (the role the Java executor plays in BASELINE.json, at array
level)."""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from orientdb_tpu.models.database import Database
from orientdb_tpu.storage.snapshot import (
    EdgeClassCSR,
    GraphSnapshot,
    PropertyColumn,
)


def build_person_knows(
    n_persons: int,
    avg_knows: int = 10,
    seed: int = 0,
    supernodes: int = 0,
    supernode_degree: int = 0,
    name: str = "bigshape",
) -> Tuple[Database, GraphSnapshot]:
    """A Person–knows graph as (schema-only Database, attached snapshot).

    Properties: ``uid`` (dense id) and ``age`` (18–79) on Person. The
    returned database holds SCHEMA ONLY — queries must run on the
    compiled engine (engine="tpu"); parity uses the numpy references
    below."""
    rng = np.random.default_rng(seed)
    db = Database(name)
    db.schema.create_vertex_class("Person")
    db.schema.create_edge_class("knows")

    V = int(n_persons)
    degrees = rng.poisson(avg_knows, V).astype(np.int64)
    if supernodes > 0:
        # celebrity vertices: a few sources with 10^4-10^5 out-degree —
        # scattered through the id space so they land in different
        # expansion chunks
        hubs = np.linspace(0, V - 1, supernodes, dtype=np.int64)
        degrees[hubs] = supernode_degree
    E = int(degrees.sum())
    indptr_out = np.concatenate([[0], np.cumsum(degrees)]).astype(np.int32)
    dst = rng.integers(0, V, E, dtype=np.int64)

    csr = EdgeClassCSR("knows")
    csr.indptr_out = indptr_out
    csr.dst = dst.astype(np.int32)
    csr.out_degree_max = int(degrees.max()) if V else 0
    order_in = np.argsort(dst, kind="stable")
    edge_src = np.repeat(np.arange(V, dtype=np.int32), degrees)
    csr._edge_src = edge_src  # pre-seed the cached property
    csr.src = edge_src[order_in].astype(np.int32)
    csr.edge_id_in = order_in.astype(np.int32)
    counts_in = np.bincount(dst, minlength=V)
    csr.indptr_in = np.concatenate([[0], np.cumsum(counts_in)]).astype(
        np.int32
    )
    csr.in_degree_max = int(counts_in.max()) if V else 0
    csr.edge_rids = []  # COUNT-only benches never marshal edge RIDs

    snap = GraphSnapshot()
    snap.num_vertices = V
    person_cluster = db.schema.get_class("Person").cluster_ids[0]
    snap.v_cluster = np.full(V, person_cluster, np.int32)
    snap.v_position = np.arange(V, dtype=np.int32)
    snap.rid_to_idx = {}  # no host records: index seeds are N/A

    all_classes = sorted(db.schema.classes(), key=lambda c: c.name)
    snap.class_names = [c.name for c in all_classes]
    snap.class_id_of = {c.name.lower(): i for i, c in enumerate(all_classes)}
    snap.v_class = np.full(V, snap.class_id_of["person"], np.int32)
    for c in all_classes:
        closure = [
            snap.class_id_of[s.name.lower()]
            for s in c.subclasses(include_self=True)
        ]
        snap.class_closure[c.name.lower()] = np.array(sorted(closure), np.int32)
    for c in all_classes:
        if c.is_vertex_type and not c.abstract:
            snap.class_vertex_range[c.name.lower()] = (
                (0, V) if c.name == "Person" else (0, 0)
            )

    ones = np.ones(V, bool)
    snap.v_columns = {
        "uid": PropertyColumn(
            "uid", "int", np.arange(V, dtype=np.int32), ones
        ),
        "age": PropertyColumn(
            "age", "int", rng.integers(18, 80, V, dtype=np.int32), ones
        ),
    }
    snap.edge_classes["knows"] = csr
    for c in all_classes:
        if c.is_edge_type:
            snap.edge_closure[c.name.lower()] = sorted(
                s.name
                for s in c.subclasses(include_self=True)
                if s.name in snap.edge_classes
            )
    snap.epoch = db.mutation_epoch
    db.attach_snapshot(snap)
    return db, snap


# ---------------------------------------------------------------------------
# exact numpy references for the benched COUNT shapes (the parity oracle
# at array level — int64 throughout, no device involved)
# ---------------------------------------------------------------------------


def _seg_sum(vals: np.ndarray, indptr: np.ndarray) -> np.ndarray:
    tot = np.concatenate([[0], np.cumsum(vals, dtype=np.int64)])
    return tot[indptr[1:].astype(np.int64)] - tot[indptr[:-1].astype(np.int64)]


def numpy_1hop_count(snap: GraphSnapshot, src_mask, dst_mask) -> int:
    """count of (p, f) pairs with src_mask[p] and dst_mask[f]."""
    csr = snap.edge_classes["knows"]
    w1 = _seg_sum(dst_mask[csr.dst].astype(np.int64), csr.indptr_out)
    return int((w1 * src_mask.astype(np.int64)).sum())


def numpy_2hop_count(snap: GraphSnapshot, src_mask, mid_mask, dst_mask) -> int:
    """count of (p, f, g) paths with the three masks applied."""
    csr = snap.edge_classes["knows"]
    w2 = _seg_sum(dst_mask[csr.dst].astype(np.int64), csr.indptr_out)
    w1 = _seg_sum((mid_mask[csr.dst] * w2[csr.dst]).astype(np.int64), csr.indptr_out)
    return int((w1 * src_mask.astype(np.int64)).sum())
