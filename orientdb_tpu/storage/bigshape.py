"""Array-native snapshot builder for SF100-scale benchmarking.

The SF100 north star (BASELINE.md row 5; SURVEY.md §6 row 5 and §7 step
7) needs graphs of 10^8 edges in HBM. The record-store ingest path
(`storage/ingest.generate_*` → Documents → `build_snapshot`) tops out
around 10^6 edges per minute because it materializes every vertex/edge
as a host object; this builder constructs the columnar `GraphSnapshot`
DIRECTLY as numpy arrays — the same CSR + property-column layout
`build_snapshot` emits (snapshot.py:327) without the object detour —
so a 10^8-edge Person–knows graph builds in under a minute and uploads
as int32 CSR (the §7 "int32 compaction" memory plan).

Degree skew (SURVEY.md §5.7 "supernode degree skew", VERDICT r3 #7):
``supernodes``/``supernode_degree`` plant celebrity vertices with 10^4+
out-degrees on top of the Poisson base, so kernels see the frontier
shapes a power-law graph produces.

The Python oracle cannot run here (there are no host records), so
parity for the benched COUNT shapes comes from `numpy_2hop_count` /
`numpy_1hop_count` — exact int64 reference computations over the same
arrays (the role the Java executor plays in BASELINE.json, at array
level)."""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from orientdb_tpu.models.database import Database
from orientdb_tpu.storage.snapshot import (
    EdgeClassCSR,
    GraphSnapshot,
    PropertyColumn,
)


def _csr_from_degrees(
    name: str, degrees: np.ndarray, dst: np.ndarray
) -> EdgeClassCSR:
    """Both-direction CSR from per-vertex out-degrees and the dst array
    in out-CSR order (the shared assembly of every array-native builder:
    indptr from cumsum, stable in-direction sort, edge ids into out
    order, degree maxima)."""
    V = degrees.shape[0]
    csr = EdgeClassCSR(name)
    csr.indptr_out = np.concatenate([[0], np.cumsum(degrees)]).astype(
        np.int32
    )
    csr.dst = dst.astype(np.int32)
    csr.out_degree_max = int(degrees.max()) if V else 0
    edge_src = np.repeat(np.arange(V, dtype=np.int32), degrees)
    csr._edge_src = edge_src  # pre-seed the cached property
    order_in = np.argsort(dst, kind="stable")
    csr.src = edge_src[order_in].astype(np.int32)
    csr.edge_id_in = order_in.astype(np.int32)
    counts_in = np.bincount(dst, minlength=V)
    csr.indptr_in = np.concatenate([[0], np.cumsum(counts_in)]).astype(
        np.int32
    )
    csr.in_degree_max = int(counts_in.max()) if V else 0
    csr.edge_rids = []  # array-native benches never marshal edge RIDs
    return csr


def build_person_knows(
    n_persons: int,
    avg_knows: int = 10,
    seed: int = 0,
    supernodes: int = 0,
    supernode_degree: int = 0,
    name: str = "bigshape",
) -> Tuple[Database, GraphSnapshot]:
    """A Person–knows graph as (schema-only Database, attached snapshot).

    Properties: ``uid`` (dense id) and ``age`` (18–79) on Person. The
    returned database holds SCHEMA ONLY — queries must run on the
    compiled engine (engine="tpu"); parity uses the numpy references
    below."""
    rng = np.random.default_rng(seed)
    db = Database(name)
    db.schema.create_vertex_class("Person")
    db.schema.create_edge_class("knows")

    V = int(n_persons)
    degrees = rng.poisson(avg_knows, V).astype(np.int64)
    if supernodes > 0:
        # celebrity vertices: a few sources with 10^4-10^5 out-degree —
        # scattered through the id space so they land in different
        # expansion chunks
        hubs = np.linspace(0, V - 1, supernodes, dtype=np.int64)
        degrees[hubs] = supernode_degree
    E = int(degrees.sum())
    dst = rng.integers(0, V, E, dtype=np.int64)
    csr = _csr_from_degrees("knows", degrees, dst)

    snap = GraphSnapshot()
    snap.num_vertices = V
    person_cluster = db.schema.get_class("Person").cluster_ids[0]
    snap.v_cluster = np.full(V, person_cluster, np.int32)
    snap.v_position = np.arange(V, dtype=np.int32)
    snap.rid_to_idx = {}  # no host records: index seeds are N/A

    all_classes = sorted(db.schema.classes(), key=lambda c: c.name)
    snap.class_names = [c.name for c in all_classes]
    snap.class_id_of = {c.name.lower(): i for i, c in enumerate(all_classes)}
    snap.v_class = np.full(V, snap.class_id_of["person"], np.int32)
    for c in all_classes:
        closure = [
            snap.class_id_of[s.name.lower()]
            for s in c.subclasses(include_self=True)
        ]
        snap.class_closure[c.name.lower()] = np.array(sorted(closure), np.int32)
    for c in all_classes:
        if c.is_vertex_type and not c.abstract:
            snap.class_vertex_range[c.name.lower()] = (
                (0, V) if c.name == "Person" else (0, 0)
            )

    ones = np.ones(V, bool)
    snap.v_columns = {
        "uid": PropertyColumn(
            "uid", "int", np.arange(V, dtype=np.int32), ones
        ),
        "age": PropertyColumn(
            "age", "int", rng.integers(18, 80, V, dtype=np.int32), ones
        ),
    }
    snap.edge_classes["knows"] = csr
    for c in all_classes:
        if c.is_edge_type:
            snap.edge_closure[c.name.lower()] = sorted(
                s.name
                for s in c.subclasses(include_self=True)
                if s.name in snap.edge_classes
            )
    snap.epoch = db.mutation_epoch
    db.attach_snapshot(snap)
    return db, snap


def build_snb_shape(
    n_persons: int,
    msgs_per_person: int = 2,
    avg_knows: int = 10,
    seed: int = 0,
    name: str = "snbshape",
) -> Tuple[Database, GraphSnapshot]:
    """The LDBC SNB *interactive* shape at array scale — BASELINE
    config 5's actual workload ingredients (SURVEY.md §6 row 5, §7 step
    7; VERDICT r4 #2):

    - **Person**–knows–Person with a ``creationDate`` EDGE property
      column (the fused edge-property WHERE the north star names,
      SURVEY.md:52-54),
    - **Message**–hasCreator–Person (multi-class: messages share the
      vertex index space after persons),
    - per-class property columns with honest presence masks (``age``
      on persons only, ``length`` on messages only) — the property
      breadth the per-query column pruning is judged on.

    Parity for the benched COUNT shapes comes from
    `numpy_config5_count` (exact int64, same arrays)."""
    rng = np.random.default_rng(seed)
    db = Database(name)
    db.schema.create_vertex_class("Person")
    db.schema.create_vertex_class("Message")
    db.schema.create_edge_class("knows")
    db.schema.create_edge_class("hasCreator")

    P = int(n_persons)
    M = P * int(msgs_per_person)
    V = P + M  # persons [0, P), messages [P, V)

    # ---- knows: Person -> Person, creationDate edge column ----
    deg = np.zeros(V, np.int64)
    deg[:P] = rng.poisson(avg_knows, P)
    E = int(deg.sum())
    dst = rng.integers(0, P, E, dtype=np.int64)  # always a Person
    knows = _csr_from_degrees("knows", deg, dst)
    e_ones = np.ones(E, bool)
    knows.edge_columns = {
        # SNB knows.creationDate: days-since-epoch ints — the fused
        # edge-predicate column (indexed by edge id = out-CSR order)
        "creationDate": PropertyColumn(
            "creationDate",
            "int",
            rng.integers(10_000, 20_000, E, dtype=np.int32),
            e_ones,
        ),
    }

    # ---- hasCreator: Message -> Person (exactly one per message) ----
    hc_deg = np.zeros(V, np.int64)
    hc_deg[P:] = 1
    creators = rng.integers(0, P, M, dtype=np.int64)
    hc = _csr_from_degrees("hasCreator", hc_deg, creators)

    # ---- snapshot assembly ----
    snap = GraphSnapshot()
    snap.num_vertices = V
    pc = db.schema.get_class("Person").cluster_ids[0]
    mc = db.schema.get_class("Message").cluster_ids[0]
    snap.v_cluster = np.concatenate(
        [np.full(P, pc, np.int32), np.full(M, mc, np.int32)]
    )
    snap.v_position = np.concatenate(
        [np.arange(P, dtype=np.int32), np.arange(M, dtype=np.int32)]
    )
    snap.rid_to_idx = {}

    all_classes = sorted(db.schema.classes(), key=lambda c: c.name)
    snap.class_names = [c.name for c in all_classes]
    snap.class_id_of = {c.name.lower(): i for i, c in enumerate(all_classes)}
    snap.v_class = np.concatenate(
        [
            np.full(P, snap.class_id_of["person"], np.int32),
            np.full(M, snap.class_id_of["message"], np.int32),
        ]
    )
    for c in all_classes:
        closure = [
            snap.class_id_of[s.name.lower()]
            for s in c.subclasses(include_self=True)
        ]
        snap.class_closure[c.name.lower()] = np.array(sorted(closure), np.int32)
    ranges = {"person": (0, P), "message": (P, V)}
    for c in all_classes:
        if c.is_vertex_type and not c.abstract:
            snap.class_vertex_range[c.name.lower()] = ranges.get(
                c.name.lower(), (0, 0)
            )

    person_pres = np.zeros(V, bool)
    person_pres[:P] = True
    msg_pres = ~person_pres
    age = np.zeros(V, np.int32)
    age[:P] = rng.integers(18, 80, P, dtype=np.int32)
    length = np.zeros(V, np.int32)
    length[P:] = rng.integers(1, 2000, M, dtype=np.int32)
    snap.v_columns = {
        "uid": PropertyColumn(
            "uid", "int", np.arange(V, dtype=np.int32), np.ones(V, bool)
        ),
        "age": PropertyColumn("age", "int", age, person_pres),
        "length": PropertyColumn("length", "int", length, msg_pres),
    }
    snap.edge_classes["knows"] = knows
    snap.edge_classes["hasCreator"] = hc
    for c in all_classes:
        if c.is_edge_type:
            snap.edge_closure[c.name.lower()] = sorted(
                s.name
                for s in c.subclasses(include_self=True)
                if s.name in snap.edge_classes
            )
    snap.epoch = db.mutation_epoch
    db.attach_snapshot(snap)
    return db, snap


# ---------------------------------------------------------------------------
# exact numpy references for the benched COUNT shapes (the parity oracle
# at array level — int64 throughout, no device involved)
# ---------------------------------------------------------------------------


def _seg_sum(vals: np.ndarray, indptr: np.ndarray) -> np.ndarray:
    tot = np.concatenate([[0], np.cumsum(vals, dtype=np.int64)])
    return tot[indptr[1:].astype(np.int64)] - tot[indptr[:-1].astype(np.int64)]


def numpy_1hop_count(snap: GraphSnapshot, src_mask, dst_mask) -> int:
    """count of (p, f) pairs with src_mask[p] and dst_mask[f]."""
    csr = snap.edge_classes["knows"]
    w1 = _seg_sum(dst_mask[csr.dst].astype(np.int64), csr.indptr_out)
    return int((w1 * src_mask.astype(np.int64)).sum())


def numpy_2hop_count(snap: GraphSnapshot, src_mask, mid_mask, dst_mask) -> int:
    """count of (p, f, g) paths with the three masks applied."""
    csr = snap.edge_classes["knows"]
    w2 = _seg_sum(dst_mask[csr.dst].astype(np.int64), csr.indptr_out)
    w1 = _seg_sum((mid_mask[csr.dst] * w2[csr.dst]).astype(np.int64), csr.indptr_out)
    return int((w1 * src_mask.astype(np.int64)).sum())


def numpy_config5_count(snap: GraphSnapshot, d_cut: int) -> int:
    """Exact reference for the config-5 multi-pattern MATCH:

        MATCH {class:Person, as:p, where:(age > 40)}
              .outE('knows'){where:(creationDate > d_cut)}
              .inV(){as:f, where:(age < 30)},
              {class:Message, as:m}-hasCreator->{as:f}
        RETURN count(*)

    = Σ over knows edges (p→f) passing the vertex+edge predicates of
    the number of messages whose creator is f."""
    knows = snap.edge_classes["knows"]
    hc = snap.edge_classes["hasCreator"]
    age_col = snap.v_columns["age"]
    age, pres = age_col.values, age_col.present
    cdate = knows.edge_columns["creationDate"].values
    msg_cnt = np.diff(hc.indptr_in).astype(np.int64)  # messages per person
    dst = knows.dst
    w = (
        (age[dst] < 30)
        & pres[dst]
        & (cdate > d_cut)
    ).astype(np.int64) * msg_cnt[dst]
    per_src = _seg_sum(w, knows.indptr_out)
    src_mask = ((age > 40) & pres).astype(np.int64)
    return int((per_src * src_mask).sum())
