"""Immutable columnar graph snapshots — the TPU-side data layout.

This is the ingest/snapshot layer of the TPU-native design (SURVEY.md §1
"TPU-native restatement" and §7 step 2): the host record store's vertices
and edges are exported into dense columnar arrays that `jax.device_put`
moves into TPU HBM:

- a **dense vertex universe**: every vertex gets an int32 index (the RID →
  dense-index remap table of [E] ODatabaseImport's RID remapping,
  SURVEY.md §3.5); RIDs are recoverable per index for result marshalling;
- **per-edge-class CSR adjacency**, both directions (out CSR and in CSR),
  with an edge-id array aligned to CSR order so edge property columns can
  be gathered alongside neighbor gathers — this is the HBM form of the
  reference's per-vertex ORidBag adjacency ([E] ORidBag / sbtree bonsai,
  SURVEY.md §2 "RidBag"), flattened for batched frontier expansion;
- **global vertex property columns** keyed by property name (int32 /
  float32 / bool with presence masks; strings dictionary-encoded with a
  *sorted* dictionary so code order == lexicographic order, letting <,>,=
  run as int32 compares on device);
- **per-edge-class edge property columns** in CSR-out edge order;
- a **class-id column** + subclass closure table so `class:X` polymorphic
  filters compile to `isin(class_id, …)` masks.

Snapshots are immutable by default; `Database.mutation_epoch` tracks
staleness and `build_snapshot` is re-run to refresh (the snapshot-epoch
model of SURVEY.md §5.4 — no WAL needed on the read-only TPU path).
Delta-maintained snapshots (`storage/deltas.py`) relax this: writes
apply device-side into pre-allocated append slabs off the CDC feed, and
periodic epoch compaction folds them back into a clean CSR.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from orientdb_tpu.models.database import Database
from orientdb_tpu.models.record import Document, Edge, Vertex
from orientdb_tpu.models.rid import RID
from orientdb_tpu.utils.logging import get_logger

log = get_logger("snapshot")

#: sentinel for "property missing" in numeric columns (presence tracked in
#: the mask; the sentinel only keeps padded math well-defined)
MISSING_INT = np.int32(-(2**31) + 1)
MISSING_FLOAT = np.float32(np.nan)


class PropertyColumn:
    """One global vertex (or per-class edge) property column."""

    __slots__ = (
        "name",
        "kind",
        "values",
        "present",
        "dictionary",
        "dict_lookup",
        "dict_unsorted",
        "_dict_arr",
    )

    def __init__(self, name: str, kind: str, values, present, dictionary=None):
        self.name = name
        self.kind = kind  # 'int' | 'float' | 'bool' | 'str'
        self.values = values  # np.ndarray
        self.present = present  # np.ndarray bool
        self.dictionary: Optional[List[str]] = dictionary  # for 'str'
        self.dict_lookup: Optional[Dict[str, int]] = (
            {s: i for i, s in enumerate(dictionary)} if dictionary else None
        )
        #: True once the delta maintainer APPENDED a new string (codes
        #: no longer sorted): equality predicates stay exact, ordered
        #: compares refuse to compile until compaction re-sorts
        self.dict_unsorted = False
        self._dict_arr = None

    def dict_array(self) -> np.ndarray:
        """The dictionary as an object ndarray, built once: row
        marshalling decodes string codes per QUERY, and re-converting a
        10^4-entry Python list each time dominated IS1-style host time
        at sf10 scale."""
        from orientdb_tpu.utils.metrics import metrics

        a = self._dict_arr
        if a is None:
            metrics.incr("snapshot.dict_array.miss")
            a = self._dict_arr = np.asarray(
                self.dictionary if self.dictionary else [""], object
            )
        else:
            metrics.incr("snapshot.dict_array.hit")
        return a

    def encode(self, value) -> Optional[np.int32]:
        """Host-side scalar → column code/value for predicate compilation."""
        if self.kind == "str":
            if not isinstance(value, str) or self.dict_lookup is None:
                return None
            code = self.dict_lookup.get(value)
            return np.int32(code) if code is not None else None
        if self.kind == "int":
            return np.int32(value)
        if self.kind == "float":
            return np.float32(value)
        if self.kind == "bool":
            return np.int32(bool(value))
        return None

    def decode(self, raw, present: bool):
        if not present:
            return None
        if self.kind == "str":
            assert self.dictionary is not None
            return self.dictionary[int(raw)]
        if self.kind == "int":
            return int(raw)
        if self.kind == "float":
            return float(raw)
        if self.kind == "bool":
            return bool(raw)
        return None


class EdgeClassCSR:
    """CSR adjacency for one concrete edge class, both directions.

    out:  indptr_out[V+1], dst[E]      (CSR order == edge dense order)
    in:   indptr_in[V+1], src[E], edge_id_in[E] (edge ids into out order)
    """

    __slots__ = (
        "class_name",
        "indptr_out",
        "dst",
        "indptr_in",
        "src",
        "edge_id_in",
        "edge_rids",
        "edge_columns",
        "non_columnar",
        "out_degree_max",
        "in_degree_max",
        "live",
        "_edge_src",
    )

    def __init__(self, class_name: str):
        self.class_name = class_name
        self.indptr_out: np.ndarray = np.zeros(1, np.int32)
        self.dst: np.ndarray = np.zeros(0, np.int32)
        self.indptr_in: np.ndarray = np.zeros(1, np.int32)
        self.src: np.ndarray = np.zeros(0, np.int32)
        self.edge_id_in: np.ndarray = np.zeros(0, np.int32)
        self.edge_rids: List[RID] = []
        self.edge_columns: Dict[str, PropertyColumn] = {}
        self.non_columnar: set = set()
        self.out_degree_max = 0
        self.in_degree_max = 0
        #: [Ecap] bool liveness when the snapshot carries delta slabs
        #: (storage/deltas.pad_for_deltas); None on classic snapshots
        self.live: Optional[np.ndarray] = None

    @property
    def num_edges(self) -> int:
        return int(self.dst.shape[0])

    def edge_src_np(self) -> np.ndarray:
        """Per-edge source vertex in out-CSR order (cached; shared by the
        device edge-list form and the mesh-sharded slices)."""
        cached = getattr(self, "_edge_src", None)
        if cached is None:
            cached = self._edge_src = np.repeat(
                np.arange(self.indptr_out.shape[0] - 1, dtype=np.int32),
                np.diff(self.indptr_out),
            )
        return cached


class GraphSnapshot:
    """The immutable columnar snapshot (host numpy form; `device()` yields
    the jnp pytree the compiled engine consumes)."""

    def __init__(self) -> None:
        self.epoch: int = -1
        self.num_vertices: int = 0
        # dense index → RID (parallel int32 arrays), and the reverse map
        self.v_cluster: np.ndarray = np.zeros(0, np.int32)
        self.v_position: np.ndarray = np.zeros(0, np.int32)
        self.rid_to_idx: Dict[RID, int] = {}
        # class metadata
        self.class_names: List[str] = []  # class_id → name
        self.class_id_of: Dict[str, int] = {}
        self.v_class: np.ndarray = np.zeros(0, np.int32)
        #: class name (lower) → sorted np.int32 array of class ids in its
        #: polymorphic closure (vertex classes)
        self.class_closure: Dict[str, np.ndarray] = {}
        #: CONCRETE vertex class (lower) → (start, end) contiguous dense-
        #: index range — vertices sort by (cluster, position) and a class's
        #: clusters are consecutively allocated, so each concrete class is
        #: one contiguous slab; root scans restrict to it
        self.class_vertex_range: Dict[str, tuple] = {}
        # property columns (global over the vertex universe)
        self.v_columns: Dict[str, PropertyColumn] = {}
        #: property names observed but not columnar-encodable (lists, links,
        #: mixed types) — device predicates on these must fall back
        self.v_non_columnar: set = set()
        # per-edge-class CSR (concrete classes)
        self.edge_classes: Dict[str, EdgeClassCSR] = {}
        #: edge class name (lower) → list of concrete edge class names
        self.edge_closure: Dict[str, List[str]] = {}
        self._device_cache = None
        #: optional jax.sharding.Mesh — set via attach, consumed by
        #: DeviceGraph to lay adjacency out shard-wise (parallel/mesh_graph)
        self._mesh = None
        #: delta-slab overlay (storage/deltas.SnapshotOverlay) when the
        #: snapshot is maintained incrementally; None = classic immutable
        self._overlay = None
        #: in-flight dispatch refcount: release_device defers the buffer
        #: free until the last dispatch admitted on this snapshot drains
        #: (epoch-gated dispatch — a compaction swap must never
        #: use-after-free a buffer an executable still reads)
        self._rc_lock = threading.Lock()
        self._inflight = 0
        self._release_pending = False

    def retain(self) -> "GraphSnapshot":
        """Pin the device buffers for an in-flight dispatch."""
        with self._rc_lock:
            self._inflight += 1
        from orientdb_tpu.obs.memledger import memledger

        memledger.lease_acquired(self)
        return self

    def try_retain(self, dg) -> bool:
        """Pin for a dispatch of a plan built against DeviceGraph
        ``dg``, refusing when ``dg`` is no longer this snapshot's
        canonical device cache — a compaction swap freed its buffers
        between plan resolution and the pin (retain() alone cannot
        tell: it would pin a corpse and the dispatch would read deleted
        arrays). Once this succeeds, inflight > 0 keeps the buffers
        alive until the matching release()."""
        with self._rc_lock:
            if self._device_cache is not dg:
                return False
            self._inflight += 1
        from orientdb_tpu.obs.memledger import memledger

        memledger.lease_acquired(self)
        return True

    def release(self) -> None:
        """Drop one dispatch pin; performs a deferred buffer free when
        this was the last in-flight dispatch after a release_device."""
        with self._rc_lock:
            self._inflight = max(0, self._inflight - 1)
            run_free = self._release_pending and self._inflight == 0
            if run_free:
                self._release_pending = False
        from orientdb_tpu.obs.memledger import memledger

        memledger.lease_released(self)
        if run_free:
            self._free_device()

    def release_device(self) -> None:
        """Free every HBM buffer this snapshot pinned: device arrays are
        deleted eagerly (not just dereferenced — compiled plans and
        stray references would otherwise keep them alive until GC), and
        the plan cache goes with them (its executables captured the
        arrays). The host-side snapshot survives; the next device use
        re-uploads. Multi-graph workloads (the bench's block sequence)
        need this — 16 GB of HBM cannot hold every graph at once.

        With in-flight dispatches retained on this snapshot the free is
        DEFERRED to the last ``release()`` — dispatches admitted on
        epoch N complete on epoch N's buffers."""
        self._free_device()

    def _free_device(self) -> None:
        # decide AND detach in one lock acquisition: a try_retain landing
        # between a caller's inflight check and this free would otherwise
        # pin buffers we are about to delete (the pinned dispatch's final
        # release() re-enters here once the deferral flag is set)
        with self._rc_lock:
            if self._inflight > 0:
                self._release_pending = True
                return
            dg = self._device_cache
            self._device_cache = None
        if dg is not None:
            # mutate the CANONICAL store: `dg.arrays = {}` would only
            # install a thread-local override (the jit-trace swap
            # mechanism) and leave every deleted buffer referenced
            for a in list(dg._arrays.values()):
                try:
                    a.delete()
                except Exception:  # pragma: no cover - already deleted
                    pass
            dg._arrays.clear()
            dg._pending.clear()
            from orientdb_tpu.obs.memledger import memledger

            memledger.drop_graph(dg)
        tier = getattr(self, "_tier", None)
        if tier is not None:
            # retract the tier gauges with the buffers: a stale
            # tier.cap_bytes from a freed plane must not keep feeding
            # alert rules for the rest of the process
            tier.unpublish()
        cache = getattr(self, "_plan_cache", None)
        if cache is not None:
            cache.clear()

    # -- lookups -----------------------------------------------------------

    def vertex_hull(self, name: str) -> tuple:
        """(start, end) dense-index hull of a class's polymorphic closure.
        The hull may include foreign-class vertices (subclass slabs are
        not necessarily adjacent), so callers keep their class masks.

        On delta-maintained snapshots (``_overlay``) inserted vertices
        land in the append slab OUTSIDE every base hull — root scans add
        :meth:`slab_vertex_range` as a second segment."""
        lo, hi = None, None
        for cid in self.class_closure.get(name.lower(), ()):
            rng = self.class_vertex_range.get(self.class_names[cid].lower())
            if rng is None or rng[1] <= rng[0]:
                continue
            lo = rng[0] if lo is None else min(lo, rng[0])
            hi = rng[1] if hi is None else max(hi, rng[1])
        if lo is None:
            return (0, 0)
        return (lo, hi)

    def slab_vertex_range(self) -> tuple:
        """(start, end) of the vertex append slab — ``(0, 0)`` on
        classic snapshots. Root scans on armed snapshots cover it in
        addition to the class hull (class masks stay exact, so the cost
        is bounded by ``delta_slab_vertex_rows`` extra scan slots)."""
        ov = self._overlay
        if ov is None:
            return (0, 0)
        return (ov.base_vertices, ov.cap_vertices)

    def rid_of(self, idx: int) -> RID:
        return RID(int(self.v_cluster[idx]), int(self.v_position[idx]))

    def idx_of(self, rid: RID) -> Optional[int]:
        return self.rid_to_idx.get(rid)

    def vertex_class_ids(self, class_name: str) -> np.ndarray:
        return self.class_closure.get(class_name.lower(), np.zeros(0, np.int32))

    def concrete_edge_classes(self, class_name: Optional[str]) -> List[str]:
        if class_name is None:
            out: List[str] = []
            for names in self.edge_closure.values():
                for n in names:
                    if n not in out:
                        out.append(n)
            return sorted(out)
        return self.edge_closure.get(class_name.lower(), [])

    def class_mask(self, class_name: str) -> np.ndarray:
        """Boolean mask over the vertex universe for a polymorphic class."""
        ids = self.vertex_class_ids(class_name)
        return np.isin(self.v_class, ids)

    def vertex_value(self, idx: int, prop: str):
        col = self.v_columns.get(prop)
        if col is None:
            return None
        return col.decode(col.values[idx], bool(col.present[idx]))

    # -- stats -------------------------------------------------------------

    def summary(self) -> Dict[str, object]:
        return {
            "vertices": self.num_vertices,
            "edge_classes": {
                n: c.num_edges for n, c in sorted(self.edge_classes.items())
            },
            "columns": sorted(self.v_columns.keys()),
            "epoch": self.epoch,
        }


# ---------------------------------------------------------------------------
# build
# ---------------------------------------------------------------------------


def _column_from_values(name: str, raw: List, present: np.ndarray) -> Optional[PropertyColumn]:
    """Choose a columnar encoding for a property from its observed values."""
    kinds = set()
    for v, p in zip(raw, present):
        if not p or v is None:
            continue
        if isinstance(v, bool):
            kinds.add("bool")
        elif isinstance(v, int):
            kinds.add("int")
        elif isinstance(v, float):
            kinds.add("float")
        elif isinstance(v, str):
            kinds.add("str")
        else:
            return None  # lists/maps/links: not columnar; host fallback
    if not kinds:
        return None
    if kinds <= {"bool"}:
        kind = "bool"
    elif kinds <= {"int", "bool"}:
        kind = "int"
    elif kinds <= {"int", "float", "bool"}:
        kind = "float"
    elif kinds == {"str"}:
        kind = "str"
    else:
        return None  # mixed string/number: host fallback
    n = len(raw)
    if kind == "str":
        # sorted dictionary => int32 code comparisons preserve lex order
        uniq = sorted({v for v, p in zip(raw, present) if p and v is not None})
        lookup = {s: i for i, s in enumerate(uniq)}
        vals = np.full(n, MISSING_INT, np.int32)
        for i, (v, p) in enumerate(zip(raw, present)):
            if p and v is not None:
                vals[i] = lookup[v]
        return PropertyColumn(name, "str", vals, present, uniq)
    if kind == "float":
        vals = np.full(n, MISSING_FLOAT, np.float32)
        for i, (v, p) in enumerate(zip(raw, present)):
            if p and v is not None:
                vals[i] = float(v)
        return PropertyColumn(name, "float", vals, present)
    # int / bool
    vals = np.full(n, MISSING_INT, np.int32)
    for i, (v, p) in enumerate(zip(raw, present)):
        if p and v is not None:
            iv = int(v)
            if not (-(2**31) + 2 <= iv < 2**31):
                # out-of-range int: promote the whole column to float
                return _column_from_values(
                    name, [float(x) if x is not None else None for x in raw], present
                )
            vals[i] = iv
    return PropertyColumn(name, kind, vals, present)


def _build_columns(docs: Sequence[Document]) -> Tuple[Dict[str, PropertyColumn], set]:
    n = len(docs)
    names: List[str] = []
    seen = set()
    for d in docs:
        for f in d.field_names():
            if f not in seen:
                seen.add(f)
                names.append(f)
    out: Dict[str, PropertyColumn] = {}
    dropped: set = set()
    for name in names:
        raw = [d.get(name) for d in docs]
        present = np.array([d.has(name) and d.get(name) is not None for d in docs])
        col = _column_from_values(name, raw, present)
        if col is not None:
            out[name] = col
        else:
            dropped.add(name)
            log.info("property %r not columnar; TPU predicates fall back", name)
    return out, dropped


def build_snapshot(db: Database) -> GraphSnapshot:
    """Export the host store into a columnar snapshot (the bulk-load step of
    the north star: plocal clusters → CSR in HBM)."""
    snap = GraphSnapshot()
    snap.epoch = db.mutation_epoch

    # ---- vertex universe (deterministic RID order) ----
    vertex_classes = [
        c for c in db.schema.classes() if c.is_vertex_type and not c.abstract
    ]
    vertices: List[Vertex] = []
    for cls in sorted(vertex_classes, key=lambda c: c.name):
        for doc in db.browse_class(cls.name, polymorphic=False):
            if isinstance(doc, Vertex):
                vertices.append(doc)
    vertices.sort(key=lambda v: (v.rid.cluster, v.rid.position))
    V = len(vertices)
    snap.num_vertices = V
    snap.v_cluster = np.array([v.rid.cluster for v in vertices], np.int32)
    snap.v_position = np.array([v.rid.position for v in vertices], np.int32)
    snap.rid_to_idx = {v.rid: i for i, v in enumerate(vertices)}

    # ---- classes ----
    all_classes = sorted(db.schema.classes(), key=lambda c: c.name)
    snap.class_names = [c.name for c in all_classes]
    snap.class_id_of = {c.name.lower(): i for i, c in enumerate(all_classes)}
    snap.v_class = np.array(
        [snap.class_id_of[v.class_name.lower()] for v in vertices], np.int32
    )
    for c in all_classes:
        closure = [
            snap.class_id_of[s.name.lower()] for s in c.subclasses(include_self=True)
        ]
        snap.class_closure[c.name.lower()] = np.array(sorted(closure), np.int32)
    for cls in vertex_classes:
        if not cls.cluster_ids:
            snap.class_vertex_range[cls.name.lower()] = (0, 0)
            continue
        lo = int(np.searchsorted(snap.v_cluster, min(cls.cluster_ids), "left"))
        hi = int(np.searchsorted(snap.v_cluster, max(cls.cluster_ids), "right"))
        snap.class_vertex_range[cls.name.lower()] = (lo, hi)


    # ---- vertex property columns ----
    snap.v_columns, snap.v_non_columnar = _build_columns(vertices)

    # ---- edges per concrete edge class ----
    edge_classes = [c for c in db.schema.classes() if c.is_edge_type and not c.abstract]
    for cls in sorted(edge_classes, key=lambda c: c.name):
        edges: List[Edge] = [
            e
            for e in db.browse_class(cls.name, polymorphic=False)
            if isinstance(e, Edge)
        ]
        # drop dangling edges defensively (cascade delete should prevent them)
        edges = [
            e
            for e in edges
            if e.out_rid in snap.rid_to_idx and e.in_rid in snap.rid_to_idx
        ]
        csr = EdgeClassCSR(cls.name)
        E = len(edges)
        src = np.array([snap.rid_to_idx[e.out_rid] for e in edges], np.int64)
        dst = np.array([snap.rid_to_idx[e.in_rid] for e in edges], np.int64)
        # CSR out: stable sort by src keeps per-vertex bag order (parity with
        # the host store's RidBag iteration order)
        order = np.argsort(src, kind="stable")
        csr.dst = dst[order].astype(np.int32)
        counts = np.bincount(src, minlength=V).astype(np.int64)
        csr.indptr_out = np.concatenate([[0], np.cumsum(counts)]).astype(np.int32)
        csr.out_degree_max = int(counts.max()) if V else 0
        ordered_edges = [edges[i] for i in order]
        csr.edge_rids = [e.rid for e in ordered_edges]
        csr.edge_columns, csr.non_columnar = _build_columns(ordered_edges)
        # CSR in: sort (dst, position) — edge ids refer to out order
        src_o = src[order]
        dst_o = dst[order]
        order_in = np.argsort(dst_o, kind="stable")
        csr.src = src_o[order_in].astype(np.int32)
        csr.edge_id_in = order_in.astype(np.int32)
        counts_in = np.bincount(dst_o, minlength=V).astype(np.int64)
        csr.indptr_in = np.concatenate([[0], np.cumsum(counts_in)]).astype(np.int32)
        csr.in_degree_max = int(counts_in.max()) if V else 0
        snap.edge_classes[cls.name] = csr
        del E
    # polymorphic edge closure
    for c in sorted(db.schema.classes(), key=lambda c: c.name):
        if not c.is_edge_type:
            continue
        concrete = [
            s.name
            for s in c.subclasses(include_self=True)
            if s.name in snap.edge_classes
        ]
        snap.edge_closure[c.name.lower()] = sorted(concrete)

    log.info("built snapshot: %s", snap.summary())
    return snap


def attach_fresh_snapshot(db: Database, mesh=None) -> GraphSnapshot:
    """Build + attach in one step (convenience for the query front door).

    With ``mesh``, adjacency is additionally laid out shard-wise over the
    mesh's ``shards`` axis and the compiled engine executes every
    expansion under shard_map (`orientdb_tpu/parallel/mesh_graph.py`)."""
    snap = build_snapshot(db)
    db.attach_snapshot(snap, mesh=mesh)
    return snap
