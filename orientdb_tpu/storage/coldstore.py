"""Cold-data capacity tier: disk-resident records with an in-RAM hot set.

The reference's biggest module is a paginated disk store with a page
cache — records live on disk, hot pages in RAM ([E] plocal
``OLocalPaginatedStorage`` + ``O2QCache``; SURVEY.md §2 rows "plocal
storage"/"Page cache", ~75k LoC). This engine's host store is
RAM-resident, so a database larger than host memory could not exist.
This module closes that capability gap the logical way this engine
stores things: records spill to an append-only SEGMENT FILE in their
checkpoint JSON form (storage/durability._rec_json — the format
recovery, deltas, and backups already speak), an offset index maps
RID → (segment offset, length), and an LRU hot set of materialized
Documents is bounded by a byte budget.

Mechanics:
- **save-through**: every committed save appends the record's current
  state to the spill segment and admits the document to the hot set;
  eviction therefore never loses acknowledged state (unsaved in-place
  mutations follow the store's existing contract: not durable until
  save()).
- **eviction**: over budget, the LRU document's cluster slot is
  replaced by a :class:`ColdRef` marker and the object is dropped.
- **fault-in**: `_Cluster.get` (the `load`/`_load_raw` path) rebuilds
  the Document from the spill and re-admits it hot; class scans
  (`browse_class`) materialize markers TRANSIENTLY without touching
  the hot set, so an analytic full scan cannot thrash the cache —
  the 2Q-style scan resistance of the reference's page cache.
- **checkpoints/backups**: `_rec_json` serializes a ColdRef by reading
  its spilled bytes directly (no fault-in), so full checkpoints of a
  mostly-cold database stay O(hot) in memory.

Compaction of the spill segment (dead versions accumulate as records
are rewritten) is deliberately out of scope for v1 — the file is
truncated on the next full checkpoint + reopen cycle."""

from __future__ import annotations

import json
import os
import threading
from collections import OrderedDict
from typing import Dict, Optional, Tuple

from orientdb_tpu.models.database import Database
from orientdb_tpu.models.record import Blob, Direction, Document, Edge, Vertex
from orientdb_tpu.models.rid import RID
from orientdb_tpu.storage.durability import _dec, _rec_json
from orientdb_tpu.utils.logging import get_logger
from orientdb_tpu.utils.metrics import metrics

log = get_logger("coldstore")


class ColdRef:
    """Cluster-slot marker for an evicted record. Duck-typed by
    ``durability._rec_json`` via :meth:`rec_json`."""

    __slots__ = ("rid", "tier")

    def __init__(self, rid: RID, tier: "ColdTier") -> None:
        self.rid = rid
        self.tier = tier

    def rec_json(self, pos: int) -> Dict:
        r = self.tier.raw(self.rid)
        r["pos"] = pos
        return r

    def __repr__(self) -> str:
        return f"ColdRef({self.rid})"


class ColdTier:
    """The spill segment + offset index + LRU hot set for one database."""

    def __init__(
        self, db: Database, directory: str, budget_bytes: int
    ) -> None:
        os.makedirs(directory, exist_ok=True)
        self.db = db
        self.path = os.path.join(directory, "cold-segment.jsonl")
        self._f = open(self.path, "a+b")
        self.budget = int(budget_bytes)
        self._index: Dict[RID, Tuple[int, int]] = {}
        #: rid → (doc, approx bytes); insertion order = LRU order
        self._hot: "OrderedDict[RID, Tuple[Document, int]]" = OrderedDict()
        self._hot_bytes = 0
        self._lock = threading.RLock()

    # -- spill segment ------------------------------------------------------

    def _append(self, rid: RID, rec: Dict) -> int:
        data = json.dumps(rec, separators=(",", ":")).encode() + b"\n"
        with self._lock:
            self._f.seek(0, os.SEEK_END)
            off = self._f.tell()
            self._f.write(data)
            self._f.flush()
            self._index[rid] = (off, len(data) - 1)
        return len(data)

    def raw(self, rid: RID) -> Dict:
        with self._lock:
            off, ln = self._index[rid]
            self._f.seek(off)
            return json.loads(self._f.read(ln))

    # -- hot set ------------------------------------------------------------

    def on_save(self, doc: Document) -> None:
        """Save-through: spill the committed state, keep the doc hot."""
        nbytes = self._append(doc.rid, _rec_json(doc, doc.rid.position))
        self._admit(doc, nbytes)

    def on_delete(self, doc: Document) -> None:
        with self._lock:
            # the index entry is KEPT (the segment is append-only, the
            # offset stays valid): a checkpoint/backup capture holding a
            # pointer-copied ColdRef of this record may still serialize
            # it after the delete — the delete's WAL entry (higher LSN)
            # removes it at replay, exactly like a torn live capture.
            entry = self._hot.pop(doc.rid, None)
            if entry is not None:
                self._hot_bytes -= entry[1]

    def _admit(self, doc: Document, nbytes: int) -> None:
        with self._lock:
            old = self._hot.pop(doc.rid, None)
            if old is not None:
                self._hot_bytes -= old[1]
            self._hot[doc.rid] = (doc, nbytes)
            self._hot_bytes += nbytes
            while self._hot_bytes > self.budget and len(self._hot) > 1:
                rid, (victim, vb) = self._hot.popitem(last=False)
                self._hot_bytes -= vb
                c = self.db._clusters.get(rid.cluster)
                if c is not None and c.get_slot(rid.position) is victim:
                    c.records[rid.position] = ColdRef(rid, self)
                    metrics.incr("coldstore.evict")

    # -- fault-in -----------------------------------------------------------

    def _build(self, rid: RID, r: Dict) -> Document:
        fields = {k: _dec(v) for k, v in r["fields"].items()}
        typ = r["type"]
        if typ == "vertex":
            doc: Document = Vertex(r["class"], fields)
            for dname, table in r.get("bags", {}).items():
                target = (
                    doc._out_edges if dname == "out" else doc._in_edges
                )
                for cls_name, rids in table.items():
                    target[cls_name] = [RID.parse(x) for x in rids]
        elif typ == "edge":
            doc = Edge(r["class"], fields)
            doc.out_rid = RID.parse(r["out"])
            doc.in_rid = RID.parse(r["in"])
        elif typ == "blob":
            doc = Blob.from_fields(fields)
        else:
            doc = Document(r["class"], fields)
        doc._db = self.db
        doc.rid = rid
        doc.version = r["version"]
        return doc

    def materialize(self, ref: ColdRef) -> Document:
        """Transient rebuild (scans): does NOT enter the hot set."""
        metrics.incr("coldstore.fault_transient")
        return self._build(ref.rid, self.raw(ref.rid))

    def fault(self, ref: ColdRef) -> Optional[Document]:
        """Point-read rebuild: re-admitted hot and placed in the slot.
        Returns None when the record was deleted since the marker was
        observed (the reader's race, same answer a pre-delete tombstone
        read would give)."""
        with self._lock:
            rid = ref.rid
            entry = self._index.get(rid)
            if entry is None:
                return None
            off, ln = entry
            doc = self._build(rid, self.raw(rid))
            c = self.db._clusters.get(rid.cluster)
            if c is not None and isinstance(
                c.get_slot(rid.position), ColdRef
            ):
                c.records[rid.position] = doc
            metrics.incr("coldstore.fault")
            self._admit(doc, ln)
            return doc

    def stats(self) -> Dict:
        with self._lock:
            return {
                "hot_records": len(self._hot),
                "hot_bytes": self._hot_bytes,
                "spilled_records": len(self._index),
                "segment_bytes": os.path.getsize(self.path),
                "budget_bytes": self.budget,
            }

    def close(self) -> None:
        self._f.close()


def enable_cold_tier(
    db: Database, directory: str, budget_bytes: int = 64 << 20
) -> ColdTier:
    """Arm the capacity tier on ``db``: committed saves spill through,
    the hot set is bounded by ``budget_bytes``, and cold records fault
    back on access. Existing records are adopted (spilled) lazily on
    their next save."""
    tier = ColdTier(db, directory, budget_bytes)
    db._cold_tier = tier
    for c in db._clusters.values():
        c.cold = tier
    db._on_new_cluster = lambda c: setattr(c, "cold", tier)
    return tier
