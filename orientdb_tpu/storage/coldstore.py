"""Cold-data capacity tier: disk-resident records with an in-RAM hot set.

The reference's biggest module is a paginated disk store with a page
cache — records live on disk, hot pages in RAM ([E] plocal
``OLocalPaginatedStorage`` + ``O2QCache``; SURVEY.md §2 rows "plocal
storage"/"Page cache", ~75k LoC). This engine's host store is
RAM-resident, so a database larger than host memory could not exist.
This module closes that capability gap the logical way this engine
stores things: records spill to an append-only SEGMENT FILE in their
checkpoint JSON form (storage/durability._rec_json — the format
recovery, deltas, and backups already speak), an offset index maps
RID → (segment offset, length), and an LRU hot set of materialized
Documents is bounded by a byte budget.

Mechanics:
- **save-through**: every committed save appends the record's current
  state to the spill segment and admits the document to the hot set;
  eviction therefore never loses acknowledged state (unsaved in-place
  mutations follow the store's existing contract: not durable until
  save()).
- **eviction**: over budget, the LRU document's cluster slot is
  replaced by a :class:`ColdRef` marker and the object is dropped.
- **fault-in**: `_Cluster.get` (the `load`/`_load_raw` path) rebuilds
  the Document from the spill and re-admits it hot; class scans
  (`browse_class`) materialize markers TRANSIENTLY without touching
  the hot set, so an analytic full scan cannot thrash the cache —
  the 2Q-style scan resistance of the reference's page cache.
- **checkpoints/backups**: `_rec_json` serializes a ColdRef by reading
  its spilled bytes directly (no fault-in), so full checkpoints of a
  mostly-cold database stay O(hot) in memory.

Compaction of the spill segment (dead versions accumulate as records
are rewritten) is deliberately out of scope for v1 — the file is
truncated on the next full checkpoint + reopen cycle."""

from __future__ import annotations

import json
import os
import threading
from collections import OrderedDict
from typing import Dict, Iterator, Optional, Tuple

import numpy as np

from orientdb_tpu.models.database import Database
from orientdb_tpu.models.record import Blob, Direction, Document, Edge, Vertex
from orientdb_tpu.models.rid import RID
from orientdb_tpu.storage.durability import _dec, _rec_json
from orientdb_tpu.utils.logging import get_logger
from orientdb_tpu.utils.metrics import metrics

log = get_logger("coldstore")

META_FILE = "cold-meta.json"


class _ColdIndex:
    """RID → (segment offset, length, lsn) as per-cluster numpy arrays.

    Positions within a cluster are dense ints, so the index costs ~20
    bytes/record instead of the ~150 of a dict keyed by RID objects —
    the difference between 10^8 spilled records fitting in a few GB of
    index or not (VERDICT r4 weak #5)."""

    __slots__ = ("_off", "_ln", "_lsn", "_count")

    def __init__(self) -> None:
        self._off: Dict[int, np.ndarray] = {}  # cluster -> int64[pos]
        self._ln: Dict[int, np.ndarray] = {}
        self._lsn: Dict[int, np.ndarray] = {}
        self._count = 0

    def _grow(self, cid: int, pos: int) -> None:
        off = self._off.get(cid)
        if off is None:
            cap = max(1024, pos + 1)
            self._off[cid] = np.full(cap, -1, np.int64)
            self._ln[cid] = np.zeros(cap, np.int32)
            self._lsn[cid] = np.zeros(cap, np.int64)
            return
        if pos >= off.shape[0]:
            cap = max(off.shape[0] * 2, pos + 1)
            for name in ("_off", "_ln", "_lsn"):
                arrs = getattr(self, name)
                old = arrs[cid]
                fill = -1 if name == "_off" else 0
                a = np.full(cap, fill, old.dtype)
                a[: old.shape[0]] = old
                arrs[cid] = a

    def set(self, rid: RID, off: int, ln: int, lsn: int = 0) -> None:
        self._grow(rid.cluster, rid.position)
        if self._off[rid.cluster][rid.position] < 0:
            self._count += 1
        self._off[rid.cluster][rid.position] = off
        self._ln[rid.cluster][rid.position] = ln
        self._lsn[rid.cluster][rid.position] = lsn

    def remove(self, rid: RID, lsn: int = 0) -> None:
        off = self._off.get(rid.cluster)
        if off is not None and 0 <= rid.position < off.shape[0]:
            if off[rid.position] >= 0:
                self._count -= 1
            off[rid.position] = -1
            self._lsn[rid.cluster][rid.position] = lsn

    def get(self, rid: RID) -> Optional[Tuple[int, int]]:
        off = self._off.get(rid.cluster)
        if off is None or not 0 <= rid.position < off.shape[0]:
            return None
        o = int(off[rid.position])
        if o < 0:
            return None
        return o, int(self._ln[rid.cluster][rid.position])

    def lsn_of(self, rid: RID) -> int:
        lsn = self._lsn.get(rid.cluster)
        if lsn is None or not 0 <= rid.position < lsn.shape[0]:
            return 0
        return int(lsn[rid.position])

    def live(self) -> Iterator[RID]:
        for cid, off in self._off.items():
            for pos in np.nonzero(off >= 0)[0]:
                yield RID(cid, int(pos))

    def __len__(self) -> int:
        return self._count


class ColdRef:
    """Cluster-slot marker for an evicted record. Duck-typed by
    ``durability._rec_json`` via :meth:`rec_json`."""

    __slots__ = ("rid", "tier")

    def __init__(self, rid: RID, tier: "ColdTier") -> None:
        self.rid = rid
        self.tier = tier

    def rec_json(self, pos: int) -> Dict:
        r = self.tier.raw(self.rid)
        r["pos"] = pos
        return r

    def __repr__(self) -> str:
        return f"ColdRef({self.rid})"


class ColdTier:
    """The spill segment + offset index + LRU hot set for one database."""

    def __init__(
        self, db: Database, directory: str, budget_bytes: int
    ) -> None:
        os.makedirs(directory, exist_ok=True)
        self.db = db
        self.directory = directory
        self.path = os.path.join(directory, "cold-segment.jsonl")
        self._f = open(self.path, "a+b")
        self.budget = int(budget_bytes)
        self._index = _ColdIndex()
        #: rid → (doc, approx bytes); insertion order = LRU order
        self._hot: "OrderedDict[RID, Tuple[Document, int]]" = OrderedDict()
        self._hot_bytes = 0
        self._lock = threading.RLock()

    # -- spill segment ------------------------------------------------------

    def _cur_lsn(self) -> int:
        wal = self.db._wal
        return wal.next_lsn - 1 if wal is not None else 0

    def _append(self, rid: RID, rec: Dict, lsn: Optional[int] = None) -> int:
        # the line carries rid + lsn so a restart can rebuild the whole
        # index (and its WAL dedup floor) by one streaming scan
        rec = {
            "rid": str(rid),
            "lsn": self._cur_lsn() if lsn is None else lsn,
            **rec,
        }
        data = json.dumps(rec, separators=(",", ":")).encode() + b"\n"
        with self._lock:
            self._f.seek(0, os.SEEK_END)
            off = self._f.tell()
            self._f.write(data)
            self._f.flush()
            self._index.set(rid, off, len(data) - 1, rec["lsn"])
        return len(data)

    def raw(self, rid: RID) -> Dict:
        with self._lock:
            entry = self._index.get(rid)
            if entry is None:
                raise KeyError(str(rid))
            off, ln = entry
            self._f.seek(off)
            return json.loads(self._f.read(ln))

    # -- hot set ------------------------------------------------------------

    def on_save(self, doc: Document, lsn: Optional[int] = None) -> None:
        """Save-through: spill the committed state, keep the doc hot.
        ``lsn`` pins the stamped WAL position (replay passes the
        entry's own lsn — stamping the log tip would make later tail
        entries for the same record look superseded)."""
        nbytes = self._append(
            doc.rid, _rec_json(doc, doc.rid.position), lsn=lsn
        )
        self._admit(doc, nbytes)

    def on_delete(self, doc: Document) -> None:
        with self._lock:
            # a TOMBSTONE line makes the delete visible to the restart
            # scan; the old offset data stays (append-only segment) for
            # any checkpoint capture still holding a ColdRef — the
            # delete's WAL entry (higher LSN) removes it at replay,
            # exactly like a torn live capture.
            line = {
                "rid": str(doc.rid),
                "lsn": self._cur_lsn(),
                "deleted": True,
            }
            self._f.seek(0, os.SEEK_END)
            self._f.write(
                json.dumps(line, separators=(",", ":")).encode() + b"\n"
            )
            self._f.flush()
            entry = self._hot.pop(doc.rid, None)
            if entry is not None:
                self._hot_bytes -= entry[1]

    def _admit(self, doc: Document, nbytes: int) -> None:
        with self._lock:
            old = self._hot.pop(doc.rid, None)
            if old is not None:
                self._hot_bytes -= old[1]
            self._hot[doc.rid] = (doc, nbytes)
            self._hot_bytes += nbytes
            while self._hot_bytes > self.budget and len(self._hot) > 1:
                rid, (victim, vb) = self._hot.popitem(last=False)
                self._hot_bytes -= vb
                c = self.db._clusters.get(rid.cluster)
                if c is not None and c.get_slot(rid.position) is victim:
                    c.records[rid.position] = ColdRef(rid, self)
                    metrics.incr("coldstore.evict")

    # -- fault-in -----------------------------------------------------------

    def _build(self, rid: RID, r: Dict) -> Document:
        fields = {k: _dec(v) for k, v in r["fields"].items()}
        typ = r["type"]
        if typ == "vertex":
            doc: Document = Vertex(r["class"], fields)
            for dname, table in r.get("bags", {}).items():
                target = (
                    doc._out_edges if dname == "out" else doc._in_edges
                )
                for cls_name, rids in table.items():
                    target[cls_name] = [RID.parse(x) for x in rids]
        elif typ == "edge":
            doc = Edge(r["class"], fields)
            doc.out_rid = RID.parse(r["out"])
            doc.in_rid = RID.parse(r["in"])
        elif typ == "blob":
            doc = Blob.from_fields(fields)
        else:
            doc = Document(r["class"], fields)
        doc._db = self.db
        doc.rid = rid
        doc.version = r["version"]
        return doc

    def materialize(self, ref: ColdRef) -> Document:
        """Transient rebuild (scans): does NOT enter the hot set."""
        metrics.incr("coldstore.fault_transient")
        return self._build(ref.rid, self.raw(ref.rid))

    def fault(self, ref: ColdRef) -> Optional[Document]:
        """Point-read rebuild: re-admitted hot and placed in the slot.
        Returns None when the record was deleted since the marker was
        observed (the reader's race, same answer a pre-delete tombstone
        read would give)."""
        with self._lock:
            rid = ref.rid
            entry = self._index.get(rid)
            if entry is None:
                return None
            off, ln = entry
            doc = self._build(rid, self.raw(rid))
            c = self.db._clusters.get(rid.cluster)
            if c is not None and isinstance(
                c.get_slot(rid.position), ColdRef
            ):
                c.records[rid.position] = doc
            metrics.incr("coldstore.fault")
            self._admit(doc, ln)
            return doc

    def stats(self) -> Dict:
        with self._lock:
            return {
                "hot_records": len(self._hot),
                "hot_bytes": self._hot_bytes,
                "spilled_records": len(self._index),
                "segment_bytes": os.path.getsize(self.path),
                "budget_bytes": self.budget,
            }

    # -- restart support ----------------------------------------------------

    def write_meta(self) -> str:
        """Persist the SMALL restart metadata (schema/metadata payload +
        cluster lengths + the WAL lsn it reflects) — O(schema), never
        O(records). `open_database_cold` builds the schema from this and
        replays only WAL entries past it; checkpoint calls refresh it so
        the covered WAL range is never pruned out from under it."""
        from orientdb_tpu.storage.durability import (
            _meta_payload,
            atomic_write,
        )

        db = self.db
        with db._lock:
            payload = _meta_payload(db)
            payload["lsn"] = self._cur_lsn()
            payload["cluster_lens"] = {
                str(cid): len(c.records) for cid, c in db._clusters.items()
            }
        path = os.path.join(self.directory, META_FILE)
        atomic_write(
            path, json.dumps(payload, separators=(",", ":")).encode()
        )
        return path

    def scan_segment(self):
        """Stream (rid, lsn, off, ln, deleted, rec) for every segment
        line in append order — the restart path's single pass. A torn
        final line (crash mid-append) is skipped."""
        with open(self.path, "rb") as f:
            off = 0
            for line in f:
                ln = len(line)
                if not line.endswith(b"\n"):
                    break  # torn tail
                try:
                    rec = json.loads(line)
                    rid = RID.parse(rec["rid"])
                except Exception:
                    break  # torn/corrupt: stop at the last good line
                yield rid, int(rec.get("lsn", 0)), off, ln - 1, bool(
                    rec.get("deleted")
                ), rec
                off += ln

    def close(self) -> None:
        try:
            self.write_meta()
        except Exception:
            log.exception("cold meta write on close failed")
        self._f.close()


def enable_cold_tier(
    db: Database, directory: str, budget_bytes: int = 64 << 20
) -> ColdTier:
    """Arm the capacity tier on ``db``: committed saves spill through,
    the hot set is bounded by ``budget_bytes``, and cold records fault
    back on access. Existing records are adopted (spilled) lazily on
    their next save."""
    tier = ColdTier(db, directory, budget_bytes)
    db._cold_tier = tier
    for c in db._clusters.values():
        c.cold = tier
    db._on_new_cluster = lambda c: setattr(c, "cold", tier)
    return tier


def open_database_cold(
    directory: str,
    budget_bytes: int = 64 << 20,
    name: Optional[str] = None,
) -> Database:
    """Reopen a cold-tier database with **O(hot) record materialization**
    (VERDICT r4 #5 / missing #4: "a database larger than RAM must
    survive a restart" — the reference's plocal is restart-durable by
    construction, SURVEY.md:103-105).

    Recovery never builds the record set as Documents:

    1. schema/metadata come from the small ``cold-meta.json``
       (`ColdTier.write_meta` — refreshed by every checkpoint/close);
    2. ONE streaming scan of the spill segment rebuilds the compact
       offset index (latest line per RID wins; tombstones drop) and
       places a :class:`ColdRef` per live record — RAM is ~20 bytes per
       record plus nothing;
    3. property indexes rebuild from the same scan via TRANSIENT
       documents (never retained);
    4. the WAL tail replays only entries past the meta's lsn, skipping
       DML the segment already reflects (per-RID spilled lsn) — the
       replayed few admit hot through the re-armed tier.

    The returned database answers queries immediately; records fault in
    from the segment on access and the hot set stays under
    ``budget_bytes``."""
    from orientdb_tpu.storage.durability import (
        WAL_FILE,
        WriteAheadLog,
        _apply_entry,
        _sync_schema,
        wal_entries_above,
    )

    meta_path = os.path.join(directory, META_FILE)
    with open(meta_path, "rb") as f:
        meta = json.loads(f.read())
    db = Database(name or os.path.basename(os.path.abspath(directory)))
    db._durability_dir = directory
    _sync_schema(db, meta)
    meta_lsn = int(meta.get("lsn", 0))
    for cid_s, ln in meta.get("cluster_lens", {}).items():
        c = db._cluster(int(cid_s))
        while len(c.records) < ln:
            c.records.append(None)

    tier = ColdTier(db, directory, budget_bytes)
    # pass 1: latest line per RID wins — rebuild the compact index
    for rid, lsn, off, ln, deleted, _rec in tier.scan_segment():
        if deleted:
            tier._index.remove(rid, lsn)
        else:
            tier._index.set(rid, off, ln, lsn)
    # place markers + rebuild property-index CONTENTS from transient
    # docs (the definitions came back with _sync_schema)
    rebuild_indexes = db._indexes is not None and bool(meta.get("indexes"))
    for rid in tier._index.live():
        c = db._cluster(rid.cluster)
        while len(c.records) <= rid.position:
            c.records.append(None)
        ref = ColdRef(rid, tier)
        c.records[rid.position] = ref
        if rebuild_indexes:
            doc = tier.materialize(ref)  # transient: not retained
            db._indexes.on_save(doc)

    # WAL tail: entries past the meta, minus DML the segment already has
    wal = WriteAheadLog(os.path.join(directory, WAL_FILE))
    wal.truncate_torn_tail()
    entries = wal_entries_above(directory, meta_lsn)

    def replay(e: Dict) -> None:
        op = e.get("op")
        if op in ("tx", "bulk"):
            for sub in e["ops"]:
                sub = {**sub, "lsn": e["lsn"]}
                replay(sub)
            return
        if op in ("create", "update", "delete"):
            rid = RID.parse(e["rid"])
            # the segment's newest state for this rid — live line OR
            # tombstone — supersedes any WAL entry at or below its lsn
            # (a created-then-deleted record must not resurrect by
            # replaying only the create)
            if 0 < e["lsn"] <= tier._index.lsn_of(rid):
                return
        _apply_entry(db, e)
        if op in ("create", "update"):
            doc = db._load_raw(RID.parse(e["rid"]))
            if isinstance(doc, Document):
                # spill at the ENTRY's lsn: stamping the tip would make
                # later tail entries for this rid look superseded
                tier.on_save(doc, lsn=e["lsn"])

    wal.replaying = True
    db._wal = wal
    try:
        for e in entries:
            try:
                replay(e)
            except Exception:
                log.exception(
                    "cold replay failed at lsn=%s; stopping", e["lsn"]
                )
                break
    finally:
        wal.replaying = False
    # LSN continuity even when the tail was empty (checkpoint rotated
    # the log): restarting below meta_lsn would hand out LSNs the next
    # reopen's cutoff filter silently discards
    wal.next_lsn = max(
        wal.next_lsn,
        meta_lsn + 1,
        (entries[-1]["lsn"] + 1) if entries else 1,
    )

    db._cold_tier = tier
    for c in db._clusters.values():
        c.cold = tier
    db._on_new_cluster = lambda c: setattr(c, "cold", tier)
    db.schema.on_ddl = db._wal_log
    metrics.incr("coldstore.cold_reopen")
    return db
